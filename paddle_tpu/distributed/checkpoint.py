"""Distributed checkpointing with reshard-on-load.

ref: python/paddle/distributed/checkpoint/{save_state_dict.py:145,
load_state_dict.py,metadata.py} — sharded save with global metadata,
replica dedup, and automatic reshard when loading under a different
parallel configuration.

TPU-native collapse: DistTensor payloads are GLOBAL arrays, so the
reference's cross-rank dedup problem disappears — each tensor is saved
once in global form plus its (mesh, placements) metadata. Loading resheds
each value onto the TARGET state_dict's current mesh/placements (which
may differ entirely from the saved configuration), i.e. reshard-on-load.
Under multi-controller, saving goes through each host's addressable
shards of the same global arrays; format unchanged.

Checkpoint format v2 (docs/resilience.md): every save lands in a fresh
``ckpt-<n>/`` subdir via write-to-temp + fsync + atomic rename, with a
crc32 checksum per array recorded in the metadata; the ``latest``
pointer is updated only after the written files re-read and verify, and
``load_state_dict`` falls back to the previous verified checkpoint when
the newest is torn or corrupt. A top-level ``data.npz``/
``metadata.json`` compatibility view keeps pre-v2 readers working, and
pre-v2 checkpoint dirs (files directly under ``path``) still load.
"""
from __future__ import annotations

import json
import os
import shutil
import sys
import threading
import uuid
import zlib

import numpy as np

from ..core.tensor import Tensor
from ..observability import metrics as _obs_metrics
from ..resilience import faults
from .dist_tensor import shard_tensor, to_global_array
from .placement import Partial, Replicate, Shard

__all__ = [
    "save_state_dict", "load_state_dict", "load_full",
    "wait_async_save", "CheckpointCorruptError",
]

_META_FILE = "metadata.json"
_DATA_FILE = "data.npz"
_LATEST_FILE = "latest"
_CKPT_PREFIX = "ckpt-"
_FORMAT = 2

# serializes the publish step (dir-index allocation + latest update)
# across concurrent async writers
_publish_lock = threading.Lock()


class CheckpointCorruptError(RuntimeError):
    """No verifiable checkpoint could be loaded from the path."""


# always-on pipeline timings (docs/observability.md): checkpoint
# cadence is an SLO input — save time bounds how often you can
# checkpoint, verify time is the recovery critical path, and the
# fallback counter should be zero on a healthy fleet
_save_s = _obs_metrics.histogram(
    "paddle_tpu_checkpoint_save_seconds",
    "write+fsync+verify+publish wall clock per checkpoint save",
)
_verify_s = _obs_metrics.histogram(
    "paddle_tpu_checkpoint_verify_seconds",
    "end-to-end checksum verification per checkpoint dir",
)
_rotate_s = _obs_metrics.histogram(
    "paddle_tpu_checkpoint_rotate_seconds",
    "keep_last_k rotation wall clock per publish",
)
_fallbacks = _obs_metrics.counter(
    "paddle_tpu_checkpoint_load_fallbacks_total",
    "loads that skipped a corrupt newest checkpoint",
)


def _placement_to_json(p):
    if p.is_shard():
        return {"kind": "shard", "dim": p.get_dim()}
    if p.is_partial():
        return {"kind": "partial", "reduce_type": p.reduce_type}
    return {"kind": "replicate"}


def _placement_from_json(d):
    if d["kind"] == "shard":
        return Shard(d["dim"])
    if d["kind"] == "partial":
        return Partial(d["reduce_type"])
    return Replicate()


# in-flight async writers (ref save_state_dict.py:46 — async_save copies
# device tensors out synchronously, then a worker thread does the IO;
# wait_async_save() is the flush barrier)
_async_writers: list = []


def wait_async_save():
    """Block until every pending async checkpoint write has finished,
    re-raising the first writer failure."""
    import threading  # noqa: F401  (documents the contract)

    while _async_writers:
        t, err = _async_writers.pop(0)
        t.join()
        if err:
            raise err[0]


def _crc(arr):
    # crc straight off the array's buffer — no tobytes() copy
    return zlib.crc32(np.ascontiguousarray(arr).data) & 0xFFFFFFFF


def _fsync_file(p):
    with open(p, "rb") as f:
        os.fsync(f.fileno())


def _fsync_dir(p):
    fd = os.open(p, os.O_RDONLY)
    try:
        os.fsync(fd)
    except OSError:
        pass  # some filesystems refuse dir fsync; rename is still atomic
    finally:
        os.close(fd)


def _ckpt_names(path):
    """Versioned checkpoint dirs under ``path``, newest first."""
    try:
        names = [
            n for n in os.listdir(path)
            if n.startswith(_CKPT_PREFIX)
            and n[len(_CKPT_PREFIX):].isdigit()
            and os.path.isdir(os.path.join(path, n))
        ]
    except OSError:
        return []
    return sorted(names, key=lambda n: int(n[len(_CKPT_PREFIX):]),
                  reverse=True)


def _verify_dir(d):
    """Verify one checkpoint dir end to end (json parses, npz opens,
    every checksummed array matches) and return the metadata payload.
    Arrays are verified ONE AT A TIME and dropped — a model-scale
    checkpoint is never fully resident during verification. Raises
    CheckpointCorruptError on any damage so callers can fall back to an
    older checkpoint."""
    import time as _time

    t0 = _time.perf_counter()
    try:
        return _verify_dir_inner(d)
    finally:
        _verify_s.observe(_time.perf_counter() - t0)


def _verify_dir_inner(d):
    try:
        with open(os.path.join(d, _META_FILE)) as f:
            payload = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointCorruptError(
            f"{d}: unreadable metadata ({e})"
        ) from e
    sums = payload.get("checksums")
    try:
        with np.load(os.path.join(d, _DATA_FILE),
                     allow_pickle=False) as data:
            files = set(data.files)
            if sums is not None:
                for key, want in sums.items():
                    if key not in files:
                        raise CheckpointCorruptError(
                            f"{d}: array {key!r} missing from data file"
                        )
                    if _crc(data[key]) != want:
                        raise CheckpointCorruptError(
                            f"{d}: checksum mismatch for {key!r}"
                        )
    except CheckpointCorruptError:
        raise
    except Exception as e:  # BadZipFile / OSError / ValueError / ...
        raise CheckpointCorruptError(f"{d}: unreadable data ({e})") from e
    return payload


class _FileLock:
    """fcntl advisory lock serializing publishers ACROSS processes
    (multi-controller hosts share the checkpoint path); the in-process
    _publish_lock alone cannot order a read-compare-write of ``latest``
    between processes."""

    def __init__(self, path):
        self._path = path
        self._fd = None

    def __enter__(self):
        import fcntl

        self._fd = os.open(self._path, os.O_CREAT | os.O_RDWR)
        try:
            fcntl.flock(self._fd, fcntl.LOCK_EX)
        except OSError:
            pass  # fs without flock: in-process lock still applies
        return self

    def __exit__(self, *exc):
        import fcntl

        try:
            fcntl.flock(self._fd, fcntl.LOCK_UN)
        except OSError:
            pass
        os.close(self._fd)
        return False


def _publish(path, tmp, keep_last_k):
    """Atomically promote a verified tmp dir: rename to the next
    ``ckpt-<n>``, update ``latest``, refresh the v1 compatibility view,
    rotate old checkpoints. Crash-safe at every boundary — until the
    ``latest`` replace lands, loads keep resolving the previous
    checkpoint."""
    with _publish_lock, _FileLock(os.path.join(path, ".publish.lock")):
        # index allocation races with OTHER processes saving to the same
        # path (multi-controller hosts share it): the rename is the
        # atomic claim, so on collision re-list and take the next index
        for _ in range(64):
            names = _ckpt_names(path)
            n = 1 + (int(names[0][len(_CKPT_PREFIX):]) if names else 0)
            name = f"{_CKPT_PREFIX}{n:08d}"
            final = os.path.join(path, name)
            try:
                os.rename(tmp, final)
                break
            except OSError:
                if not os.path.isdir(final):
                    raise  # not an index collision — surface it
        else:
            raise OSError(
                f"could not claim a checkpoint index under {path}"
            )
        _fsync_dir(path)
        # the latest pointer flips only now, after verification — and
        # only FORWARD: a slow writer in another process must not move
        # it back onto an older checkpoint
        cur = 0
        try:
            with open(os.path.join(path, _LATEST_FILE)) as f:
                c = f.read().strip()
            if c.startswith(_CKPT_PREFIX) and c[len(_CKPT_PREFIX):].isdigit():
                cur = int(c[len(_CKPT_PREFIX):])
        except OSError:
            pass
        if n > cur:
            ltmp = os.path.join(path, f".latest-{uuid.uuid4().hex[:8]}")
            with open(ltmp, "w") as f:
                f.write(name)
                f.flush()
                os.fsync(f.fileno())
            os.replace(ltmp, os.path.join(path, _LATEST_FILE))
            # v1 compatibility view: top-level data.npz/metadata.json
            # track the newest checkpoint. COPIED, not hardlinked — a
            # pre-v2 writer rewriting the top-level files in place
            # (O_TRUNC) must not destroy the versioned data through a
            # shared inode during a mixed-version rollout
            for fname in (_DATA_FILE, _META_FILE):
                vtmp = os.path.join(path, f".view-{uuid.uuid4().hex[:8]}")
                shutil.copy2(os.path.join(final, fname), vtmp)
                _fsync_file(vtmp)  # torn view files defeat its purpose
                os.replace(vtmp, os.path.join(path, fname))
            _fsync_dir(path)
        if keep_last_k:
            import time as _time

            t0 = _time.perf_counter()
            for old in _ckpt_names(path)[keep_last_k:]:
                if old != name:
                    shutil.rmtree(
                        os.path.join(path, old), ignore_errors=True
                    )
            _rotate_s.observe(_time.perf_counter() - t0)


def save_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, async_save=False,
                    keep_last_k=None):
    """Write each tensor once (global value) + dist metadata
    (ref save_state_dict.py:145). With async_save=True the device->host
    snapshot happens NOW (so training may donate/overwrite buffers
    immediately) and the file IO runs on a background thread; call
    wait_async_save() as the flush barrier before relying on the files.

    Format v2: the save is atomic (temp dir + fsync + rename) and
    verified (per-array crc32 re-read) before the ``latest`` pointer
    moves; ``keep_last_k`` bounds how many verified checkpoints are
    retained (None keeps all)."""
    if keep_last_k is not None and keep_last_k < 1:
        raise ValueError(
            f"keep_last_k must be >= 1 or None (keep all), got "
            f"{keep_last_k}"
        )
    os.makedirs(path, exist_ok=True)
    meta = {"tensors": {}}
    arrays = {}
    for key, value in state_dict.items():
        if isinstance(value, Tensor):
            if value._dist_meta is not None:
                arr = np.asarray(to_global_array(value))
                m = value._dist_meta
                meta["tensors"][key] = {
                    "dtype": str(arr.dtype),
                    "shape": list(arr.shape),
                    "mesh_shape": m.mesh.shape,
                    "mesh_dim_names": m.mesh.dim_names,
                    "placements": [
                        _placement_to_json(p) for p in m.placements
                    ],
                }
            else:
                arr = np.asarray(value._data)
                meta["tensors"][key] = {
                    "dtype": str(arr.dtype),
                    "shape": list(arr.shape),
                }
            if arr.dtype.name == "bfloat16":
                # npz cannot hold bf16; stored widened, dtype key restores
                meta["tensors"][key]["dtype"] = "bfloat16"
                arr = arr.astype(np.float32)
            arrays[key] = arr
        elif isinstance(value, np.ndarray):
            meta["tensors"][key] = {
                "dtype": str(value.dtype), "shape": list(value.shape),
            }
            arrays[key] = value
        else:
            meta["tensors"][key] = {"python": True}
            arrays[key] = value

    if async_save:
        # snapshot BEFORE the background writer starts: Tensor values were
        # already copied out via np.asarray, but raw ndarrays and python
        # containers were held by reference, racing user mutation against
        # the writer thread
        import copy as _copy

        arrays = {
            k: (v.copy() if isinstance(v, np.ndarray) else _copy.deepcopy(v))
            for k, v in arrays.items()
        }

    pyvals = {
        k: v for k, v in arrays.items() if not isinstance(v, np.ndarray)
    }
    def _json_default(v):
        # numpy scalars degrade losslessly; anything else is an error —
        # silent str() corruption is worse than failing the save
        if isinstance(v, np.integer):
            return int(v)
        if isinstance(v, (np.floating, np.bool_)):
            return v.item()
        raise TypeError(
            f"state_dict value of type {type(v).__name__} is not "
            "checkpointable; convert it to a Tensor, ndarray, or plain "
            "python value"
        )

    ndarrays = {
        k: v for k, v in arrays.items() if isinstance(v, np.ndarray)
    }

    def _write():
        import time as _time

        t0 = _time.perf_counter()
        # checksums computed HERE so async_save's foreground cost stays
        # the snapshot copy alone (the crc pass rides the writer thread)
        checksums = {k: _crc(v) for k, v in ndarrays.items()}
        tmp = os.path.join(path, f".tmp-{uuid.uuid4().hex[:8]}")
        os.makedirs(tmp)
        try:
            faults.fire("ckpt.write", file=_DATA_FILE, path=path)
            np.savez(os.path.join(tmp, _DATA_FILE), **ndarrays)
            faults.fire("ckpt.write", file=_META_FILE, path=path)
            with open(os.path.join(tmp, _META_FILE), "w") as f:
                json.dump(
                    {"meta": meta, "python_values": pyvals,
                     "format": _FORMAT, "checksums": checksums}, f,
                    default=_json_default,
                )
                f.flush()
                os.fsync(f.fileno())
            _fsync_file(os.path.join(tmp, _DATA_FILE))
            _fsync_dir(tmp)
            # verify the bytes that actually hit disk BEFORE publishing:
            # a torn/corrupt write must never become the latest pointer
            _verify_dir(tmp)
            _publish(path, tmp, keep_last_k)
            _save_s.observe(_time.perf_counter() - t0)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise

    if not async_save:
        _write()
        return

    import threading

    err: list = []

    def _guarded():
        try:
            _write()
        except Exception as e:  # surfaced at wait_async_save()
            err.append(e)

    t = threading.Thread(target=_guarded, daemon=False)
    t.start()
    _async_writers.append((t, err))


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0):
    """Fill `state_dict`'s tensors in place, resharding each saved global
    value onto the TARGET tensor's current mesh/placements (ref
    load_state_dict.py + auto_parallel converter semantics).

    The target parallel configuration may differ arbitrarily from the one
    the checkpoint was saved under.

    Recovery semantics (format v2): the ``latest`` pointer is resolved
    first; if that checkpoint is torn or corrupt (checksum mismatch,
    unreadable file), older verified checkpoints are tried newest-first
    before giving up with CheckpointCorruptError. The state_dict is
    only mutated after a checkpoint fully verifies (verification
    streams the arrays, so the checkpoint is never resident twice)."""
    payload, ckpt_dir = _read_checkpoint(path)
    meta = payload["meta"]["tensors"]
    # lazy handle: arrays decompress one at a time during the copy loop
    data = np.load(os.path.join(ckpt_dir, _DATA_FILE),
                   allow_pickle=False)

    missing, unexpected = [], []
    for key, target in state_dict.items():
        if key not in meta:
            missing.append(key)
            continue
        info = meta[key]
        if info.get("python"):
            state_dict[key] = payload["python_values"].get(key)
            continue
        arr = _decode_array(info, data, key)
        if not isinstance(target, Tensor):
            state_dict[key] = Tensor(arr)
            continue
        if list(arr.shape) != list(target.shape):
            raise ValueError(
                f"shape mismatch for {key}: ckpt {list(arr.shape)} vs "
                f"target {list(target.shape)}"
            )
        src = Tensor(arr)
        if target._dist_meta is not None:
            # reshard-on-load: lay the value out like the target, in the
            # target's dtype
            m = target._dist_meta
            src = Tensor(src._data.astype(target._data.dtype))
            d = shard_tensor(
                src, m.mesh,
                [Replicate() if p.is_partial() else p for p in m.placements],
            )
            target._rebind(d._data, dist_meta=d._dist_meta)
        else:
            target._rebind(src._data.astype(target._data.dtype))
    for key in meta:
        if key not in state_dict:
            unexpected.append(key)
    return missing, unexpected


def _decode_array(info, data, key):
    """One saved array entry -> ndarray (bf16 re-widened) — the single
    decode point shared by templated and template-free loads, so the
    on-disk encoding can only ever change in lockstep."""
    arr = data[key]
    if info.get("dtype") == "bfloat16":
        import jax.numpy as jnp

        arr = jnp.asarray(arr).astype(jnp.bfloat16)
    return arr


def load_full(path):
    """Load EVERY entry of the newest verified checkpoint under
    ``path`` without a target template — arrays come back as plain
    Tensors, python values as-is. The training resume path
    (``resilience.TrainState.load``) needs this: a resuming process
    cannot know ahead of time which keys (e.g. mid-accumulation
    ``grad.*`` buffers) the dying incarnation captured. Same fallback
    semantics as :func:`load_state_dict`."""
    payload, ckpt_dir = _read_checkpoint(path)
    data = np.load(os.path.join(ckpt_dir, _DATA_FILE),
                   allow_pickle=False)
    sd = {}
    for key, info in payload["meta"]["tensors"].items():
        if info.get("python"):
            sd[key] = payload["python_values"].get(key)
        else:
            sd[key] = Tensor(_decode_array(info, data, key))
    return sd


def _read_checkpoint(path):
    """Resolve + verify a checkpoint under ``path``: the v2 ``latest``
    chain with fallback, or the legacy v1 top-level files. Returns
    (metadata payload, directory holding the verified data file)."""
    candidates = _ckpt_names(path)
    latest = None
    try:
        with open(os.path.join(path, _LATEST_FILE)) as f:
            latest = f.read().strip()
    except OSError:
        pass
    if latest and latest in candidates:
        candidates.remove(latest)
        candidates.insert(0, latest)
    if not candidates:
        # legacy (pre-v2) layout: files directly under path. A missing
        # checkpoint keeps raising FileNotFoundError (the long-standing
        # "no checkpoint yet" probe), not CheckpointCorruptError.
        if not os.path.exists(os.path.join(path, _META_FILE)):
            raise FileNotFoundError(f"no checkpoint found under {path}")
        return _verify_dir(path), path
    errors = []
    for name in candidates:
        d = os.path.join(path, name)
        try:
            payload = _verify_dir(d)
        except CheckpointCorruptError as e:
            errors.append(str(e))
            continue
        if errors:
            _fallbacks.inc()
            from ..observability import flight

            flight.record(
                "checkpoint", "fallback", loaded=name,
                skipped="; ".join(errors),
            )
            sys.stderr.write(
                "[checkpoint] fell back to %s after: %s\n"
                % (name, "; ".join(errors))
            )
        return payload, d
    raise CheckpointCorruptError(
        f"no verifiable checkpoint under {path}: " + "; ".join(errors)
    )
