"""Eager collective API + process groups.

ref: python/paddle/distributed/communication/{all_reduce,all_gather,
all_to_all,broadcast,reduce_scatter,scatter,reduce,group}.py and the
ProcessGroup stack (phi/core/distributed/collective/process_group.h:48,
fluid/distributed/collective/process_group_nccl.h:37).

TPU-native model (SURVEY §2.6 "TPU equivalent" row): there are no per-rank
processes issuing NCCL calls — collectives are array operations on global
arrays whose rank axis is the leading dimension, stacked over a Group's
1-d mesh. Each function takes/returns the stacked form (`x[rank, ...]`):
what rank r "holds" is `x[r]`. The ops run through the normal dispatcher,
so they are differentiable and GSPMD lowers them to real ICI collectives
when the rank axis is device-sharded. Under multi-controller
(jax.distributed) the same global-array code spans hosts.

The reference's stream/`sync_op` knobs collapse: XLA schedules collectives
(no user-visible comm streams); `sync_op=False` returns immediately anyway
because jax dispatch is async.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from .dist_tensor import dtensor_from_local, shard_tensor
from .placement import Replicate, Shard
from .process_mesh import ProcessMesh

__all__ = [
    "Group", "new_group", "get_group", "destroy_process_group",
    "all_reduce", "all_gather", "all_to_all", "broadcast", "reduce",
    "reduce_scatter", "scatter", "barrier", "ReduceOp",
]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    AVG = "avg"
    PROD = "prod"


class Group:
    """A collective group = an ordered list of global ranks backed by a
    1-d mesh over those devices (ref communication/group.py)."""

    # id 0 is reserved for the world group (the reference's global group)
    _next_id = 1

    def __init__(self, ranks, name=None, _id=None):
        self.ranks = list(ranks)
        self.nranks = len(self.ranks)
        if _id is None:
            self.id = Group._next_id
            Group._next_id += 1
        else:
            self.id = _id
        self.name = name or f"group_{self.id}"
        self.process_mesh = ProcessMesh(self.ranks, ["rank"])

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    def __repr__(self):
        return f"Group(id={self.id}, ranks={self.ranks})"


_default_group = None
_groups = {}


def _world():
    import jax

    global _default_group
    if _default_group is None:
        _default_group = Group(list(range(len(jax.devices()))), "default", _id=0)
        _groups[_default_group.id] = _default_group
    return _default_group


def new_group(ranks=None, backend=None, timeout=None):
    g = Group(ranks if ranks is not None else _world().ranks)
    _groups[g.id] = g
    return g


def get_group(gid=0):
    return _groups.get(gid, _world())


def destroy_process_group(group=None):
    global _default_group
    if group is None:
        _groups.clear()
        _default_group = None
    else:
        _groups.pop(group.id, None)


def _watched(fn):
    """Run a collective under the comm watchdog when one is enabled
    (ref comm_task_manager.h:37 — every NCCL task is watchdog-tracked)."""
    import functools

    @functools.wraps(fn)
    def wrap(*a, **kw):
        from ..resilience import faults
        from .watchdog import get_comm_watchdog

        faults.fire("collective", op=fn.__name__)
        wd = get_comm_watchdog()
        if wd is None:
            return fn(*a, **kw)
        with wd.watch(fn.__name__):
            return fn(*a, **kw)

    return wrap


def _member_rank(g, rank, what):
    r = g.get_group_rank(rank)
    if r < 0:
        raise ValueError(
            f"{what} rank {rank} is not a member of {g!r}"
        )
    return r


def _stacked(x, group):
    """Coerce input to the stacked [nranks, ...] DistTensor over the
    group's rank mesh."""
    g = group or _world()
    if not isinstance(x, Tensor):
        x = Tensor(x)
    if x._dist_meta is None:
        if x.shape[0] != g.nranks:
            raise ValueError(
                f"stacked collective input needs leading dim {g.nranks}, "
                f"got shape {x.shape} (wrap per-rank values with "
                "dtensor_from_local or stack them)"
            )
        x = shard_tensor(x, g.process_mesh, [Shard(0)])
    return x, g


@_watched
def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """Every rank ends with the elementwise reduction (ref
    communication/all_reduce.py). Stacked form: out[r] = reduce_r' x[r']."""
    from .. import ops as F

    x, g = _stacked(tensor, group)
    fns = {"sum": F.sum, "avg": F.mean, "max": F.max, "min": F.min,
           "prod": F.prod}
    red = fns[op](x, axis=0, keepdim=True)
    out = F.tile(red, [g.nranks] + [1] * (x.ndim - 1))
    if isinstance(tensor, Tensor):
        tensor._rebind(out._data, dist_meta=out._dist_meta)
        tensor._grad_node = out._grad_node
        tensor._out_index = out._out_index
        tensor.stop_gradient = out.stop_gradient
    return out


@_watched
def all_gather(tensor_or_list, tensor=None, group=None, sync_op=True):
    """out[r] = concat(x[0], ..., x[n-1]) for every r (ref
    communication/all_gather.py). Returns the stacked gathered tensor;
    when called with (tensor_list, tensor) fills the list with per-rank
    views for API parity."""
    from .. import ops as F

    if tensor is None:
        x, g = _stacked(tensor_or_list, group)
        gathered = F.reshape(x, [1, g.nranks] + list(x.shape[1:]))
        return F.tile(gathered, [g.nranks] + [1] * (x.ndim))
    out_list, (x, g) = tensor_or_list, _stacked(tensor, group)
    for r in range(g.nranks):
        out_list.append(F.getitem(x, (r,)))
    return out_list


@_watched
def all_to_all(out_tensor_list, in_tensor_list=None, group=None,
               sync_op=True):
    """out[r][j] = in[j][r] (ref communication/all_to_all.py). Stacked
    form: x[r, j, ...] -> y[r, j, ...] = x[j, r, ...]."""
    from .. import ops as F

    if in_tensor_list is None:
        x, g = _stacked(out_tensor_list, group)
        if x.shape[1] != g.nranks:
            raise ValueError(
                f"stacked all_to_all needs shape [n, n, ...]; got {x.shape}"
            )
        return F.transpose(
            x, [1, 0] + list(range(2, x.ndim))
        )
    # list API: in_tensor_list has nranks entries per rank — single-
    # controller stacked emulation
    g = group or _world()
    stacked = F.stack(in_tensor_list, axis=0)
    out = F.transpose(stacked, [1, 0] + list(range(2, stacked.ndim)))
    for r in range(g.nranks):
        out_tensor_list.append(F.getitem(out, (r,)))
    return out_tensor_list


@_watched
def broadcast(tensor, src=0, group=None, sync_op=True):
    """out[r] = x[src_group_rank] (ref communication/broadcast.py)."""
    from .. import ops as F

    x, g = _stacked(tensor, group)
    src_rank = _member_rank(g, src, "src")
    piece = F.getitem(x, (slice(src_rank, src_rank + 1),))
    out = F.tile(piece, [g.nranks] + [1] * (x.ndim - 1))
    if isinstance(tensor, Tensor):
        tensor._rebind(out._data, dist_meta=out._dist_meta)
        tensor._grad_node = out._grad_node
        tensor._out_index = out._out_index
        tensor.stop_gradient = out.stop_gradient
    return out


@_watched
def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    """Only dst ends with the reduction; others keep their input (ref
    communication/reduce.py)."""
    from .. import ops as F

    x, g = _stacked(tensor, group)
    fns = {"sum": F.sum, "avg": F.mean, "max": F.max, "min": F.min,
           "prod": F.prod}
    red = fns[op](x, axis=0, keepdim=True)
    dst_rank = _member_rank(g, dst, "dst")
    mask_np = np.zeros((g.nranks,) + (1,) * (x.ndim - 1), np.float32)
    mask_np[dst_rank] = 1.0
    mask = F.cast(Tensor(mask_np), x.dtype.name)
    out = x * (1 - mask) + F.tile(red, [g.nranks] + [1] * (x.ndim - 1)) * mask
    if isinstance(tensor, Tensor):
        tensor._rebind(out._data, dist_meta=out._dist_meta)
    return out


@_watched
def reduce_scatter(tensor, tensor_list=None, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    """Rank r gets the r-th chunk of the reduction (ref
    communication/reduce_scatter.py). Stacked x[r, ...] with first tensor
    dim divisible by nranks -> out[r] = chunk_r(reduce(x)). With the list
    API (tensor=receive buffer, tensor_list=inputs), the inputs are
    stacked and the result written into the buffer."""
    from .. import ops as F

    if tensor_list is not None:
        x, g = _stacked(F.stack(list(tensor_list), axis=0), group)
    else:
        x, g = _stacked(tensor, group)
    fns = {"sum": F.sum, "avg": F.mean, "max": F.max, "min": F.min,
           "prod": F.prod}
    red = fns[op](x, axis=0)  # [chunkdim, ...]
    out = F.reshape(
        red, [g.nranks, red.shape[0] // g.nranks] + list(red.shape[1:])
    )
    if isinstance(tensor, Tensor):
        tensor._rebind(out._data, dist_meta=out._dist_meta)
    return out


@_watched
def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    """Rank r gets chunk r of src's value (ref communication/scatter.py).
    List API: tensor_list holds src's per-rank chunks."""
    from .. import ops as F

    if tensor_list is not None:
        g = group or _world()
        out = F.stack(list(tensor_list), axis=0)
        if isinstance(tensor, Tensor):
            tensor._rebind(out._data, dist_meta=out._dist_meta)
        return out
    x, g = _stacked(tensor, group)
    src_rank = _member_rank(g, src, "src")
    piece = F.getitem(x, (src_rank,))
    out = F.reshape(
        piece, [g.nranks, piece.shape[0] // g.nranks] + list(piece.shape[1:])
    )
    if isinstance(tensor, Tensor):
        tensor._rebind(out._data, dist_meta=out._dist_meta)
    return out


@_watched
def barrier(group=None):
    """Device sync (XLA has no cross-op barrier need; block on a token)."""
    import jax

    jax.block_until_ready(jax.numpy.zeros(()))
