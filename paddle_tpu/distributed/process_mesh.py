"""ProcessMesh — N-d mesh of devices with named axes.

ref: paddle/phi/core/distributed/auto_parallel/process_mesh.h:34 and
python/paddle/distributed/auto_parallel/process_mesh.py. TPU-first: lowers
to jax.sharding.Mesh; process ids index jax.devices() so the same mesh
works on the forced-8-device CPU platform, one real chip, or a multi-host
slice (where jax.devices() spans hosts over ICI/DCN).
"""
from __future__ import annotations

import numpy as np

__all__ = ["ProcessMesh"]


class ProcessMesh:
    def __init__(self, mesh, dim_names=None, process_ids=None):
        arr = np.asarray(mesh, dtype=np.int64)
        if arr.ndim == 0:
            arr = arr.reshape(1)
        self._shape = list(arr.shape)
        self._process_ids = arr.reshape(-1).tolist()
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        if len(dim_names) != arr.ndim:
            raise ValueError(
                f"dim_names {dim_names} rank != mesh rank {arr.ndim}"
            )
        self._dim_names = list(dim_names)
        self._jax_mesh = None

    @property
    def shape(self):
        return list(self._shape)

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def process_ids(self):
        return list(self._process_ids)

    @property
    def dim_names(self):
        return list(self._dim_names)

    @property
    def size(self):
        return len(self._process_ids)

    def get_dim_size(self, name_or_idx):
        if isinstance(name_or_idx, str):
            return self._shape[self._dim_names.index(name_or_idx)]
        return self._shape[name_or_idx]

    def get_mesh_with_dim(self, dim_name):
        """Reorder so dim_name is first (ref process_mesh.py)."""
        idx = self._dim_names.index(dim_name)
        arr = np.asarray(self._process_ids).reshape(self._shape)
        order = [idx] + [i for i in range(self.ndim) if i != idx]
        names = [self._dim_names[i] for i in order]
        return ProcessMesh(arr.transpose(order), names)

    def jax_mesh(self):
        """Lower to jax.sharding.Mesh (cached)."""
        if self._jax_mesh is None:
            import jax
            from jax.sharding import Mesh

            all_devs = {d.id: d for d in jax.devices()}
            try:
                devs = np.array(
                    [all_devs[i] for i in self._process_ids], dtype=object
                ).reshape(self._shape)
            except KeyError as e:
                raise RuntimeError(
                    f"mesh references device id {e} but only "
                    f"{len(all_devs)} devices exist"
                ) from None
            self._jax_mesh = Mesh(devs, tuple(self._dim_names))
        return self._jax_mesh

    def __eq__(self, other):
        return (
            isinstance(other, ProcessMesh)
            and self._shape == other._shape
            and self._process_ids == other._process_ids
            and self._dim_names == other._dim_names
        )

    def __hash__(self):
        return hash(
            (tuple(self._shape), tuple(self._process_ids),
             tuple(self._dim_names))
        )

    def __repr__(self):
        return (
            f"ProcessMesh(shape={self._shape}, dim_names={self._dim_names})"
        )
