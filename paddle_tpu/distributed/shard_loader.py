"""shard_dataloader: pre-sharded batches on a mesh axis.

ref: python/paddle/distributed/auto_parallel/api.py:3301
(shard_dataloader / ShardDataloader — split the loader along a mesh dim
for data parallelism and emit DistTensors placed on the mesh).

TPU-native form: batches stay GLOBAL arrays; each yielded tensor is
placed with dist.shard_tensor([Shard(0) on the named axis]) so GSPMD
sees the dp split — under multi-controller each host only materializes
its addressable shard. ``shard_dims=None`` keeps batches replicated
(mp-style inputs), matching the reference default.
"""
from __future__ import annotations

from ..core.tensor import Tensor
from .dist_tensor import shard_tensor
from .placement import Replicate, Shard
from .process_mesh import ProcessMesh

__all__ = ["ShardDataloader", "shard_dataloader"]


def _axis_index(mesh: ProcessMesh, dim):
    if dim is None:
        return None
    if isinstance(dim, str):
        if dim not in mesh.dim_names:
            raise ValueError(
                f"shard_dim {dim!r} not in mesh axes {mesh.dim_names}"
            )
        return mesh.dim_names.index(dim)
    return int(dim)


class ShardDataloader:
    """Iterates the wrapped loader, placing every yielded Tensor on its
    mesh: batch axis 0 sharded over the chosen mesh dim (dp), remaining
    axes replicated. len() follows the inner loader."""

    def __init__(self, dataloader, meshes, input_keys=None,
                 shard_dims=None, is_dataset_splitted=False,
                 retry_policy=None):
        self._loader = dataloader
        # per-leaf placement retry under the unified policy. NOTE: the
        # policy's retry_on decides what counts as transient — jax
        # backend failures surface as jaxlib XlaRuntimeError (a
        # RuntimeError), so cover them explicitly, e.g.
        # RetryPolicy(retry_on=(RuntimeError, OSError)); the default
        # retry_on (connection/timeout/OS errors) will NOT retry them
        self._retry = retry_policy
        self._meshes = (
            list(meshes) if isinstance(meshes, (list, tuple)) else [meshes]
        )
        self._input_keys = list(input_keys) if input_keys else None
        if isinstance(shard_dims, (list, tuple)):
            dims = list(shard_dims)
        else:
            dims = [shard_dims] * len(self._meshes)
        if len(dims) != len(self._meshes):
            raise ValueError(
                f"{len(dims)} shard_dims for {len(self._meshes)} meshes"
            )
        self._shard_dims = dims
        # is_dataset_splitted means the user already split the dataset
        # per rank; placement is identical either way here because the
        # yielded value is the GLOBAL batch in the SPMD model.
        self._is_dataset_splitted = bool(is_dataset_splitted)

    def __len__(self):
        return len(self._loader)

    def _mesh_for(self, i):
        # batches may carry more elements than meshes (sample ids,
        # masks, ...): extras follow the LAST mesh, mirroring the
        # reference's "all inputs on one mesh" default
        i = min(i, len(self._meshes) - 1)
        return self._meshes[i], self._shard_dims[i]

    def _place(self, value, i):
        # containers recurse WITHOUT the retry wrapper: only the leaf
        # placement is retried, so attempts don't multiply with nesting
        # depth and healthy siblings are never re-placed
        if isinstance(value, (list, tuple)):
            return type(value)(self._place(v, i) for v in value)
        if self._retry is not None:
            return self._retry.call(self._place_once, value, i)
        return self._place_once(value, i)

    def _place_once(self, value, i):
        mesh, dim = self._mesh_for(i)
        if not isinstance(value, Tensor):
            return value
        if value.is_dist():
            return value
        axis = _axis_index(mesh, dim)
        placements = [Replicate()] * mesh.ndim
        if axis is not None and value._data.ndim > 0:
            size = mesh.shape[axis]
            if value._data.shape[0] % size == 0:
                placements[axis] = Shard(0)
        return shard_tensor(
            value, mesh, placements, stop_gradient=value.stop_gradient
        )

    def __iter__(self):
        for batch in self._loader:
            if isinstance(batch, dict):
                keys = self._input_keys or list(batch.keys())
                out = dict(batch)  # input_keys selects what to PLACE,
                for i, k in enumerate(keys):  # never filters the batch
                    out[k] = self._place(batch[k], i)
                yield out
            elif isinstance(batch, (list, tuple)):
                yield type(batch)(
                    self._place(v, i) for i, v in enumerate(batch)
                )
            else:
                yield self._place(batch, 0)


def shard_dataloader(dataloader, meshes, input_keys=None,
                     shard_dims=None, is_dataset_splitted=False,
                     retry_policy=None):
    """ref api.py:3301 — see ShardDataloader."""
    return ShardDataloader(
        dataloader, meshes, input_keys=input_keys, shard_dims=shard_dims,
        is_dataset_splitted=is_dataset_splitted,
        retry_policy=retry_policy,
    )
