"""Communication watchdog: hung-collective detection + propagated abort.

ref: phi/core/distributed/comm_task_manager.h:37 (CommTaskManager — a
background loop that watches enqueued NCCL tasks, times out hung ones,
dumps debug state, and propagates the abort to peer ranks through the
TCPStore) and nccl_comm_task.cc.

TPU-native form: XLA collectives are compiled into programs, so the
watchable unit is a host-side span (a collective call, a whole train
step, a checkpoint barrier). ``watch(tag)`` registers a deadline with
the background thread; on expiry the watchdog dumps every Python
thread's stack, writes the abort key into the TCPStore (peers polling
the same watchdog see it and raise instead of waiting out their own
timeouts), and interrupts the main thread.

    wd = enable_comm_watchdog(timeout=300, store=tcp_store)
    with wd.watch("all_reduce"):          # or automatic via collectives
        dist.all_reduce(x)
"""
from __future__ import annotations

import sys
import threading
import time
import traceback

__all__ = [
    "CommWatchdog", "enable_comm_watchdog", "disable_comm_watchdog",
    "get_comm_watchdog", "CommTimeoutError",
]

ABORT_KEY = "__comm_abort__"


class CommTimeoutError(RuntimeError):
    pass


class CommWatchdog:
    def __init__(self, timeout=1800.0, store=None, rank=0,
                 poll_interval=1.0, on_timeout=None):
        self.timeout = float(timeout)
        self.store = store
        self.rank = rank
        self._poll = poll_interval
        self._on_timeout = on_timeout
        self._active = {}      # id -> (tag, deadline)
        self._next = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.fired = None      # (tag, why) after a trip
        self._seen_abort = None  # last ABORT_KEY value acted on
        self._probes = {}      # name -> (probe fn, owner weakref|None)
        if store is not None:
            try:  # a fresh watchdog must not trip on a PREVIOUS abort
                store.delete_key(ABORT_KEY)
            except Exception:
                # analysis: allow(broad-except) best-effort cleanup on a
                # user-supplied store: any failure here must not block
                # watchdog construction
                pass
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    # -- registration ------------------------------------------------------
    class _Scope:
        def __init__(self, wd, tag, timeout):
            self._wd = wd
            self._tag = tag
            self._timeout = timeout
            self._id = None

        def __enter__(self):
            self._id = self._wd._register(self._tag, self._timeout)
            return self

        def __exit__(self, *exc):
            # _clear returns the trip observed ATOMICALLY with the
            # deregistration: another thread's _register may re-arm
            # (fired=None) the instant our registration leaves _active
            fired = self._wd._clear(self._id)
            if exc[0] is None and fired is not None:
                tag, why = fired
                raise CommTimeoutError(
                    f"communication watchdog fired during {tag!r}: {why}"
                )
            return False

    def watch(self, tag, timeout=None):
        return self._Scope(self, tag, timeout or self.timeout)

    def register_probe(self, name, fn, owner=None):
        """Attach a health probe (e.g. ``serving.Engine.health``); its
        snapshot is dumped next to the thread stacks when the watchdog
        trips, so a hang report carries subsystem state. Probes are
        only INVOKED at trip time (they may touch wedged subsystems);
        one that returns None — its target was garbage-collected — is
        pruned by the trip dump. Register through a weakref closure so
        a dead target costs a dict entry, not its object graph.

        ``owner``: the probed object; held by weakref so registration
        and trips can prune dead probes WITHOUT invoking them (an
        invoke-to-check would defeat the only-at-trip-time rule).
        Long-lived processes churn through probed objects (serving
        engines per test/deploy), so dead entries are dropped every
        time a new probe registers."""
        import weakref

        ref = None
        if owner is not None:
            try:
                ref = weakref.ref(owner)
            except TypeError:
                ref = None  # unweakrefable owner: keep the probe forever
        self._prune_probes()
        self._probes[name] = (fn, ref)

    def unregister_probe(self, name):
        """Drop a probe; returns True if it was registered."""
        return self._probes.pop(name, None) is not None

    def _prune_probes(self):
        for name, (fn, ref) in list(self._probes.items()):
            if ref is not None and ref() is None:
                self._probes.pop(name, None)

    def _register(self, tag, timeout):
        with self._lock:
            # a trip is one-shot for the scopes that observed it (they
            # raise at exit); the FIRST scope opened after all of those
            # drained re-arms the watchdog. The monitor thread exits
            # after a trip, so always start a fresh one (the old one may
            # still be finishing its stack dump — it returns on its own).
            # The propagated ABORT_KEY is deliberately NOT deleted here:
            # peers may not have polled it yet; _seen_abort makes this
            # watchdog ignore aborts it already acted on. No store I/O
            # under the lock.
            if self.fired is not None and not self._active:
                self.fired = None
                self._thread = threading.Thread(
                    target=self._loop, daemon=True
                )
                self._thread.start()
            wid = self._next
            self._next += 1
            self._active[wid] = (tag, time.time() + timeout)
            return wid

    def _clear(self, wid):
        with self._lock:
            self._active.pop(wid, None)
            return self.fired

    # -- the background loop ----------------------------------------------
    def _loop(self):
        while not self._stop.wait(self._poll):
            now = time.time()
            expired = None
            with self._lock:
                for tag, deadline in self._active.values():
                    if now > deadline:
                        expired = (tag, "local timeout")
                        break
            if expired is None and self.store is not None and self._active:
                try:
                    aborted = self.store.get(ABORT_KEY, wait=False)
                except Exception:
                    aborted = None
                if aborted and aborted != self._seen_abort:
                    self._seen_abort = aborted
                    expired = (
                        "peer", f"abort propagated by {aborted}"
                    )
            if expired is not None:
                self._trip(*expired)
                return

    def _trip(self, tag, why):
        self.fired = (tag, why)
        sys.stderr.write(
            f"[comm_watchdog] rank {self.rank}: {tag!r} {why} "
            f"(timeout={self.timeout}s) — thread stacks:\n"
        )
        for tid, frame in sys._current_frames().items():
            sys.stderr.write(f"--- thread {tid} ---\n")
            sys.stderr.write("".join(traceback.format_stack(frame)))
        self._prune_probes()
        probe_snaps = {}
        for name, (probe, _ref) in list(self._probes.items()):
            try:
                snap = probe()
                if snap is None:  # probe target was garbage-collected
                    self._probes.pop(name, None)
                    continue
                probe_snaps[name] = snap
                sys.stderr.write(f"--- probe {name}: {snap!r}\n")
            except Exception as e:  # a broken probe must not mask the trip
                probe_snaps[name] = {"error": repr(e)}
                sys.stderr.write(f"--- probe {name} failed: {e!r}\n")
        # postmortem: the flight recorder captures what led UP to the
        # hang (recent compiles, fault fires, shed/poisoned requests)
        # next to the probe snapshots; dump degrades its own failures
        try:
            from ..observability import flight

            flight.record(
                "watchdog", "trip", tag=tag, why=why, rank=self.rank,
            )
            flight.dump(f"watchdog-trip:{tag}", probes=probe_snaps)
        except Exception as e:  # never mask the trip itself
            sys.stderr.write(f"--- flight dump failed: {e!r}\n")
        if self.store is not None and why == "local timeout":
            try:  # propagate so peers abort instead of waiting
                # timestamp nonce: a repeat abort of the same tag must
                # still read as NEW to re-armed peers
                val = f"rank{self.rank}:{tag}@{time.time():.3f}"
                self._seen_abort = val  # don't re-trip on our own abort
                self.store.set(ABORT_KEY, val)
            except Exception:
                # analysis: allow(broad-except) abort propagation is
                # best-effort over a possibly-wedged store; peers still
                # time out locally if this write never lands
                pass
        if self._on_timeout is not None:
            self._on_timeout(tag, why)
        else:
            import _thread

            _thread.interrupt_main()

    def shutdown(self):
        self._stop.set()
        self._thread.join(timeout=2)


_singleton: CommWatchdog | None = None


def enable_comm_watchdog(timeout=1800.0, store=None, rank=0, **kw):
    """Install the process-wide watchdog; eager collectives
    (distributed/communication.py) then run under watch scopes."""
    global _singleton
    if _singleton is not None:
        _singleton.shutdown()
    _singleton = CommWatchdog(timeout=timeout, store=store, rank=rank, **kw)
    return _singleton


def disable_comm_watchdog():
    global _singleton
    if _singleton is not None:
        _singleton.shutdown()
        _singleton = None


def get_comm_watchdog():
    return _singleton
