"""Process launcher (ref: python/paddle/distributed/launch/main.py:23
launch(); controllers/collective.py:37 build_pod; env contract set at
collective.py:76-132).

TPU-native shape: jax is single-controller per HOST (one process drives
all local chips), so the per-GPU-process fan-out the reference performs
collapses to one worker per node; multi-node rendezvous goes through the
jax coordination service (PADDLE_MASTER -> coordinator_address) instead
of TCPStore. The reference's env contract is preserved so existing
`paddle.distributed.launch`-style scripts keep working:

    python -m paddle_tpu.distributed.launch --nnodes=2 \
        --master=10.0.0.1:8090 --rank=0 train.py --my-args

Workers read PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_MASTER
(ParallelEnv, distributed/parallel.py) and call
paddle.distributed.init_parallel_env().
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time

from ...resilience.train_state import HANG_EXIT_CODE, PREEMPT_EXIT_CODE

__all__ = ["launch"]


def _parse(argv):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="Launch distributed training workers",
    )
    p.add_argument("--nnodes", type=int, default=1,
                   help="number of nodes (hosts)")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="worker processes per node (TPU: 1 process "
                        "drives all local chips)")
    p.add_argument("--master", type=str, default=None,
                   help="coordinator host:port (node rank 0)")
    p.add_argument("--rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", 0)),
                   help="this node's rank")
    p.add_argument("--log_dir", type=str, default="log",
                   help="per-worker log directory")
    p.add_argument("--devices", type=str, default=None,
                   help="visible device ids (comma separated)")
    p.add_argument("--max_restarts", type=int, default=0,
                   help="elastic: relaunch the pod up to N times after a "
                        "worker failure (workers resume from their own "
                        "checkpoints; PADDLE_RESTART_COUNT tells them "
                        "which incarnation they are). A pod that exits "
                        f"{PREEMPT_EXIT_CODE} (preemption after a "
                        "verified emergency checkpoint) relaunches "
                        "WITHOUT consuming this budget")
    p.add_argument("--max_preempt_restarts", type=int, default=100,
                   help="runaway guard: bound preemption relaunches "
                        "(which never burn --max_restarts) so a worker "
                        "stuck in a preempt-exit loop cannot respawn "
                        "forever")
    p.add_argument("--restart_interval", type=float, default=1.0,
                   help="seconds between elastic relaunches")
    p.add_argument("--elastic", action="store_true",
                   help="elastic manager v2: store-based membership with "
                        "rank remap — on any node's failure the surviving "
                        "nodes re-rendezvous, get new contiguous ranks "
                        "(scale-down) and relaunch; requires --master")
    p.add_argument("--elastic_grace", type=float, default=5.0,
                   help="seconds the master waits for members to register "
                        "before sealing a (possibly smaller) RE-rendezvous "
                        "epoch")
    p.add_argument("--elastic_join_timeout", type=float, default=300.0,
                   help="seconds the master waits for the FULL node set "
                        "at the initial (epoch 0) rendezvous")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _worker_env(args, local_rank, node_rank=None, nnodes=None,
                master=None):
    env = dict(os.environ)
    node_rank = args.rank if node_rank is None else node_rank
    nnodes = args.nnodes if nnodes is None else nnodes
    master = args.master if master is None else master
    world = nnodes * args.nproc_per_node
    rank = node_rank * args.nproc_per_node + local_rank
    env.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_LOCAL_RANK": str(local_rank),
        "PADDLE_NNODES": str(nnodes),
    })
    if master:
        env["PADDLE_MASTER"] = master
        # jax.distributed.initialize reads these directly
        env["JAX_COORDINATOR_ADDRESS"] = master
        env["JAX_NUM_PROCESSES"] = str(world)
        env["JAX_PROCESS_ID"] = str(rank)
    if args.devices:
        env["TPU_VISIBLE_DEVICES"] = args.devices
    return env


def launch(argv=None):
    """Run the pod; with --max_restarts > 0, relaunch it after worker
    failures (the elastic policy).

    ref: fleet/elastic/manager.py:125 — the reference's elastic manager
    watches etcd membership and rebuilds the pod on change. The TPU
    single-controller form needs no external store: the pod IS the
    membership (one process per host over the jax coordination service),
    so elasticity reduces to supervised relaunch — each incarnation gets
    PADDLE_RESTART_COUNT and resumes from its sharded checkpoint
    (distributed/checkpoint.py), which is the reference's
    train-resume contract."""
    args = _parse(argv if argv is not None else sys.argv[1:])
    if args.elastic:
        return _elastic_launch(args)
    restarts = 0    # crash budget consumed (--max_restarts)
    preempts = 0    # preemption relaunches (budget-free)
    history = []    # (incarnation, exit code) for the summary
    reason = None   # restart provenance handed to the NEXT incarnation
    while True:
        incarnation = restarts + preempts
        code = _run_pod(args, incarnation, restart_reason=reason)
        history.append((incarnation, code))
        if code in (0, 130):
            _pod_summary(history)
            return code
        if code == PREEMPT_EXIT_CODE:
            # preemption protocol (resilience.train_state): the worker
            # checkpointed and exited on a preemption notice — relaunch
            # without burning the crash budget
            if preempts >= args.max_preempt_restarts:
                print(
                    f"elastic: max_preempt_restarts "
                    f"({args.max_preempt_restarts}) exhausted",
                    file=sys.stderr,
                )
                _pod_summary(history)
                return code
            preempts += 1
            reason = "preempt"
            print(
                f"elastic: pod preempted (emergency checkpoint taken); "
                f"relaunching (preempt {preempts}, crash budget "
                f"untouched at {restarts}/{args.max_restarts}) in "
                f"{args.restart_interval}s",
                file=sys.stderr,
            )
        else:
            if restarts >= args.max_restarts:
                _pod_summary(history)
                return code
            restarts += 1
            reason = "crash"
            print(
                f"elastic: relaunching pod (restart {restarts}/"
                f"{args.max_restarts}) in {args.restart_interval}s",
                file=sys.stderr,
            )
        time.sleep(args.restart_interval)


def _classify_exit(code):
    if code == 0:
        return "ok"
    if code == PREEMPT_EXIT_CODE:
        return "preempt"
    if code == HANG_EXIT_CODE:
        # watchdog-detected stuck step: burns the crash budget like any
        # failure, but the summary should say what actually happened
        return "hang"
    if code == 130:
        return "interrupt"
    return "crash"


def _pod_summary(history):
    """Per-incarnation exit codes, printed once at launcher exit so a
    postmortem reads the whole restart history in one place."""
    if not history:
        return
    print("launch summary:", file=sys.stderr)
    for incarnation, code in history:
        print(
            f"  incarnation {incarnation}: exit={code} "
            f"({_classify_exit(code)})",
            file=sys.stderr,
        )


_RESTART_CODE = -999  # internal: pod stopped because the epoch moved on


def _elastic_launch(args):
    """Elastic manager v2 (ref fleet/elastic/manager.py:125): membership
    epochs over the TCPStore. Per epoch every surviving node registers;
    the master seals the member list after a grace period (all nnodes
    present ends the wait early), assigns NEW CONTIGUOUS RANKS (rank
    remap — a lost node shrinks the world), and every node launches its
    pod against a fresh coordinator port. Any node whose pod fails bumps
    the epoch; every supervision loop polls it and re-rendezvouses.
    Workers see the usual env contract plus PADDLE_RESTART_COUNT and
    resume from their checkpoints."""
    import json as _json

    from ..store import TCPStore

    if not args.master:
        raise SystemExit("--elastic requires --master host:port")
    host, port = args.master.rsplit(":", 1)
    store = TCPStore(
        host, int(port) + 1, is_master=args.rank == 0, timeout=120.0
    )
    epoch, restarts, preempts, incarnation = 0, 0, 0, 0
    reason = None
    history = []
    while True:
        epoch = max(
            epoch, int(store.get("current_epoch", wait=False) or 0)
        )
        store.set(f"epoch/{epoch}/node/{args.rank}", "alive")
        if args.rank == 0:
            # epoch 0 is the initial rendezvous: wait for the FULL node
            # set (the reference's job-start join); re-rendezvous epochs
            # use the short grace and seal with the survivors
            wait = (args.elastic_join_timeout if epoch == 0
                    else args.elastic_grace)
            deadline = time.time() + wait
            while time.time() < deadline:
                n = len(store.list_keys(f"epoch/{epoch}/node/"))
                if n >= args.nnodes:
                    break
                time.sleep(0.1)
            members = sorted(
                int(k.rsplit("/", 1)[1])
                for k in store.list_keys(f"epoch/{epoch}/node/")
            )
            plan = {
                "ranks": {str(nid): i for i, nid in enumerate(members)},
                "nnodes": len(members),
                "coord_port": int(port) + 2 + epoch,
            }
            store.set(f"epoch/{epoch}/plan", _json.dumps(plan))
            print(f"elastic: epoch {epoch} sealed with nodes {members}",
                  file=sys.stderr)
        # the master seals epoch 0 only after --elastic_join_timeout, so
        # non-master nodes must out-wait that window (store default is
        # 120s; a straggler sealing late would otherwise kill the others)
        plan = _json.loads(store.get(
            f"epoch/{epoch}/plan",
            timeout=args.elastic_join_timeout + 60.0,
        ))
        my_rank = plan["ranks"].get(str(args.rank))
        if my_rank is None:
            print(f"elastic: node {args.rank} not in epoch {epoch}; "
                  "exiting", file=sys.stderr)
            return 0

        def epoch_moved(e=epoch):
            return int(store.get("current_epoch", wait=False) or 0) > e

        code = _run_pod(
            args, incarnation, node_rank=my_rank, nnodes=plan["nnodes"],
            master=f"{host}:{plan['coord_port']}", stop_check=epoch_moved,
            restart_reason=reason,
        )
        if code != _RESTART_CODE:
            history.append((incarnation, code))
        if code == 0:
            _pod_summary(history)
            return 0
        if code == PREEMPT_EXIT_CODE:
            # preempted node: checkpointed; rejoin the next epoch
            # without consuming the crash budget — but under the same
            # runaway guard as the non-elastic path
            if preempts >= args.max_preempt_restarts:
                print(
                    f"elastic: max_preempt_restarts "
                    f"({args.max_preempt_restarts}) exhausted",
                    file=sys.stderr,
                )
                _pod_summary(history)
                return code
            preempts += 1
            incarnation += 1
            reason = "preempt"
            store.set("current_epoch", str(epoch + 1))
        elif code != _RESTART_CODE:
            # our pod failed: tell the others and count the restart
            restarts += 1
            incarnation += 1
            reason = "crash"
            store.set("current_epoch", str(epoch + 1))
            if restarts > args.max_restarts:
                print(f"elastic: max_restarts ({args.max_restarts}) "
                      "exhausted", file=sys.stderr)
                _pod_summary(history)
                return code
        epoch += 1
        time.sleep(args.restart_interval)


def _run_pod(args, restart_count=0, node_rank=None, nnodes=None,
             master=None, stop_check=None, restart_reason=None):
    os.makedirs(args.log_dir, exist_ok=True)

    procs = []
    for local_rank in range(args.nproc_per_node):
        nr = args.rank if node_rank is None else node_rank
        rank = nr * args.nproc_per_node + local_rank
        suffix = f".r{restart_count}" if restart_count else ""
        log_path = os.path.join(args.log_dir, f"workerlog.{rank}{suffix}")
        log_f = open(log_path, "w")
        cmd = [sys.executable, "-u", args.training_script,
               *args.training_script_args]
        env = _worker_env(args, local_rank, node_rank=node_rank,
                          nnodes=nnodes, master=master)
        env["PADDLE_RESTART_COUNT"] = str(restart_count)
        # restart provenance: preempt|crash next to the incarnation
        # count, so a resuming worker can tell a budget-free preemption
        # relaunch from a crash recovery (first incarnations get none)
        env.pop("PADDLE_RESTART_REASON", None)
        if restart_reason is not None:
            env["PADDLE_RESTART_REASON"] = restart_reason
        proc = subprocess.Popen(
            cmd, env=env,
            stdout=log_f, stderr=subprocess.STDOUT,
        )
        procs.append((proc, log_f, log_path))
        print(f"launched worker rank={rank} pid={proc.pid} "
              f"log={log_path}", file=sys.stderr)

    # Pod supervision (ref controllers/watcher.py): fail fast if any
    # worker dies nonzero, terminate the rest.
    exit_code = 0
    try:
        while procs:
            if stop_check is not None and stop_check():
                print("elastic: epoch moved on — stopping local pod",
                      file=sys.stderr)
                _terminate(procs)
                return _RESTART_CODE
            alive = []
            for proc, log_f, log_path in procs:
                ret = proc.poll()
                if ret is None:
                    alive.append((proc, log_f, log_path))
                    continue
                log_f.close()
                if ret != 0:
                    print(
                        f"worker pid={proc.pid} exited {ret}; see "
                        f"{log_path} — terminating pod",
                        file=sys.stderr,
                    )
                    exit_code = ret
                    _terminate(alive + procs[procs.index((proc, log_f,
                                                          log_path)) + 1:])
                    procs = []
                    alive = []
                    break
            procs = alive
            if procs:
                time.sleep(0.2)
    except KeyboardInterrupt:
        _terminate(procs)
        exit_code = 130
    return exit_code


def _terminate(procs, grace=5.0):
    """SIGTERM the pod, wait out the grace period, SIGKILL stragglers,
    and close log handles (workers must not outlive the launcher and keep
    the TPU locked for the next job)."""
    for proc, _, _ in procs:
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
    deadline = time.time() + grace
    for proc, log_f, _ in procs:
        remaining = max(0.1, deadline - time.time())
        try:
            proc.wait(timeout=remaining)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
        try:
            log_f.close()
        except OSError:
            pass  # flush of a torn log pipe; the procs are already down


if __name__ == "__main__":
    sys.exit(launch())
