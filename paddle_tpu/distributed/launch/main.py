"""Process launcher (ref: python/paddle/distributed/launch/main.py:23
launch(); controllers/collective.py:37 build_pod; env contract set at
collective.py:76-132).

TPU-native shape: jax is single-controller per HOST (one process drives
all local chips), so the per-GPU-process fan-out the reference performs
collapses to one worker per node; multi-node rendezvous goes through the
jax coordination service (PADDLE_MASTER -> coordinator_address) instead
of TCPStore. The reference's env contract is preserved so existing
`paddle.distributed.launch`-style scripts keep working:

    python -m paddle_tpu.distributed.launch --nnodes=2 \
        --master=10.0.0.1:8090 --rank=0 train.py --my-args

Workers read PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_MASTER
(ParallelEnv, distributed/parallel.py) and call
paddle.distributed.init_parallel_env().
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time

__all__ = ["launch"]


def _parse(argv):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="Launch distributed training workers",
    )
    p.add_argument("--nnodes", type=int, default=1,
                   help="number of nodes (hosts)")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="worker processes per node (TPU: 1 process "
                        "drives all local chips)")
    p.add_argument("--master", type=str, default=None,
                   help="coordinator host:port (node rank 0)")
    p.add_argument("--rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", 0)),
                   help="this node's rank")
    p.add_argument("--log_dir", type=str, default="log",
                   help="per-worker log directory")
    p.add_argument("--devices", type=str, default=None,
                   help="visible device ids (comma separated)")
    p.add_argument("--max_restarts", type=int, default=0,
                   help="elastic: relaunch the pod up to N times after a "
                        "worker failure (workers resume from their own "
                        "checkpoints; PADDLE_RESTART_COUNT tells them "
                        "which incarnation they are)")
    p.add_argument("--restart_interval", type=float, default=1.0,
                   help="seconds between elastic relaunches")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _worker_env(args, local_rank):
    env = dict(os.environ)
    world = args.nnodes * args.nproc_per_node
    rank = args.rank * args.nproc_per_node + local_rank
    env.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_LOCAL_RANK": str(local_rank),
        "PADDLE_NNODES": str(args.nnodes),
    })
    if args.master:
        env["PADDLE_MASTER"] = args.master
        # jax.distributed.initialize reads these directly
        env.setdefault("JAX_COORDINATOR_ADDRESS", args.master)
        env.setdefault("JAX_NUM_PROCESSES", str(world))
        env.setdefault("JAX_PROCESS_ID", str(rank))
    if args.devices:
        env["TPU_VISIBLE_DEVICES"] = args.devices
    return env


def launch(argv=None):
    """Run the pod; with --max_restarts > 0, relaunch it after worker
    failures (the elastic policy).

    ref: fleet/elastic/manager.py:125 — the reference's elastic manager
    watches etcd membership and rebuilds the pod on change. The TPU
    single-controller form needs no external store: the pod IS the
    membership (one process per host over the jax coordination service),
    so elasticity reduces to supervised relaunch — each incarnation gets
    PADDLE_RESTART_COUNT and resumes from its sharded checkpoint
    (distributed/checkpoint.py), which is the reference's
    train-resume contract."""
    args = _parse(argv if argv is not None else sys.argv[1:])
    restarts = 0
    while True:
        code = _run_pod(args, restarts)
        if code in (0, 130) or restarts >= args.max_restarts:
            return code
        restarts += 1
        print(
            f"elastic: relaunching pod (restart {restarts}/"
            f"{args.max_restarts}) in {args.restart_interval}s",
            file=sys.stderr,
        )
        time.sleep(args.restart_interval)


def _run_pod(args, restart_count=0):
    os.makedirs(args.log_dir, exist_ok=True)

    procs = []
    for local_rank in range(args.nproc_per_node):
        rank = args.rank * args.nproc_per_node + local_rank
        suffix = f".r{restart_count}" if restart_count else ""
        log_path = os.path.join(args.log_dir, f"workerlog.{rank}{suffix}")
        log_f = open(log_path, "w")
        cmd = [sys.executable, "-u", args.training_script,
               *args.training_script_args]
        env = _worker_env(args, local_rank)
        env["PADDLE_RESTART_COUNT"] = str(restart_count)
        proc = subprocess.Popen(
            cmd, env=env,
            stdout=log_f, stderr=subprocess.STDOUT,
        )
        procs.append((proc, log_f, log_path))
        print(f"launched worker rank={rank} pid={proc.pid} "
              f"log={log_path}", file=sys.stderr)

    # Pod supervision (ref controllers/watcher.py): fail fast if any
    # worker dies nonzero, terminate the rest.
    exit_code = 0
    try:
        while procs:
            alive = []
            for proc, log_f, log_path in procs:
                ret = proc.poll()
                if ret is None:
                    alive.append((proc, log_f, log_path))
                    continue
                log_f.close()
                if ret != 0:
                    print(
                        f"worker pid={proc.pid} exited {ret}; see "
                        f"{log_path} — terminating pod",
                        file=sys.stderr,
                    )
                    exit_code = ret
                    _terminate(alive + procs[procs.index((proc, log_f,
                                                          log_path)) + 1:])
                    procs = []
                    alive = []
                    break
            procs = alive
            if procs:
                time.sleep(0.2)
    except KeyboardInterrupt:
        _terminate(procs)
        exit_code = 130
    return exit_code


def _terminate(procs, grace=5.0):
    """SIGTERM the pod, wait out the grace period, SIGKILL stragglers,
    and close log handles (workers must not outlive the launcher and keep
    the TPU locked for the next job)."""
    for proc, _, _ in procs:
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
    deadline = time.time() + grace
    for proc, log_f, _ in procs:
        remaining = max(0.1, deadline - time.time())
        try:
            proc.wait(timeout=remaining)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
        try:
            log_f.close()
        except Exception:
            pass


if __name__ == "__main__":
    sys.exit(launch())
