"""Parallel-config auto-tuner.

ref: python/paddle/distributed/auto_tuner/{tuner.py:21 (search loop),
search.py (grid), prune.py (constraint pruning), cost_model.py (memory
prediction)}. The reference launches a real trial job per candidate; on
TPU the virtual-mesh dryrun makes probing nearly free, so the tuner is:
grid -> hard-constraint prune -> analytic HBM model (calibrated against
the measured single-chip ceiling, BASELINE.md: ~1B params trainable on a
15.75 GB v5e with bf16 moments, i.e. a ~2x transient factor over resident
state) -> throughput score (MXU efficiency x pipeline-bubble x comm
discounts) -> optional compile probe of the top candidates via
``dist.parallelize`` on the virtual mesh.
"""
from __future__ import annotations

from dataclasses import dataclass, field

__all__ = ["TuneConfig", "Candidate", "tune"]

_BYTES = {"bfloat16": 2, "float16": 2, "float32": 4}


@dataclass
class TuneConfig:
    """Workload description (the reference's tuner_cfg dict,
    auto_tuner/tuner.py)."""

    num_params: float                 # total model params
    hidden_size: int
    num_layers: int
    num_heads: int
    vocab_size: int
    seq_len: int
    global_batch: int
    n_devices: int
    hbm_gb: float = 15.75             # per-chip HBM (v5e default)
    dtype: str = "bfloat16"
    moments_dtype: str = "bfloat16"   # fp32 for master-weight AdamW
    recompute: bool = False
    # calibration: transiently-resident multiple of the STATE bytes
    # (params+grads+moments). Measured single-chip (remote-AOT tunnel,
    # donation not aliased): 1.12B OOMs / 0.97B trains on one v5e => ~2x.
    # Sharded multi-chip programs donate in-program, leaving collective
    # staging buffers => ~1.3x.
    transient_single: float = 2.0
    transient_sharded: float = 1.3
    max_sharding_level: int = 3


@dataclass
class Candidate:
    dp: int
    mp: int
    pp: int
    micro_batches: int
    sharding_level: int
    est_hbm_gb: float = 0.0
    score: float = 0.0
    fits: bool = False
    pruned: str = ""
    probe_ok: bool | None = None
    extras: dict = field(default_factory=dict)

    @property
    def config(self):
        """dist.parallelize config for this candidate."""
        return {
            "dp_degree": self.dp, "mp_degree": self.mp,
            "pp_degree": self.pp,
            "dp_config": {"sharding_level": self.sharding_level},
            "pp_config": {"micro_batches": self.micro_batches},
        }


def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


def _grid(cfg: TuneConfig):
    """All (dp, mp, pp, micro, stage) filling the device count (the
    reference's grid search, auto_tuner/search.py)."""
    out = []
    for dp in _divisors(cfg.n_devices):
        for mp in _divisors(cfg.n_devices // dp):
            pp = cfg.n_devices // (dp * mp)
            micro_opts = {pp, 2 * pp, 4 * pp} if pp > 1 else {1}
            for micro in sorted(micro_opts):
                levels = (
                    range(0, cfg.max_sharding_level + 1) if dp > 1 else [0]
                )
                for stage in levels:
                    out.append(Candidate(dp, mp, pp, micro, stage))
    return out


def _prune(c: Candidate, cfg: TuneConfig):
    """Hard constraints (ref auto_tuner/prune.py: _prune_by_mp/_pp/_mbs):
    divisibility of heads/layers/vocab/batch."""
    if cfg.num_heads % c.mp:
        return f"heads {cfg.num_heads} % mp {c.mp}"
    if cfg.vocab_size % c.mp:
        return f"vocab {cfg.vocab_size} % mp {c.mp}"
    if cfg.num_layers % c.pp:
        return f"layers {cfg.num_layers} % pp {c.pp}"
    if cfg.global_batch % (c.dp * c.micro_batches):
        return (f"batch {cfg.global_batch} % dp*micro "
                f"{c.dp * c.micro_batches}")
    if c.pp > 1 and c.micro_batches < c.pp:
        return "micro_batches < pp (bubble-dominated)"
    return ""


def _est_hbm_gb(c: Candidate, cfg: TuneConfig):
    """Per-device HBM estimate (ref cost_model.py memory model, re-fit to
    the GSPMD layouts this framework actually emits)."""
    pb = _BYTES[cfg.dtype]
    mb = _BYTES[cfg.moments_dtype]
    shard = c.mp * c.pp
    p_local = cfg.num_params / shard
    params = p_local * pb
    grads = p_local * pb / (c.dp if c.sharding_level >= 2 else 1)
    moments = 2 * p_local * mb / (c.dp if c.sharding_level >= 1 else 1)
    if c.sharding_level >= 3:
        params = params / c.dp
    # activations: full per-layer tensors live for ONE in-flight
    # micro-batch (1F1B recomputes the rest from its stage-input ring,
    # which stashes O(pp) micro-batch INPUTS only)
    mb_size = cfg.global_batch // (c.dp * c.micro_batches)
    act_per_layer = mb_size * cfg.seq_len * cfg.hidden_size * 14 * pb
    layers_local = cfg.num_layers / c.pp
    acts = act_per_layer * (1 if cfg.recompute else layers_local)
    stage_in = mb_size * cfg.seq_len * cfg.hidden_size * pb
    stash = (2 * c.pp * stage_in) if c.pp > 1 else 0
    # fused-loss chunking keeps logits out of the picture; embedding +
    # head activations ~ 2 * mb * seq * h
    edge = 2 * mb_size * cfg.seq_len * cfg.hidden_size * pb
    state = params + grads + moments
    tf = (cfg.transient_single
          if (c.dp == c.mp == c.pp == 1) else cfg.transient_sharded)
    return (tf * state + acts + stash + edge) / 1e9


def _score(c: Candidate, cfg: TuneConfig):
    """Relative step-time estimate (smaller is better -> score is its
    inverse). Terms: pipeline bubble, TP collective tax, ZeRO-3 gather
    tax, MXU-width efficiency falling with mp (matmul columns shrink)."""
    from .pipeline import schedule_bubble_fraction

    bubble = (
        schedule_bubble_fraction("1f1b", c.pp, c.micro_batches)
        if c.pp > 1 else 0.0
    )
    tp_tax = 0.04 * (c.mp - 1)          # 2 psums/block over ICI
    zero3_tax = 0.10 if c.sharding_level >= 3 else 0.0
    width = cfg.hidden_size / c.mp
    mxu_eff = min(1.0, width / 2048.0) ** 0.5  # MFU rises with width
    time_rel = (1.0 + tp_tax + zero3_tax) / ((1.0 - bubble) * mxu_eff)
    return 1.0 / time_rel


def tune(cfg: TuneConfig, top_k=5, probe=None):
    """Rank parallel configs for the workload. Returns (ranked_fitting,
    all_candidates). ``probe(candidate) -> bool`` optionally validates
    the top-k (e.g. a compile-only dryrun through dist.parallelize);
    failures drop the candidate (the reference's trial-job loop,
    tuner.py:21, with compiles instead of jobs)."""
    cands = _grid(cfg)
    for c in cands:
        c.pruned = _prune(c, cfg)
        if c.pruned:
            continue
        c.est_hbm_gb = round(_est_hbm_gb(c, cfg), 2)
        c.fits = c.est_hbm_gb <= cfg.hbm_gb
        c.score = round(_score(c, cfg), 4)
    fitting = sorted(
        (c for c in cands if not c.pruned and c.fits),
        key=lambda c: -c.score,
    )
    if probe is not None:
        validated = []
        for c in fitting[:top_k]:
            c.probe_ok = bool(probe(c))
            if c.probe_ok:
                validated.append(c)
        fitting = validated + fitting[top_k:]
    return fitting[:top_k], cands
