"""Activation recomputation (gradient checkpointing).

ref: python/paddle/distributed/fleet/utils/recompute (recompute(),
recompute_sequential) and the static pass
distributed/passes/auto_parallel_recompute.py.

TPU-native: `recompute(fn, *args)` records ONE tape op whose vjp is
`jax.vjp(jax.checkpoint(pure_fn))` — the checkpoint transform drops the
segment's internal residuals and recomputes them in backward, trading
FLOPs for HBM exactly like the reference's RecomputeFunction, but the
recompute schedule is compiled into the XLA program instead of re-running
Python.
"""
from __future__ import annotations

import jax

from ..core import autograd, dispatch
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer

__all__ = ["recompute", "recompute_sequential"]


def _layer_state(l):
    return [p for _, p in l.named_parameters()] + [
        b for _, b in l.named_buffers()
    ]


def _callable_state(function):
    """Params/buffers a non-Layer callable depends on: bound Layer
    methods and Layers/Tensors captured in closures or default args."""
    state = []
    seen = set()

    def visit(v):
        if isinstance(v, Layer) and id(v) not in seen:
            seen.add(id(v))
            state.extend(_layer_state(v))
        elif isinstance(v, Tensor) and not v.stop_gradient:
            if id(v) not in seen:
                seen.add(id(v))
                state.append(v)
        elif isinstance(v, (list, tuple)):
            for item in v:
                visit(item)

    visit(getattr(function, "__self__", None))
    for cell in getattr(function, "__closure__", None) or ():
        try:
            visit(cell.cell_contents)
        except ValueError:
            pass
    for d in getattr(function, "__defaults__", None) or ():
        visit(d)
    return state


def recompute(function, *args, use_reentrant=True,
              _extra_state=None, **kwargs):
    """Run `function(*args, **kwargs)` with activation checkpointing.

    Tensor args (and any Layer parameters/buffers the function closes
    over) become inputs of the checkpointed segment so their gradients
    flow; everything computed inside is recomputed during backward instead
    of being saved."""
    # Collect params/buffers the function depends on so their gradients
    # flow: Layer instances directly, bound Layer methods, and Layers /
    # Parameters captured in a lambda's closure (the reference pattern
    # recompute(lambda h: self.block(h), h)).
    if isinstance(function, Layer):
        fn = function.forward
        state = _layer_state(function)
    else:
        fn = function
        state = _callable_state(function)
        # dedup against explicit args handled below via identity
        arg_ids = {
            id(a) for a in jax.tree_util.tree_leaves(
                (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor)
            )
            if isinstance(a, Tensor)
        }
        state = [t for t in state if id(t) not in arg_ids]
    if _extra_state:
        have = {id(t) for t in state}
        state.extend(t for t in _extra_state if id(t) not in have)

    flat_in, treedef = jax.tree_util.tree_flatten(
        (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor)
    )
    slots = [i for i, x in enumerate(flat_in) if isinstance(x, Tensor)]
    n_state = len(state)
    out_tree_box = [None]

    # One fresh key per segment, drawn at the OUTER trace level. The
    # forward trace and the checkpoint's backward re-trace both replay
    # from this key (same dropout mask), and the global generator never
    # retains a sub-trace tracer (that leak breaks later ops).
    from ..core import random as random_mod

    seg_key = random_mod.split_key()

    def pure(*arrays):
        state_arrays = arrays[:n_state]
        in_arrays = arrays[n_state:]
        old = [t._data for t in state]
        gen = random_mod.default_generator
        saved_key = gen._key
        gen._key = seg_key
        for t, a in zip(state, state_arrays):
            t._data = a
        try:
            rebuilt = list(flat_in)
            for i, a in zip(slots, in_arrays):
                rebuilt[i] = Tensor(a, stop_gradient=True)
            a2, k2 = jax.tree_util.tree_unflatten(treedef, rebuilt)
            with autograd.no_grad():
                out = fn(*a2, **k2)
        finally:
            for t, a in zip(state, old):
                t._data = a
            gen._key = saved_key
        out_flat, out_tree = jax.tree_util.tree_flatten(
            out, is_leaf=lambda x: isinstance(x, Tensor)
        )
        out_tree_box[0] = out_tree
        return tuple(
            o._data if isinstance(o, Tensor) else o for o in out_flat
        )

    ckpt = jax.checkpoint(pure)
    tensor_inputs = tuple(state) + tuple(flat_in[i] for i in slots)
    results = dispatch.call("recompute", ckpt, tensor_inputs, {})
    results = (
        list(results) if isinstance(results, (tuple, list)) else [results]
    )
    # the out_tree reproduces fn's exact return structure (a single
    # Tensor stays a Tensor; a 1-tuple stays a 1-tuple)
    return jax.tree_util.tree_unflatten(out_tree_box[0], results)


def recompute_sequential(ctx, functions, *args, **kwargs):
    """ref: fleet/utils recompute_sequential — run a Sequential-style
    chain in ctx['segments'] checkpointed chunks (default 1 function per
    segment); kwargs forward to every segment."""
    functions = list(functions)
    segments = int((ctx or {}).get("segments", len(functions))) or len(
        functions
    )
    per = max(1, (len(functions) + segments - 1) // segments)
    out = args
    for i in range(0, len(functions), per):
        chunk = functions[i : i + per]

        def seg_fn(*xs, _chunk=chunk, **kw):
            cur = xs
            for f in _chunk:
                cur = f(*cur, **kw) if kw else f(*cur)
                if not isinstance(cur, tuple):
                    cur = (cur,)
            return cur[0] if len(cur) == 1 else cur

        seg_state = []
        for f in chunk:
            if isinstance(f, Layer):
                seg_state.extend(_layer_state(f))
            else:
                seg_state.extend(_callable_state(f))
        out = recompute(
            seg_fn, *(out if isinstance(out, tuple) else (out,)),
            _extra_state=seg_state, **kwargs
        )
    return out
