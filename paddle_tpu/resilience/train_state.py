"""Bit-exact training resume contract (docs/resilience.md).

The elastic launcher (``distributed/launch``) has always been able to
RELAUNCH a failed pod; this module makes the relaunch TRUSTWORTHY: a
run killed at any step boundary and resumed from its checkpoint
produces final weights bit-identical to an uninterrupted run.

Three pieces:

* :class:`TrainState` — one capture/restore object bundling everything
  a training process owns: model + optimizer (accumulators, LR
  schedule, global step) + AMP scaler + grad-accumulation phase (with
  the in-flight gradient buffers) + ALL RNG streams (python ``random``,
  global ``np.random``, the framework's jax key) + the DataLoader's
  mid-epoch cursor. Persisted through checkpoint format v2 (atomic,
  checksummed, verified-before-publish).
* :class:`PreemptionHandler` / :class:`TrainLoop` — SIGTERM (or a
  programmatic :func:`request_preemption` notice) triggers a
  barrier-coordinated **emergency checkpoint** at the next step
  boundary, then exits with :data:`PREEMPT_EXIT_CODE` — which the
  elastic launcher recognizes as *preemption* and relaunches WITHOUT
  burning the ``--max_restarts`` crash budget.
* hang handling — each train step runs under a ``CommWatchdog``
  deadline when a watchdog is supplied; a stuck step dumps a flight
  postmortem, propagates the abort through the TCPStore (the
  watchdog's own trip path), and exits :data:`HANG_EXIT_CODE` for an
  elastic relaunch.

The proof lives in ``tests/test_train_resume.py``: a seeded chaos
schedule at the ``train.step`` fault site kills a worker mid-run, the
launcher resumes it, and the final weights are asserted bit-identical
to the uninterrupted run.

Module-level imports are stdlib + numpy only: the launcher imports
:data:`PREEMPT_EXIT_CODE` from here, and observability/distributed load
lazily (they import ``resilience`` themselves).
"""
from __future__ import annotations

import contextlib
import os
import signal
import sys
import threading
import time

import numpy as np

__all__ = [
    "TrainState", "TrainLoop", "PreemptionHandler", "request_preemption",
    "preemption_requested", "PREEMPT_EXIT_CODE", "HANG_EXIT_CODE",
]

# Exit-code protocol with distributed/launch: a worker that exits
# PREEMPT_EXIT_CODE checkpointed cleanly after a preemption notice —
# relaunch it without consuming the crash-restart budget. HANG_EXIT_CODE
# is a watchdog-detected stuck step — a real failure that DOES burn
# budget, but is distinguishable in the launcher summary.
PREEMPT_EXIT_CODE = 76
HANG_EXIT_CODE = 68

# key a preempted rank writes into the TCPStore so peers that got no
# OS signal of their own still join the emergency checkpoint barrier.
# TrainLoop scopes it (and the barrier names) by the incarnation id
# (PADDLE_RESTART_COUNT) so a store that outlives the pod cannot leak
# the previous incarnation's notice into the resumed one.
PREEMPT_NOTICE_KEY = "__train_preempt__"


def _obs():
    """Lazy observability handle (flight, metrics, spans) — may be None
    mid-bootstrap; every caller degrades to a no-op."""
    try:
        from .. import observability

        return observability
    except Exception:
        # analysis: allow(broad-except) telemetry must never take down
        # the training it observes
        return None


# -- RNG stream capture ------------------------------------------------------


def _capture_rng():
    """Snapshot every RNG stream training can draw from: python
    ``random``, the global ``np.random`` MT19937, and the framework's
    splitting jax key (core.random.default_generator)."""
    import random as pyrandom

    out = {}
    version, keys, gauss = pyrandom.getstate()
    out["rng.py"] = [int(version), [int(k) for k in keys],
                     None if gauss is None else float(gauss)]
    name, np_keys, pos, has_gauss, cached = np.random.get_state()
    out["rng.np.keys"] = np.asarray(np_keys, dtype=np.uint32)
    out["rng.np.meta"] = [str(name), int(pos), int(has_gauss),
                          float(cached)]
    from ..core import random as frand

    out["rng.fw"] = np.asarray(frand.get_rng_state())
    return out


def _restore_rng(flat):
    import random as pyrandom

    if "rng.py" in flat:
        version, keys, gauss = flat["rng.py"]
        pyrandom.setstate(
            (int(version), tuple(int(k) for k in keys),
             None if gauss is None else float(gauss))
        )
    if "rng.np.keys" in flat and "rng.np.meta" in flat:
        name, pos, has_gauss, cached = flat["rng.np.meta"]
        np.random.set_state(
            (str(name), _as_np(flat["rng.np.keys"]).astype(np.uint32),
             int(pos), int(has_gauss), float(cached))
        )
    if "rng.fw" in flat:
        from ..core import random as frand

        frand.set_rng_state(_as_np(flat["rng.fw"]))


def _as_np(v):
    """Checkpoint values come back as framework Tensors; RNG plumbing
    wants raw ndarrays."""
    if hasattr(v, "numpy"):
        return np.asarray(v.numpy())
    return np.asarray(v)


# -- TrainState --------------------------------------------------------------


class TrainState:
    """Everything a training process must carry across a kill.

    ``state_dict()`` returns ONE flat, namespaced dict (``model.*``,
    ``opt.*``, ``grad.*``, ``rng.*``, ``data``, ``scaler``,
    ``train.*``) that round-trips through
    ``distributed.checkpoint.save_state_dict`` / ``load_full``;
    ``save``/``load`` do exactly that. Restoring into freshly
    constructed (identically configured) objects and continuing
    training is bit-identical to never having stopped — the contract
    ``tests/test_train_resume.py`` pins.

    ``accum_phase`` is the number of micro-batches folded into the
    current gradient-accumulation window; when non-zero, the in-flight
    ``p.grad`` buffers are captured too, so even a mid-window
    checkpoint resumes exactly.
    """

    def __init__(self, model=None, optimizer=None, scaler=None,
                 dataloader=None, step=0, epoch=0, accum_steps=1):
        self.model = model
        self.optimizer = optimizer
        self.scaler = scaler
        self.dataloader = dataloader
        self.step = int(step)
        self.epoch = int(epoch)
        self.accum_steps = int(accum_steps)
        self.accum_phase = 0

    # -- capture -----------------------------------------------------------
    def _named_params(self):
        params = (
            self.optimizer._parameter_list
            if self.optimizer is not None
            else list(self.model.parameters()) if self.model is not None
            else []
        )
        return [
            (p.name if p.name is not None else f"param_{i}", p)
            for i, p in enumerate(params)
        ]

    def state_dict(self):
        flat = {}
        if self.model is not None:
            for k, v in self.model.state_dict().items():
                flat[f"model.{k}"] = v
        if self.optimizer is not None:
            for k, v in self.optimizer.state_dict().items():
                flat[f"opt.{k}"] = v
        if self.scaler is not None:
            flat["scaler"] = dict(self.scaler.state_dict())
        if self.dataloader is not None and hasattr(
            self.dataloader, "state_dict"
        ):
            flat["data"] = self.dataloader.state_dict()
        flat.update(_capture_rng())
        flat["train.step"] = self.step
        flat["train.epoch"] = self.epoch
        flat["train.accum_steps"] = self.accum_steps
        flat["train.accum_phase"] = self.accum_phase
        if self.accum_phase:
            # mid-accumulation-window: the half-summed gradients are
            # live state — capture them or the window replays wrong
            for name, p in self._named_params():
                if p.grad is not None:
                    flat[f"grad.{name}"] = p.grad
        return flat

    # -- restore -----------------------------------------------------------
    def load_state_dict(self, flat):
        from ..core.tensor import Tensor

        if self.model is not None:
            sub = {
                k[len("model."):]: v
                for k, v in flat.items() if k.startswith("model.")
            }
            missing, _unexpected = self.model.set_state_dict(sub)
            if missing:
                raise ValueError(
                    "checkpoint is missing model entries (bit-exact "
                    f"resume impossible): {missing}"
                )
        if self.optimizer is not None:
            sub = {
                k[len("opt."):]: v
                for k, v in flat.items() if k.startswith("opt.")
            }
            self.optimizer.set_state_dict(sub)
        if self.scaler is not None and flat.get("scaler") is not None:
            self.scaler.load_state_dict(dict(flat["scaler"]))
        if self.dataloader is not None and flat.get("data") is not None:
            self.dataloader.load_state_dict(dict(flat["data"]))
        _restore_rng(flat)
        self.step = int(flat.get("train.step", self.step))
        self.epoch = int(flat.get("train.epoch", self.epoch))
        self.accum_steps = int(
            flat.get("train.accum_steps", self.accum_steps)
        )
        self.accum_phase = int(flat.get("train.accum_phase", 0))
        grads = {
            k[len("grad."):]: v
            for k, v in flat.items() if k.startswith("grad.")
        }
        if grads:
            for name, p in self._named_params():
                if name in grads:
                    src = grads[name]
                    arr = src._data if isinstance(src, Tensor) else src
                    p.grad = Tensor(arr, stop_gradient=True)
        return self

    # -- persistence (checkpoint format v2) --------------------------------
    def save(self, path, keep_last_k=2, emergency=False):
        """Persist through checkpoint v2: atomic, checksummed, verified
        before the ``latest`` pointer moves — an emergency checkpoint
        interrupted by the final SIGKILL can never become ``latest``."""
        from ..distributed import checkpoint as ckpt

        obs = _obs()
        t0 = time.perf_counter()
        with (obs.span("train.checkpoint", step=self.step,
                       emergency=emergency)
              if obs is not None else contextlib.nullcontext()):
            sd = self.state_dict()
            sd["train.emergency"] = bool(emergency)
            ckpt.save_state_dict(sd, path, keep_last_k=keep_last_k)
        dt = time.perf_counter() - t0
        if obs is not None:
            obs.metrics.histogram(
                "paddle_tpu_train_ckpt_seconds",
                "TrainState capture+save wall clock", ("kind",),
            ).observe(dt, kind="emergency" if emergency else "periodic")
            obs.flight.record(
                "train", "checkpoint", step=self.step,
                emergency=emergency, ms=round(dt * 1e3, 1),
            )
        return dt

    def load(self, path):
        """Restore from the newest verified checkpoint under ``path``.
        Raises FileNotFoundError when none exists (cold start) —
        callers distinguish 'first incarnation' from 'corrupt beyond
        recovery' (CheckpointCorruptError)."""
        from ..distributed import checkpoint as ckpt

        obs = _obs()
        reason = os.environ.get("PADDLE_RESTART_REASON", "cold")
        t0 = time.perf_counter()
        with (obs.span("train.resume", reason=reason)
              if obs is not None else contextlib.nullcontext()):
            flat = ckpt.load_full(path)
            self.load_state_dict(flat)
        dt = time.perf_counter() - t0
        if obs is not None:
            obs.metrics.counter(
                "paddle_tpu_train_resumes_total",
                "TrainState restores, by restart provenance",
                ("reason",),
            ).inc(reason=reason)
            obs.metrics.histogram(
                "paddle_tpu_train_resume_seconds",
                "TrainState load+restore wall clock",
            ).observe(dt)
            obs.flight.record(
                "train", "resume", step=self.step, reason=reason,
                ms=round(dt * 1e3, 1),
            )
        return self

    def try_load(self, path):
        """``load`` that treats 'no checkpoint yet' as a cold start;
        returns True when a checkpoint was restored."""
        try:
            self.load(path)
            return True
        except FileNotFoundError:
            return False


# -- preemption notice -------------------------------------------------------

# process-wide notice flag: set by signal handlers and by
# request_preemption() (cloud preemption notices arrive out-of-band)
_notice = threading.Event()


def request_preemption():
    """Programmatic preemption notice — equivalent to receiving
    SIGTERM. The train loop checkpoints at the next step boundary and
    exits PREEMPT_EXIT_CODE."""
    _notice.set()
    obs = _obs()
    if obs is not None:
        obs.flight.record("train", "preempt-notice", source="api")


def preemption_requested():
    return _notice.is_set()


class PreemptionHandler:
    """Signal -> notice-flag bridge. ``install()`` chains the previous
    handler (a framework must not eat a user's own SIGTERM hook);
    ``uninstall()`` restores it. Signal handlers only bind on the main
    thread — elsewhere install() is a no-op and only the programmatic
    notice works."""

    def __init__(self, signals=(signal.SIGTERM,)):
        self.signals = tuple(signals)
        self._previous = {}

    def _on_signal(self, signum, frame):
        _notice.set()
        obs = _obs()
        if obs is not None:
            obs.flight.record(
                "train", "preempt-notice", source=f"signal:{signum}"
            )
        prev = self._previous.get(signum)
        if callable(prev):
            prev(signum, frame)

    def install(self):
        # deliberately does NOT clear a pending notice: install() may
        # run while a live notice (e.g. from a cloud-notice poller
        # thread) is already set, and eating it would skip the
        # emergency checkpoint. The flag is consumed exactly where it
        # is honored — TrainLoop._emergency_exit.
        for s in self.signals:
            try:
                self._previous[s] = signal.signal(s, self._on_signal)
            except ValueError:  # not the main thread
                pass
        return self

    def uninstall(self):
        for s, prev in self._previous.items():
            try:
                signal.signal(s, prev if prev is not None
                              else signal.SIG_DFL)
            except ValueError:
                pass
        self._previous.clear()

    def requested(self):
        return _notice.is_set()

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False


# -- the elastic train loop --------------------------------------------------


class TrainLoop:
    """Preemption-safe, hang-safe step loop around a :class:`TrainState`.

    ``step_fn(batch, state)`` owns the actual work (forward, backward,
    optimizer step — and, if it accumulates, maintaining
    ``state.accum_phase``); the loop owns everything a preemptible pod
    needs around it:

    * automatic resume from ``ckpt_dir`` (cold starts just begin),
    * the ``train.step`` fault site (chaos harness hook),
    * periodic checkpoints every ``save_every`` steps,
    * SIGTERM / :func:`request_preemption` -> barrier-coordinated
      emergency checkpoint -> ``SystemExit(PREEMPT_EXIT_CODE)``,
    * optional ``CommWatchdog`` deadline per step: a stuck step exits
      ``HANG_EXIT_CODE`` after the watchdog's own postmortem dump and
      TCPStore abort propagation.

    Multi-rank coordination (``store=``, ``world=``): a preempted rank
    publishes the notice into the store so un-signalled peers join the
    same checkpoint barrier; the coordinator rank saves, everyone else
    waits at a second barrier so no rank exits before the checkpoint is
    published.
    """

    def __init__(self, state, step_fn, ckpt_dir, *, save_every=None,
                 keep_last_k=2, watchdog=None, step_timeout=None,
                 hang_grace=2.0, store=None, world=1, rank=0,
                 coordinator_rank=0, barrier_timeout=60.0,
                 store_poll_s=0.5, signals=(signal.SIGTERM,)):
        self.state = state
        self.step_fn = step_fn
        self.ckpt_dir = ckpt_dir
        self.save_every = save_every
        self.keep_last_k = keep_last_k
        self.watchdog = watchdog
        self.step_timeout = step_timeout
        self.hang_grace = float(hang_grace)
        self.store = store
        self.world = int(world)
        self.rank = int(rank)
        self.coordinator_rank = int(coordinator_rank)
        self.barrier_timeout = float(barrier_timeout)
        # floor on seconds between store-notice polls: the local signal
        # path stays per-step, but a blocking store RPC before EVERY
        # step would tax short steps; 0.5s is far inside any cloud
        # preemption grace window
        self.store_poll_s = float(store_poll_s)
        self._last_store_poll = 0.0
        # incarnation-scoped store keys: a persistent store cannot leak
        # the previous incarnation's notice/barriers into this one
        gen = os.environ.get("PADDLE_RESTART_COUNT", "0")
        self._notice_key = f"{PREEMPT_NOTICE_KEY}/{gen}"
        self._barrier_suffix = gen
        self._handler = PreemptionHandler(signals)
        self._hang_unwound = threading.Event()

    # -- preemption --------------------------------------------------------
    def _clear_stale_preempt_keys(self):
        """Belt over the generation-scoped keys' suspenders: a process
        that reuses an incarnation id with a persistent store (e.g.
        two in-process loops with no launcher, both gen 0) could still
        see its OWN previous notice — the coordinator clears this
        generation's keys before stepping, the same reset CommWatchdog
        applies to its ABORT_KEY. Cross-incarnation leaks are already
        impossible: the keys embed PADDLE_RESTART_COUNT."""
        if (self.store is None or self.world <= 1
                or self.rank != self.coordinator_rank):
            return
        for key in (
            self._notice_key,
            f"__barrier/__preempt_sync__/{self._barrier_suffix}",
            f"__barrier/__preempt_done__/{self._barrier_suffix}",
        ):
            try:
                self.store.delete_key(key)
            except Exception:
                # analysis: allow(broad-except) best-effort: a wedged
                # store must not block training startup; the notice
                # poll degrades the same way
                pass

    def _preempt_pending(self):
        if self._handler.requested():
            return True
        if self.store is not None and self.world > 1:
            now = time.monotonic()
            if now - self._last_store_poll < self.store_poll_s:
                return False
            self._last_store_poll = now
            try:
                return bool(
                    self.store.get(self._notice_key, wait=False)
                )
            except Exception:
                # analysis: allow(broad-except) a wedged store must not
                # turn the preemption poll into a crash; the local
                # signal path still works
                return False
        return False

    def _emergency_exit(self):
        # the notice is being HONORED — consume it, so a later loop in
        # this process does not instantly re-preempt on a flag whose
        # emergency checkpoint was already taken
        _notice.clear()
        obs = _obs()
        step = self.state.step
        if obs is not None:
            obs.metrics.counter(
                "paddle_tpu_train_preemptions_total",
                "preemption notices honored with an emergency checkpoint",
            ).inc()
        sys.stderr.write(
            f"[train] rank {self.rank}: preemption at step {step} — "
            "emergency checkpoint\n"
        )
        if self.store is not None and self.world > 1:
            try:
                self.store.set(
                    self._notice_key, f"rank{self.rank}@{step}"
                )
                # incarnation-scoped fixed barrier names: ranks can sit
                # one step apart when the notice lands, and an
                # incarnation preempts at most once (it exits below)
                self.store.barrier(
                    f"__preempt_sync__/{self._barrier_suffix}",
                    self.world, timeout=self.barrier_timeout,
                )
            except Exception as e:
                # analysis: allow(broad-except) peers may already be
                # dead; an un-coordinated emergency checkpoint is still
                # better than none
                sys.stderr.write(
                    f"[train] preempt barrier degraded: {e!r}\n"
                )
        if self.world == 1 or self.rank == self.coordinator_rank:
            dt = self.state.save(
                self.ckpt_dir, keep_last_k=self.keep_last_k,
                emergency=True,
            )
            sys.stderr.write(
                f"[train] emergency checkpoint saved in {dt*1e3:.0f}ms "
                f"(step {step})\n"
            )
        if self.store is not None and self.world > 1:
            try:  # nobody exits before the checkpoint is published
                self.store.barrier(
                    f"__preempt_done__/{self._barrier_suffix}",
                    self.world, timeout=self.barrier_timeout,
                )
            except Exception:
                # analysis: allow(broad-except) see preempt barrier above
                pass
        raise SystemExit(PREEMPT_EXIT_CODE)

    # -- hang handling -----------------------------------------------------
    def _on_hang(self, tag, why):
        """Runs ON THE WATCHDOG THREAD after its trip (stack dump,
        flight postmortem, TCPStore abort propagation are already
        done). ``interrupt_main`` only lands once the main thread
        returns to the interpreter — a step wedged inside a blocking
        runtime call never does — so after ``hang_grace`` seconds
        without a cooperative unwind, hard-exit with the
        provenance-readable code (the elastic launcher relaunches and
        resume takes over)."""
        import _thread

        _thread.interrupt_main()
        if self._hang_unwound.wait(self.hang_grace):
            return  # the main thread converted it to SystemExit itself
        sys.stderr.write(
            f"[train] rank {self.rank}: stuck step ({tag}: {why}) did "
            f"not unwind within {self.hang_grace}s — hard exit "
            f"{HANG_EXIT_CODE} for elastic relaunch\n"
        )
        sys.stderr.flush()
        os._exit(HANG_EXIT_CODE)

    def _run_step(self, batch):
        from . import faults

        faults.fire("train.step", step=self.state.step)
        if self.watchdog is None:
            return self.step_fn(batch, self.state)
        from ..distributed.watchdog import CommTimeoutError

        try:
            with self.watchdog.watch(
                "train.step", timeout=self.step_timeout
            ):
                return self.step_fn(batch, self.state)
        except (CommTimeoutError, KeyboardInterrupt) as e:
            if self.watchdog.fired is None:
                raise  # a genuine ctrl-C, not a watchdog trip
            # the watchdog already dumped the flight postmortem and
            # propagated the abort through the TCPStore; all that is
            # left is to die with a provenance-readable code
            self._hang_unwound.set()  # call off the hard-exit timer
            sys.stderr.write(
                f"[train] rank {self.rank}: step {self.state.step} "
                f"stuck ({e}) — exiting for elastic relaunch\n"
            )
            raise SystemExit(HANG_EXIT_CODE) from e

    # -- the loop ----------------------------------------------------------
    def _batches(self):
        if self.state.dataloader is None:
            while True:
                yield None
        else:
            yield from self.state.dataloader

    def run(self, max_steps):
        """Train until ``state.step == max_steps``; returns the state.
        Automatically resumes from ``ckpt_dir`` when a verified
        checkpoint exists."""
        obs = _obs()
        state = self.state
        # NO _notice.clear() here: a live notice that arrived before
        # run() (e.g. a cloud-notice poller during bootstrap) must be
        # honored with an emergency checkpoint at the first boundary.
        # Staleness is handled at the source — _emergency_exit consumes
        # the flag when it honors it. The handler is installed before
        # the (possibly long) restore so a SIGTERM arriving mid-restore
        # becomes an orderly emergency exit, not process death.
        self._clear_stale_preempt_keys()
        self._handler.install()
        hooked_watchdog = False
        try:
            resumed = state.try_load(self.ckpt_dir)
            if resumed:
                sys.stderr.write(
                    f"[train] rank {self.rank}: resumed at step "
                    f"{state.step} (epoch {state.epoch})\n"
                )
            steps_total = None
            if obs is not None:
                steps_total = obs.metrics.counter(
                    "paddle_tpu_train_steps_total",
                    "train steps completed by the elastic train loop",
                )
            self._sync_epoch()
            if (self.watchdog is not None
                    and self.watchdog._on_timeout is None):
                # default watchdog trips interrupt the main thread,
                # which a wedged runtime call never observes; take the
                # trip hook so a true hang hard-exits after the
                # cooperative grace
                self.watchdog._on_timeout = self._on_hang
                hooked_watchdog = True
            while state.step < max_steps:
                progressed = False
                # a resume cursor that already consumed the WHOLE epoch
                # (preemption landed on the epoch boundary) yields an
                # empty iterator — that is an epoch rollover, not an
                # empty dataset
                resumed_past_epoch = bool(
                    getattr(state.dataloader, "_resume_skip", 0)
                )
                batches = self._batches()
                while True:
                    # the preemption check runs BEFORE the next batch
                    # is pulled: pulling advances the dataloader's
                    # served-batch cursor, and an emergency checkpoint
                    # must not count a batch the step never trained on
                    if self._preempt_pending():
                        self._emergency_exit()
                    try:
                        batch = next(batches)
                    except StopIteration:
                        break
                    self._run_step(batch)
                    progressed = True
                    state.step += 1
                    if steps_total is not None:
                        steps_total.inc()
                    if (self.save_every
                            and state.step % self.save_every == 0
                            and (self.world == 1
                                 or self.rank == self.coordinator_rank)):
                        # periodic saves are coordinator-only, like the
                        # emergency path: every rank writing the shared
                        # dir would leave `latest` on an arbitrary
                        # rank's RNG streams
                        state.save(
                            self.ckpt_dir, keep_last_k=self.keep_last_k,
                        )
                    if state.step >= max_steps:
                        return state
                if (not progressed and state.dataloader is not None
                        and not resumed_past_epoch):
                    raise RuntimeError(
                        "dataloader yielded no batches; cannot reach "
                        f"step {max_steps} from {state.step}"
                    )
                state.epoch += 1
                self._sync_epoch()
            return state
        finally:
            self._handler.uninstall()
            if hooked_watchdog:
                self.watchdog._on_timeout = None

    def _sync_epoch(self):
        dl = self.state.dataloader
        sampler = getattr(dl, "batch_sampler", None)
        if sampler is not None and hasattr(sampler, "set_epoch"):
            sampler.set_epoch(self.state.epoch)
