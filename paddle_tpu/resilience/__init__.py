"""paddle_tpu.resilience — failure is a first-class event.

Three pieces shared by every subsystem (see docs/resilience.md):

  * ``faults`` — a deterministic fault-injection registry. Product code
    declares named sites (``faults.fire("store.rpc", op=...)``); tests
    activate seeded schedules via ``faults.inject({...})`` and assert
    the recovery path actually runs.
  * ``RetryPolicy`` — the unified exponential-backoff/jitter/deadline
    retry loop used by TCPStore, distributed.rpc, and shard_loader.
  * checkpoint hardening, serving degradation, and dataloader shutdown
    escalation live in their own subsystems but are built on the two
    primitives above.
"""
from . import faults
from .faults import FaultInjector, FaultSpec
from .retry import RetryPolicy, retry_call

__all__ = [
    "faults", "FaultSpec", "FaultInjector", "RetryPolicy", "retry_call",
]
