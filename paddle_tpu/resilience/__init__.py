"""paddle_tpu.resilience — failure is a first-class event.

Three pieces shared by every subsystem (see docs/resilience.md):

  * ``faults`` — a deterministic fault-injection registry. Product code
    declares named sites (``faults.fire("store.rpc", op=...)``); tests
    activate seeded schedules via ``faults.inject({...})`` and assert
    the recovery path actually runs.
  * ``RetryPolicy`` — the unified exponential-backoff/jitter/deadline
    retry loop used by TCPStore, distributed.rpc, and shard_loader.
  * ``train_state`` — the bit-exact training resume contract:
    ``TrainState`` capture/restore (model + optimizer + LR + AMP +
    grad-accum phase + all RNG streams + dataloader cursor), the
    preemption exit-code protocol with the elastic launcher, and the
    hang-safe ``TrainLoop``.
  * checkpoint hardening, serving degradation, and dataloader shutdown
    escalation live in their own subsystems but are built on the
    primitives above.
"""
from . import faults, train_state
from .faults import FaultInjector, FaultSpec
from .retry import RetryPolicy, retry_call
from .train_state import (
    HANG_EXIT_CODE,
    PREEMPT_EXIT_CODE,
    PreemptionHandler,
    TrainLoop,
    TrainState,
    preemption_requested,
    request_preemption,
)

__all__ = [
    "faults", "FaultSpec", "FaultInjector", "RetryPolicy", "retry_call",
    "train_state", "TrainState", "TrainLoop", "PreemptionHandler",
    "request_preemption", "preemption_requested", "PREEMPT_EXIT_CODE",
    "HANG_EXIT_CODE",
]
