"""Unified retry policy: exponential backoff + jitter + deadline.

One policy object replaces the ad-hoc per-call-site retry loops that had
grown around the coordination plane (TCPStore._rpc reconnect-once,
TCPStore._connect poll loop, rpc connection establishment). Semantics:

  * attempt 1 runs immediately; attempt k sleeps
    ``min(base * multiplier**(k-2), max_delay) * (1 ± jitter)`` first
  * only exceptions in ``retry_on`` are retried — anything else
    propagates immediately (a server-side error is not a transient)
  * the overall ``deadline`` (seconds of wall clock from the first
    attempt) caps total time: once exceeded, the last exception is
    re-raised even if attempts remain
  * ``max_attempts=None`` retries until the deadline alone

Jitter is drawn from ``random.Random(seed)`` when a seed is given, so
tests are deterministic; ``sleep`` is injectable for zero-wall-clock
tests.
"""
from __future__ import annotations

import random
import time

__all__ = ["RetryPolicy", "retry_call"]


class RetryPolicy:
    def __init__(self, max_attempts=5, base_delay=0.05, max_delay=2.0,
                 multiplier=2.0, jitter=0.1, deadline=None,
                 retry_on=(ConnectionError, TimeoutError, OSError),
                 seed=None, sleep=time.sleep, clock=time.monotonic):
        if max_attempts is not None and max_attempts < 1:
            raise ValueError("max_attempts must be >= 1 or None")
        if max_attempts is None and deadline is None:
            raise ValueError(
                "unbounded retries need a deadline (max_attempts=None "
                "requires deadline)"
            )
        if not 0.0 <= jitter <= 1.0:
            raise ValueError(f"jitter must be in [0, 1], got {jitter}")
        self.max_attempts = max_attempts
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.deadline = deadline
        self.retry_on = tuple(retry_on)
        self._rng = random.Random(seed) if seed is not None else random
        self._sleep = sleep
        self._clock = clock

    def delay(self, attempt):
        """Backoff before attempt number ``attempt`` (2-indexed: the
        first retry)."""
        d = min(
            self.base_delay * self.multiplier ** (attempt - 2),
            self.max_delay,
        )
        if self.jitter:
            d *= 1.0 + self._rng.uniform(-self.jitter, self.jitter)
        return max(0.0, d)

    def pause(self, attempt):
        """Sleep this policy's backoff for ``attempt`` (2-indexed like
        :meth:`delay`) — for call sites that own their loop but want
        the policy's backoff curve (e.g. the serving shed-retry loop).
        Returns the seconds slept."""
        d = self.delay(attempt)
        self._sleep(d)
        return d

    def call(self, fn, *args, on_retry=None, **kwargs):
        """Run ``fn(*args, **kwargs)`` under this policy. ``on_retry``
        (exc, attempt) is invoked before each backoff sleep — call sites
        use it to reset connections."""
        start = self._clock()
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn(*args, **kwargs)
            except self.retry_on as e:
                out_of_attempts = (
                    self.max_attempts is not None
                    and attempt >= self.max_attempts
                )
                pause = self.delay(attempt + 1)
                past_deadline = (
                    self.deadline is not None
                    and self._clock() - start + pause > self.deadline
                )
                if out_of_attempts or past_deadline:
                    raise
                _count_retry(fn, e)
                if on_retry is not None:
                    on_retry(e, attempt)
                self._sleep(pause)


def _count_retry(fn, exc):
    """Telemetry: every retried attempt lands in
    ``paddle_tpu_resilience_retries_total{fn,exc}`` — a fleet whose
    coordination plane is silently retrying its way through flakiness
    should show it on a dashboard before it becomes an outage. Lazy
    import (retry loads before observability in the package graph) and
    best-effort: counting must never break the retry."""
    try:
        from ..observability import metrics

        metrics.counter(
            "paddle_tpu_resilience_retries_total",
            "retried attempts under RetryPolicy", ("fn", "exc"),
        ).inc(
            fn=getattr(fn, "__name__", "call"),
            exc=type(exc).__name__,
        )
    except Exception:
        # analysis: allow(broad-except) telemetry is best-effort; the
        # backoff/retry semantics must be unaffected by a counting
        # failure
        pass


def retry_call(fn, *args, policy=None, **kwargs):
    """Convenience: run under ``policy`` (or a default RetryPolicy)."""
    return (policy or RetryPolicy()).call(fn, *args, **kwargs)
