"""Deterministic fault-injection registry.

Capability target: the reference runtime treats failure as a first-class
event (CommTaskManager times out hung collectives and propagates aborts
through the TCPStore); this module makes every recovery path TESTABLE by
letting a test schedule faults at named sites inside product code and
assert the system recovers.

Product code declares a site with one cheap call:

    from paddle_tpu.resilience import faults
    faults.fire("ckpt.write", file="data.npz")       # no-op when inactive

Tests activate a seeded schedule with a context manager:

    spec = faults.FaultSpec(OSError("disk full"), at=3)
    with faults.inject({"ckpt.write": spec}) as inj:
        ...                                          # 3rd write raises
    assert inj.fired["ckpt.write"] == 1

Named sites instrumented in this repo (the catalog lives in
docs/resilience.md):

    store.rpc          one TCPStore client RPC attempt (per try)
    store.connect      one TCPStore (re)connection attempt
    rpc.call           distributed.rpc outbound connection
    ckpt.write         one checkpoint file write (context: file=)
    serving.step       one engine prefill/decode launch (context:
                       phase=, request_id=/request_ids=)
    serving.replica    one fleet replica lifecycle event (context:
                       replica=, phase= "spawn"/"restart"/"step") —
                       phase="step" fires before the engine step, so an
                       injected death lands on a step boundary where
                       the recompute-preemption KV invariant holds
    fleet.route        one fleet request placement attempt (context:
                       request_id=, replica=) — routing failures must
                       degrade to a retry on the next fleet step, never
                       to a dropped request
    journal.append     one request-journal flush (context: path=,
                       records=) — append failures degrade to a
                       warning + paddle_tpu_serving_journal_* counters
                       (records dropped, serving continues), never to
                       a fatal
    journal.replay     one request-journal replay at engine/fleet
                       build (context: path=) — a replay failure
                       degrades to an empty recovery (warn + counter),
                       never blocks serving
    dataloader.worker  one process-worker job (context: worker_id=)
    train.step         one elastic-train-loop step, fired BEFORE the
                       step body (context: step=) — the chaos hook the
                       bit-exact resume contract is verified through:
                       an injected death lands on a step boundary,
                       where TrainState capture/restore is exact
    collective         one watched eager collective (context: op=)
    analysis.pass      one static-analyzer pass invocation (context:
                       rule=) — lets tests assert a crashing analyzer
                       degrades (check="warn") instead of killing the
                       caller
    analysis.compiled  one compiled-program (L3) analysis pass
                       invocation (context: rule=, program=) — a
                       crashing census/memory pass degrades to a
                       warned ``pass-crash`` finding in collect mode,
                       so an engine build with
                       ``device_memory_budget=`` set survives an L3
                       crash instead of failing to construct
    obs.export         one observability exporter invocation (context:
                       what= "scrape"/"healthz"/"flight"/
                       "chrome_trace") — exporter/scrape failures must
                       degrade to a logged warning, never crash the
                       training or serving they observe
    obs.stepstats      one serving step-observatory sample (context:
                       engine=), fired at the step tail before the
                       sample folds — a crashing sampler warns once
                       and disables itself (the engine drops its
                       StepStats; the weakref collector view follows),
                       never perturbing the step that carried it
    kv.spill           one KV-block spill to the host tier (context:
                       key=, cls= "prefix"/"request", nbytes=) — an
                       injected failure degrades to the old
                       destructive path (the block is freed, the
                       request recomputes at resume; warn-once +
                       spill_errors counter), never fatal
    kv.restore         one KV-block fetch from the host tier (context:
                       key=) — an injected failure degrades to the
                       recompute path the spill replaced (warn-once +
                       restore_errors counter, no block leak), never
                       fatal

Every injected fault is itself telemetry: the moment a spec fires it is
counted in ``paddle_tpu_resilience_fault_fires_total{site}`` and logged
to the observability flight recorder, so a postmortem shows which
injected (or test-scheduled) faults preceded the failure.

Schedules are deterministic: occurrence-number triggers (``at``/
``every``) count ``fire()`` calls per site per injector, and the
probabilistic mode draws from ``random.Random(hash((seed, site)))`` —
the same seed always injects the same faults. Specs are inherited by
fork-spawned children (the registry is plain module state), which is how
dataloader worker faults reach the worker process.
"""
from __future__ import annotations

import random
import threading
import time

__all__ = ["FaultSpec", "FaultInjector", "inject", "fire", "is_active"]


class FaultSpec:
    """One fault schedule for one site.

    exc:    exception instance, class, or zero-arg factory raised on a
            matching occurrence (ignored when ``action`` is given).
    at:     1-indexed occurrence number(s) that fault; int or iterable.
    every:  fault every Nth occurrence (1 = every call).
    p:      probability a given occurrence faults (seeded, see module
            docstring). Exactly one of at/every/p should be set; with
            none set, EVERY occurrence faults.
    when:   optional predicate over the fire() context kwargs; a
            non-matching call neither counts nor faults.
    max_fires: stop injecting after this many faults (None = unbounded).
    delay:  sleep this many seconds before raising (latency injection).
    action: optional callable(context) run INSTEAD of raising — e.g. a
            dataloader test hangs the worker with an action that masks
            SIGTERM and sleeps.
    """

    def __init__(self, exc=OSError, at=None, every=None, p=None,
                 when=None, max_fires=None, delay=0.0, action=None):
        self.exc = exc
        if at is None:
            self.at = None
        else:
            self.at = frozenset(
                (at,) if isinstance(at, int) else tuple(at)
            )
        self.every = every
        self.p = p
        self.when = when
        self.max_fires = max_fires
        self.delay = float(delay)
        self.action = action
        if sum(x is not None for x in (self.at, every, p)) > 1:
            raise ValueError("set at most one of at/every/p")

    def _matches(self, count, rng):
        if self.at is not None:
            return count in self.at
        if self.every is not None:
            return count % self.every == 0
        if self.p is not None:
            return rng.random() < self.p
        return True

    def _raise(self, site, context):
        if self.delay:
            time.sleep(self.delay)
        if self.action is not None:
            self.action(context)
            return
        exc = self.exc
        if isinstance(exc, type) or callable(exc) and not isinstance(
            exc, BaseException
        ):
            exc = exc()
        if not isinstance(exc, BaseException):
            raise TypeError(f"FaultSpec.exc for {site!r} is not raisable")
        raise exc


class FaultInjector:
    """Context manager holding active specs + per-site accounting.

    ``hits[site]``  — fire() calls that matched the spec's ``when``
    ``fired[site]`` — faults actually injected
    """

    def __init__(self, specs, seed=0):
        self.specs = {
            site: list(sl) if isinstance(sl, (list, tuple)) else [sl]
            for site, sl in specs.items()
        }
        self.seed = seed
        self.hits: dict = {}
        self.fired: dict = {}
        self._counts: dict = {}
        self._nfired: dict = {}
        self._rngs: dict = {}
        self._lock = threading.Lock()

    def _rng(self, site):
        if site not in self._rngs:
            self._rngs[site] = random.Random(f"{self.seed}:{site}")
        return self._rngs[site]

    def _fire(self, site, context):
        specs = self.specs.get(site)
        if not specs:
            return
        with self._lock:
            for i, spec in enumerate(specs):
                if spec.when is not None and not spec.when(context):
                    continue
                key = (site, i)
                self._counts[key] = count = self._counts.get(key, 0) + 1
                self.hits[site] = self.hits.get(site, 0) + 1
                if (spec.max_fires is not None
                        and self._nfired.get(key, 0) >= spec.max_fires):
                    continue
                if spec._matches(count, self._rng(site)):
                    self._nfired[key] = self._nfired.get(key, 0) + 1
                    self.fired[site] = self.fired.get(site, 0) + 1
                    break
            else:
                return
        _record_fire(site, context)
        # raise outside the lock: handlers may re-enter fire()
        spec._raise(site, context)

    def __enter__(self):
        _stack.append(self)
        return self

    def __exit__(self, *exc):
        try:
            _stack.remove(self)
        except ValueError:
            pass
        return False


def _record_fire(site, context):
    """Telemetry for an injected fault (counter + flight-recorder
    event). Lazy import: resilience loads before observability in the
    package graph, and a fork-inherited worker may fire before either
    is imported. Telemetry must never break the injection itself."""
    try:
        from ..observability import flight, metrics

        metrics.counter(
            "paddle_tpu_resilience_fault_fires_total",
            "injected faults actually fired, by site", ("site",),
        ).inc(site=site)
        flight.record(
            "fault", site,
            **{k: repr(v) for k, v in context.items()},
        )
    except Exception:
        # analysis: allow(broad-except) telemetry is best-effort here;
        # the scheduled fault must still raise even if recording fails
        pass


# Active injectors, innermost last. Plain module state on purpose: fork
# inheritance carries schedules into dataloader worker processes.
_stack: list = []


def inject(specs, seed=0):
    """``with faults.inject({"site": FaultSpec(...)}) as inj:`` —
    activate a schedule for the dynamic extent of the block."""
    return FaultInjector(specs, seed=seed)


def is_active():
    return bool(_stack)


def fire(site, **context):
    """Product-code fault point. Free when no injector is active; under
    an active schedule, raises/acts per the matching FaultSpec."""
    if not _stack:
        return
    for inj in reversed(_stack):
        inj._fire(site, context)
