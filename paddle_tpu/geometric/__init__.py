"""paddle.geometric analogue (ref: python/paddle/geometric — message
passing send_u_recv/send_ue_recv/segment ops over
phi/kernels/gpu/send_u_recv_kernel.cu, segment_pool kernels).

TPU-first: gather + jax.ops.segment_{sum,max,min} — XLA lowers segment
reductions to sorted-scatter programs; static num_segments (dst node
count) keeps shapes compile-friendly.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core import dispatch
from ..core.tensor import Tensor

__all__ = [
    "send_u_recv", "send_ue_recv", "segment_sum", "segment_mean",
    "segment_max", "segment_min",
]


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _segment_reduce(data, seg_ids, num_segments, pool):
    if pool in ("sum", "add"):
        return jax.ops.segment_sum(data, seg_ids, num_segments)
    cnt_shape = (-1,) + (1,) * (data.ndim - 1)
    cnt = jax.ops.segment_sum(
        jnp.ones((data.shape[0],), data.dtype), seg_ids, num_segments
    ).reshape(cnt_shape)
    if pool == "mean":
        s = jax.ops.segment_sum(data, seg_ids, num_segments)
        return s / jnp.maximum(cnt, 1.0)
    if pool in ("max", "min"):
        red = (
            jax.ops.segment_max if pool == "max" else jax.ops.segment_min
        )(data, seg_ids, num_segments)
        # reference semantics (phi graph_send_recv/segment_pool kernels):
        # rows receiving no message are 0, not +-inf
        return jnp.where(cnt > 0, red, jnp.zeros_like(red))
    raise ValueError(f"unknown pool_type {pool!r}")


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x[src] and reduce onto dst (ref geometric/message_passing/
    send_recv.py send_u_recv). Differentiable w.r.t. x."""
    src = np.asarray(
        src_index.numpy() if isinstance(src_index, Tensor) else src_index
    ).astype(np.int32)
    dst = np.asarray(
        dst_index.numpy() if isinstance(dst_index, Tensor) else dst_index
    ).astype(np.int32)
    # reference API: out_size None or <= 0 means "use x's node count"
    n_out = (
        int(out_size) if out_size is not None and int(out_size) > 0
        else _arr(x).shape[0]
    )
    xt = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))

    def impl(xa):
        return _segment_reduce(xa[src], dst, n_out, reduce_op)

    return dispatch.call("send_u_recv", impl, (xt,), {})


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Node-feature x[src] combined with edge feature y per edge, reduced
    onto dst (ref send_recv.py send_ue_recv)."""
    src = np.asarray(
        src_index.numpy() if isinstance(src_index, Tensor) else src_index
    ).astype(np.int32)
    dst = np.asarray(
        dst_index.numpy() if isinstance(dst_index, Tensor) else dst_index
    ).astype(np.int32)
    n_out = (
        int(out_size) if out_size is not None and int(out_size) > 0
        else _arr(x).shape[0]
    )
    xt = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    yt = y if isinstance(y, Tensor) else Tensor(jnp.asarray(y))

    def impl(xa, ya):
        msg = xa[src]
        if message_op == "add":
            msg = msg + ya
        elif message_op == "mul":
            msg = msg * ya
        else:
            raise ValueError(f"unknown message_op {message_op!r}")
        return _segment_reduce(msg, dst, n_out, reduce_op)

    return dispatch.call("send_ue_recv", impl, (xt, yt), {})


def _segment_api(pool):
    def fn(data, segment_ids, name=None):
        seg = np.asarray(
            segment_ids.numpy()
            if isinstance(segment_ids, Tensor) else segment_ids
        ).astype(np.int32)
        n = int(seg.max()) + 1 if seg.size else 0
        dt = data if isinstance(data, Tensor) else Tensor(jnp.asarray(data))

        def impl(da):
            return _segment_reduce(da, seg, n, pool)

        return dispatch.call(f"segment_{pool}", impl, (dt,), {})

    fn.__name__ = f"segment_{pool}"
    fn.__doc__ = (
        f"ref: python/paddle/geometric/math.py segment_{pool} "
        "(phi segment_pool kernels). Differentiable w.r.t. data."
    )
    return fn


segment_sum = _segment_api("sum")
segment_mean = _segment_api("mean")
segment_max = _segment_api("max")
segment_min = _segment_api("min")
