"""Replica supervision for the serving fleet.

One ``ReplicaSupervisor`` owns one ``Engine`` replica's lifecycle on
behalf of ``serving.fleet.Fleet``:

  * **spawn** — synchronous first launch. The replica's decode step is
    gated through ``Engine.check_decode`` before it may serve (unless
    the engine config already ran the gate): a fleet never launches a
    decode loop carrying host-sync or retrace findings.
  * **supervised stepping** — :meth:`step` fires the ``serving.replica``
    fault site (``phase="step"``) and forwards to ``Engine.step``; any
    exception that escapes is a replica death the fleet turns into a
    failover.
  * **quarantine + background restart** — after a death the fleet calls
    :meth:`quarantine` (the broken engine is dropped so its weights and
    KV pool can be reclaimed) and :meth:`start_restart`, which rebuilds
    the engine on a daemon thread under a ``resilience.RetryPolicy``.
    Each crash restart spends one unit of the ``max_restarts`` budget;
    exhausting the budget — or exhausting the retry policy within one
    restart — marks the replica permanently ``"failed"`` and the fleet
    shrinks around it.

States: ``offline`` → ``healthy`` ⇄ ``draining``; ``healthy`` →
``quarantined`` (dead, restart pending/in flight) → ``healthy`` or
``failed`` (terminal).

Every spawn/restart attempt fires ``serving.replica`` with
``phase="spawn"``/``"restart"``, so tests schedule deterministic
replica crashes and restart failures the same way they schedule any
other fault (docs/resilience.md site catalog).
"""
from __future__ import annotations

import threading

from ..resilience import faults
from ..resilience.retry import RetryPolicy

__all__ = ["ReplicaSupervisor"]


class ReplicaSupervisor:
    def __init__(self, name, factory, restart_policy=None, max_restarts=2,
                 analysis_check="error", devices=None, slice_index=None):
        self.name = name
        self._factory = factory
        # per-replica placement slice (serving.placement): the factory
        # closure already bakes these into EngineConfig(devices=), so a
        # crash restart — restart_policy.call(self._build, "restart")
        # re-invoking the SAME factory — rebuilds onto THIS slice, not
        # the fleet-wide shared list. Kept on the supervisor for
        # observability (Fleet.health(), replica-device gauges) and
        # slice bookkeeping (Fleet._free_slice_index).
        self.devices = None if devices is None else list(devices)
        self.slice_index = slice_index
        # restart attempts retry ANY exception: an engine build failure
        # has no transient/permanent signature the supervisor could
        # classify, and the restart budget bounds the total damage
        self.restart_policy = restart_policy or RetryPolicy(
            max_attempts=3, base_delay=0.05, max_delay=0.5,
            retry_on=(Exception,), seed=0,
        )
        self.max_restarts = int(max_restarts)
        self.analysis_check = analysis_check
        self.engine = None
        self.status = "offline"
        self.restarts = 0          # crash restarts consumed (budget)
        self.last_error = None
        self._lock = threading.Lock()
        self._restart_thread = None
        self._pending_engine = None
        self._restart_error = None
        # errored+timeout counter watermark for routable(): the
        # engine's "degraded" flag is cumulative (those counters never
        # reset), so admission gates on NEW errors since the last
        # observe_errors() sweep — one expired request must not
        # unroute a replica forever
        self._seen_errors = 0
        self._fresh_degraded = False

    def __repr__(self):
        return (
            f"ReplicaSupervisor({self.name!r}, status={self.status!r}, "
            f"restarts={self.restarts}/{self.max_restarts})"
        )

    # -- build / spawn -------------------------------------------------------
    def _build(self, phase):
        faults.fire("serving.replica", replica=self.name, phase=phase)
        engine = self._factory()
        if (self.analysis_check is not None
                and engine.config.analysis_check is None):
            # decode-loop gate (skipped only when the engine config
            # already ran it at _build_steps): host-sync/retrace
            # findings must keep a replica out of the fleet
            engine.check_decode(self.analysis_check)
        return engine

    def spawn(self):
        """Synchronous first launch (fleet construction / rolling
        restart)."""
        self.engine = self._build("spawn")
        self.status = "healthy"
        self._seen_errors = 0
        self._fresh_degraded = False
        return self.engine

    # -- serving -------------------------------------------------------------
    def step(self):
        """One supervised engine step; exceptions escape to the fleet's
        death handler. ``serving.replica``/``phase="step"`` is the
        deterministic kill site: it fires BEFORE the engine step, so an
        injected death always lands on a step boundary where the KV
        invariant (``num_cached`` = prompt + output[:-1]) holds — the
        state re-prefill recovery depends on."""
        faults.fire("serving.replica", replica=self.name, phase="step")
        return self.engine.step()

    def health(self):
        """The engine's health snapshot, or None when there is no live
        engine (quarantined/failed/offline)."""
        eng = self.engine
        if eng is None:
            return None
        try:
            return eng.health()
        except Exception:
            # analysis: allow(broad-except) a replica whose health
            # probe raises is unroutable, not a fleet crash
            return None

    def observe_errors(self):
        """Advance the error watermark — called by the fleet ONCE per
        scheduler step, and nowhere else. Separated from
        :meth:`routable` so that read paths (health scrapes,
        ``Fleet.health()``, repeated ``_pick_replica`` calls within one
        step) never consume the one-step "fresh degraded" admission
        gate."""
        eng = self.engine
        if eng is None:
            self._fresh_degraded = False
            return
        m = eng.metrics
        errors = m.requests_errored + m.requests_timeout
        self._fresh_degraded = errors > self._seen_errors
        self._seen_errors = errors

    def routable(self):
        """May this replica receive NEW requests? Healthy status AND a
        clean health snapshot: the ``overloaded`` flag, a tripped comm
        watchdog, or a fresh ``degraded`` signal (new poisoned/expired
        requests since the previous :meth:`observe_errors` sweep — the
        underlying counters are cumulative, and gating on their history
        would make one expired request unroute a replica forever) stops
        admission. Read-only: safe from any thread."""
        if self.status != "healthy" or self.engine is None:
            return False
        h = self.health()
        if h is None:
            return False
        if "overloaded" in h.get("flags", ()) or h["watchdog"]["fired"]:
            return False
        return not self._fresh_degraded

    def load(self):
        """Routing load: queued + running requests (least-loaded
        admission key)."""
        eng = self.engine
        if eng is None:
            return float("inf")
        return len(eng.waiting) + sum(
            r is not None for r in eng.slots
        )

    # -- death / restart -----------------------------------------------------
    def quarantine(self, exc):
        """Mark the replica dead and drop the broken engine (the fleet
        re-enqueues its in-flight requests FIRST — see
        ``Fleet._on_replica_death``)."""
        self.last_error = f"{type(exc).__name__}: {exc}"
        self.engine = None
        self.status = "quarantined"

    def start_restart(self):
        """Kick off a background rebuild under the retry policy.
        Returns False — and flips to ``"failed"`` — when the restart
        budget is already spent."""
        if self.restarts >= self.max_restarts:
            self.status = "failed"
            return False
        self.restarts += 1

        def run():
            try:
                engine = self.restart_policy.call(self._build, "restart")
            except Exception as e:
                # analysis: allow(broad-except) the restart thread's
                # only job is to report: ANY failure past the retry
                # policy means this replica is done
                with self._lock:
                    self._restart_error = e
                return
            with self._lock:
                self._pending_engine = engine

        self._restart_thread = threading.Thread(
            target=run, name=f"fleet-restart-{self.name}", daemon=True
        )
        self._restart_thread.start()
        return True

    def poll(self):
        """Adopt a finished background restart. Returns "recovered",
        "failed", or None (still restarting / nothing pending)."""
        with self._lock:
            engine = self._pending_engine
            error = self._restart_error
            self._pending_engine = self._restart_error = None
        if engine is not None:
            self.engine = engine
            self.status = "healthy"
            self._seen_errors = 0
            self._fresh_degraded = False
            return "recovered"
        if error is not None:
            self.last_error = f"{type(error).__name__}: {error}"
            self.status = "failed"
            return "failed"
        return None

    def join_restart(self, timeout=None):
        """Wait for an in-flight background restart thread (tests /
        rolling drains); returns True when no thread is still
        running. The result is adopted by the next :meth:`poll`."""
        t = self._restart_thread
        if t is None:
            return True
        t.join(timeout)
        return not t.is_alive()
