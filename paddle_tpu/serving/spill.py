"""Hierarchical KV spill tier: host-RAM block swap under the paged pool.

Device memory is the serving hard ceiling (the bench's single-chip
RESOURCE_EXHAUSTED wall), and before this module every memory-pressure
event was *destructive*: an LRU-evicted prefix-cache chain died, a
preempted request recomputed its whole KV from scratch (the goodput
ledger's ``preempt_recompute`` class), and an allocation failure was a
crash. :class:`HostSpillTier` turns all three into survivable
degradations by adding a host-RAM tier under the device pool:

  * **Prefix-chain spill** — ``PrefixCache`` eviction demotes full
    chain blocks here (keyed by chain digest) instead of freeing their
    bytes; a later chain match restores them into fresh pool blocks,
    byte-identical to the never-evicted path.
  * **Restore-instead-of-recompute preemption** — ``Engine._preempt``
    and ``Engine.release`` snapshot a victim's cached blocks here as
    ONE handle; re-admission writes them back and skips the re-prefill
    entirely. The handle key is journaled on the re-ADMIT record, so a
    crash replay can re-anchor against the disk tier.
  * **Disk third tier** — ``spill_dir=`` demotes host-LRU victims to
    ``.npz`` files (compile-cache style, content-keyed filenames), and
    serves misses from disk. Because prefix keys are content-derived
    chain digests, a fresh process pointed at the same directory finds
    the previous incarnation's warm chains with no journal involved.

Payloads are nested tuples of numpy arrays exactly as
``KVPool.read_block`` produces them (per layer, per k/v, per leaf —
``(pages,)`` or ``(pages, scales)``), captured per-shard via
``addressable_shards`` on sharded pools. Host numpy buffers stand in
for pinned allocations (the restore ``device_put`` path is identical;
a TPU build can swap the allocator without touching callers).

Degradation contract (docs/resilience.md): the fault sites ``kv.spill``
and ``kv.restore`` fire at the head of :meth:`HostSpillTier.put` /
:meth:`HostSpillTier.get`; an injected failure warns ONCE, counts, and
returns False/None — the caller falls back to the pre-spill behavior
(free-and-recompute), never crashes, never leaks a block.

Thread safety: the tier is mutated by the scheduler thread and read by
the metrics scrape thread (``stats()`` / the collector view), so every
entry-map and counter access holds ``self._lock``.
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
import warnings
import weakref
from collections import OrderedDict

from ..resilience import faults

__all__ = [
    "HostSpillTier", "is_resource_exhausted", "payload_nbytes",
    "register_spill_view",
]

# spill classes: what kind of state a key holds. "prefix" entries are
# chain-digest-keyed single blocks; "request" entries are whole-request
# handles (every cached block of one preempted/released request).
_CLASSES = ("prefix", "request")

# substrings that identify a backend out-of-memory failure across
# jax/XLA error flavors (XlaRuntimeError renders the gRPC status name)
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "out of memory", "OUT_OF_MEMORY")

# live tiers in this process, for same-host handle exchange: a fleet
# migration releases on one engine and resumes on another, and when
# both share the process their host RAM is one resource — the survivor
# may restore a handle the source tier holds. WeakSet: a dead engine's
# tier must not be pinned by the registry.
_TIERS: "weakref.WeakSet" = weakref.WeakSet()


def is_resource_exhausted(exc):
    """True when ``exc`` looks like a backend allocation failure —
    the trigger for the memory-pressure degradation ladder
    (reclaim -> spill colder blocks -> shed) instead of a crash."""
    text = f"{type(exc).__name__}: {exc}"
    return any(m in text for m in _OOM_MARKERS)


def payload_nbytes(payload):
    """Host bytes of one spill payload (a list of per-block snapshots
    from ``KVPool.read_block``)."""
    total = 0
    for snap in payload:
        for side in snap:                 # (k_layers, v_layers)
            for layer in side:            # per-layer leaf tuple
                for leaf in layer:
                    total += leaf.nbytes
    return total


class _SpillEntry:
    __slots__ = ("key", "cls", "payload", "nbytes", "signature",
                 "num_tokens")

    def __init__(self, key, cls, payload, nbytes, signature, num_tokens):
        self.key = key
        self.cls = cls
        self.payload = payload        # None when demoted to disk only
        self.nbytes = nbytes
        self.signature = signature
        self.num_tokens = num_tokens


class HostSpillTier:
    """Bounded host-RAM store of spilled KV blocks, its own LRU.

    ``capacity_bytes`` bounds the host payload bytes held at once;
    exceeding it evicts oldest entries first — to the ``spill_dir``
    disk tier when one is configured, otherwise they are dropped (the
    caller's recompute path still exists; the tier is an optimization,
    never the correctness story). Keys are plain strings
    (``"prefix:<digest-hex>"`` / ``"req:<rid>:<seq>"``) so they ride
    journal records unchanged; every entry carries the pool's
    ``block_signature()`` and a restore against a different layout is
    a miss, not a corruption.
    """

    def __init__(self, capacity_bytes, spill_dir=None, engine_id="0"):
        capacity_bytes = int(capacity_bytes)
        if capacity_bytes < 1:
            raise ValueError(
                f"host_spill_bytes must be >= 1, got {capacity_bytes}"
            )
        self.capacity_bytes = capacity_bytes
        self.spill_dir = str(spill_dir) if spill_dir is not None else None
        if self.spill_dir is not None:
            os.makedirs(self.spill_dir, exist_ok=True)
        self.engine_id = str(engine_id)
        self._lock = threading.Lock()
        self._entries: OrderedDict = OrderedDict()  # key -> _SpillEntry
        self._host_bytes = 0
        # counters (read by the scrape thread through stats())
        self.spilled_blocks = dict.fromkeys(_CLASSES, 0)
        self.spilled_bytes = dict.fromkeys(_CLASSES, 0)
        self.restored_blocks = dict.fromkeys(_CLASSES, 0)
        self.restored_bytes = dict.fromkeys(_CLASSES, 0)
        self.restore_hits = 0
        self.restore_misses = 0
        self.spill_errors = 0
        self.restore_errors = 0
        self.host_evictions = 0
        self.disk_writes = 0
        self.disk_reads = 0
        self.disk_errors = 0
        self.restore_seconds_total = 0.0
        self.restores = 0
        self._spill_warned = False
        self._restore_warned = False
        _TIERS.add(self)

    # -- core API ------------------------------------------------------------
    def put(self, key, payload, signature, num_tokens=0, cls="prefix"):
        """Admit one spill payload under ``key``. Returns True when the
        bytes are safely in the host (or disk) tier — only then may the
        caller treat the device blocks as restorable. False means the
        old destructive path applies (injected ``kv.spill`` fault, a
        payload larger than the whole budget with no disk tier, an
        unwritable disk tier): warn-once + counted, never raised."""
        nbytes = payload_nbytes(payload)
        try:
            faults.fire("kv.spill", key=key, cls=cls, nbytes=nbytes)
        except Exception as e:
            # analysis: allow(broad-except) the degradation contract:
            # an injected spill failure must fall back to the
            # free-and-recompute path, never crash the step
            self._degrade("spill", e)
            return False
        with self._lock:
            if nbytes > self.capacity_bytes and self.spill_dir is None:
                self.spill_errors += 1
                return False
            old = self._entries.pop(key, None)
            if old is not None and old.payload is not None:
                self._host_bytes -= old.nbytes
            entry = _SpillEntry(
                key, cls, payload, nbytes, signature, int(num_tokens)
            )
            if nbytes > self.capacity_bytes:
                # bigger than the whole host budget: straight to disk
                if not self._disk_write(entry):
                    self.spill_errors += 1
                    return False
                entry.payload = None
            else:
                self._host_bytes += nbytes
            self._entries[key] = entry
            self.spilled_blocks[cls] = (
                self.spilled_blocks.get(cls, 0) + len(payload)
            )
            self.spilled_bytes[cls] = (
                self.spilled_bytes.get(cls, 0) + nbytes
            )
            self._enforce_budget()
            return True

    def get(self, key, signature, pop=False):
        """Fetch a payload for restore. Returns the payload or None
        (miss / signature mismatch / injected ``kv.restore`` fault /
        unreadable disk entry) — None means the caller recomputes.
        Checks this tier (host then disk), then the other live tiers
        in the process (same-host migration hands a handle from the
        source engine's tier to the survivor's)."""
        try:
            faults.fire("kv.restore", key=key)
        except Exception as e:
            # analysis: allow(broad-except) the degradation contract:
            # an injected restore failure must fall back to the
            # recompute path, never crash admission
            self._degrade("restore", e)
            return None
        payload = self._get_local(key, signature, pop)
        if payload is None:
            for tier in list(_TIERS):
                if tier is self:
                    continue
                payload = tier._get_local(key, signature, pop)
                if payload is not None:
                    break
        with self._lock:
            if payload is None:
                self.restore_misses += 1
            else:
                self.restore_hits += 1
        return payload

    def has(self, key, signature):
        """Cheap restorability peek (no fault fire, no hit/miss
        accounting): does any live tier — or this tier's disk — hold
        ``key`` under a matching pool signature?"""
        if self._has_local(key, signature):
            return True
        return any(
            tier is not self and tier._has_local(key, signature)
            for tier in list(_TIERS)
        )

    def discard(self, key):
        """Drop ``key`` if held (host and disk); idempotent."""
        with self._lock:
            e = self._entries.pop(key, None)
            if e is not None and e.payload is not None:
                self._host_bytes -= e.nbytes
        self._disk_remove(key)

    def note_restored(self, cls, payload, seconds):
        """Book a COMPLETED restore (payload fetched AND written back
        into the pool) — restored blocks/bytes only count once the
        device write succeeded, so the counters never overstate."""
        nbytes = payload_nbytes(payload)
        with self._lock:
            self.restored_blocks[cls] = (
                self.restored_blocks.get(cls, 0) + len(payload)
            )
            self.restored_bytes[cls] = (
                self.restored_bytes.get(cls, 0) + nbytes
            )
            self.restore_seconds_total += float(seconds)
            self.restores += 1

    def note_restore_failure(self, cls):
        """A fetched payload failed its device write (OOM-degraded or
        torn): counted here so ``restore_errors`` covers both halves
        of the path."""
        with self._lock:
            self.restore_errors += 1

    def note_spill_failure(self, cls):
        """A device-side block read failed before ``put`` — counted so
        the spill error total covers the whole demotion path."""
        with self._lock:
            self.spill_errors += 1

    # -- internals -----------------------------------------------------------
    def _get_local(self, key, signature, pop):
        with self._lock:
            e = self._entries.get(key)
            if e is not None and e.signature != signature:
                return None
            if e is not None and e.payload is not None:
                payload = e.payload
                if pop:
                    self._entries.pop(key)
                    self._host_bytes -= e.nbytes
                    self._disk_remove(key)
                else:
                    self._entries.move_to_end(key)
                return payload
        # disk tier (entry demoted, or written by a dead incarnation)
        payload = self._disk_read(key, signature)
        if payload is not None and pop:
            self._disk_remove(key)
            with self._lock:
                e = self._entries.pop(key, None)
                if e is not None and e.payload is not None:
                    self._host_bytes -= e.nbytes
        return payload

    def _has_local(self, key, signature):
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                return e.signature == signature
        if self.spill_dir is None:
            return False
        return os.path.exists(self._disk_path(key))

    def _enforce_budget(self):
        """Caller holds the lock. Oldest-first host eviction down to
        ``capacity_bytes``; victims demote to disk when configured."""
        while self._host_bytes > self.capacity_bytes and self._entries:
            victim = None
            for key, e in self._entries.items():   # oldest first
                if e.payload is not None:
                    victim = key
                    break
            if victim is None:
                break
            e = self._entries[victim]
            if self.spill_dir is not None and self._disk_write(e):
                e.payload = None           # demoted, key stays findable
                self._entries.move_to_end(victim)
            else:
                self._entries.pop(victim)
            self._host_bytes -= e.nbytes
            self.host_evictions += 1

    def _degrade(self, stage, exc):
        with self._lock:
            if stage == "spill":
                self.spill_errors += 1
                warned, self._spill_warned = self._spill_warned, True
            else:
                self.restore_errors += 1
                warned, self._restore_warned = self._restore_warned, True
        if not warned:
            warnings.warn(
                f"[spill] kv.{stage} failed "
                f"({type(exc).__name__}: {exc}); degrading to the "
                "recompute path (warned once, counted in "
                f"{stage}_errors)",
                stacklevel=3,
            )

    # -- disk third tier -----------------------------------------------------
    def _disk_path(self, key):
        name = hashlib.sha256(key.encode()).hexdigest()[:32]
        return os.path.join(self.spill_dir, f"kv-{name}.npz")

    def _disk_write(self, entry):
        """Caller holds the lock (rare path: demotion/oversize only).
        compilecache-style: write to a temp name, rename into place —
        a SIGKILL mid-write leaves no half-entry under the real key."""
        if self.spill_dir is None or entry.payload is None:
            return False
        import numpy as np

        path = self._disk_path(entry.key)
        arrays = {}
        structure = []                 # per-block (k, v) leaf counts
        i = 0
        for snap in entry.payload:
            sides = []
            for side in snap:
                layers = []
                for layer in side:
                    layers.append(len(layer))
                    for leaf in layer:
                        arrays[f"a{i}"] = leaf
                        i += 1
                sides.append(layers)
            structure.append(sides)
        meta = json.dumps({
            "signature": entry.signature, "cls": entry.cls,
            "num_tokens": entry.num_tokens, "structure": structure,
        })
        tmp = f"{path}.tmp{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                np.savez(f, meta=np.frombuffer(
                    meta.encode(), dtype=np.uint8
                ), **arrays)
            os.replace(tmp, path)
            self.disk_writes += 1
            return True
        except Exception:
            # analysis: allow(broad-except) unwritable disk tier: the
            # entry just dies like it did before the tier existed
            self.disk_errors += 1
            try:
                os.remove(tmp)
            except OSError:
                pass
            return False

    def _disk_read(self, key, signature):
        if self.spill_dir is None:
            return None
        import numpy as np

        path = self._disk_path(key)
        if not os.path.exists(path):
            return None
        try:
            with np.load(path) as z:
                meta = json.loads(bytes(z["meta"]).decode())
                if meta["signature"] != signature:
                    return None
                payload = []
                i = 0
                for sides in meta["structure"]:
                    snap = []
                    for layers in sides:
                        side = []
                        for n in layers:
                            side.append(tuple(
                                z[f"a{i + j}"] for j in range(n)
                            ))
                            i += n
                        snap.append(tuple(side))
                    payload.append(tuple(snap))
            with self._lock:
                self.disk_reads += 1
            return payload
        except Exception:
            # analysis: allow(broad-except) a torn/alien file is a
            # miss (recompute path), never a crash
            with self._lock:
                self.disk_errors += 1
            return None

    def _disk_remove(self, key):
        if self.spill_dir is None:
            return
        try:
            os.remove(self._disk_path(key))
        except OSError:
            pass

    # -- introspection -------------------------------------------------------
    def disk_tokens(self, key):
        """Token count recorded with a disk entry (crash re-anchor
        uses the journaled count; this is the cross-check)."""
        if self.spill_dir is None:
            return None
        import numpy as np

        path = self._disk_path(key)
        if not os.path.exists(path):
            return None
        try:
            with np.load(path) as z:
                return json.loads(
                    bytes(z["meta"]).decode()
                ).get("num_tokens")
        except Exception:
            # analysis: allow(broad-except) introspection must mirror
            # _disk_read's miss-not-crash contract
            return None

    def stats(self):
        """Snapshot for ``Engine.health()`` / the collector view (one
        lock hold; every value is a plain number)."""
        with self._lock:
            hits, misses = self.restore_hits, self.restore_misses
            lookups = hits + misses
            return {
                "host_bytes": self._host_bytes,
                "host_capacity_bytes": self.capacity_bytes,
                "host_entries": sum(
                    1 for e in self._entries.values()
                    if e.payload is not None
                ),
                "disk_entries": sum(
                    1 for e in self._entries.values()
                    if e.payload is None
                ),
                "spilled_blocks": dict(self.spilled_blocks),
                "spilled_bytes": dict(self.spilled_bytes),
                "restored_blocks": dict(self.restored_blocks),
                "restored_bytes": dict(self.restored_bytes),
                "restore_hits": hits,
                "restore_misses": misses,
                "restore_hit_rate": (
                    hits / lookups if lookups else None
                ),
                "restore_ms_mean": (
                    1e3 * self.restore_seconds_total / self.restores
                    if self.restores else None
                ),
                "restores": self.restores,
                "restore_seconds_total": self.restore_seconds_total,
                "spill_errors": self.spill_errors,
                "restore_errors": self.restore_errors,
                "host_evictions": self.host_evictions,
                "disk_writes": self.disk_writes,
                "disk_reads": self.disk_reads,
                "disk_errors": self.disk_errors,
            }

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def clear(self):
        with self._lock:
            self._entries.clear()
            self._host_bytes = 0


def register_spill_view(tier, engine_id, registry=None):
    """Pull-time collector over one spill tier — the
    ``paddle_tpu_serving_spill_*`` family. Weakref: a collected
    engine's tier unregisters itself at the next scrape, mirroring
    EngineMetrics/StepStats views."""
    from ..observability import MetricFamily, get_registry

    ref = weakref.ref(tier)
    label = {"engine": str(engine_id)}

    def collect():
        t = ref()
        if t is None:
            return None
        s = t.stats()
        fams = [
            MetricFamily(
                "paddle_tpu_serving_spill_host_bytes", "gauge",
            ).add(s["host_bytes"], label),
            MetricFamily(
                "paddle_tpu_serving_spill_host_capacity_bytes", "gauge",
            ).add(s["host_capacity_bytes"], label),
            MetricFamily(
                "paddle_tpu_serving_spill_host_entries", "gauge",
            ).add(s["host_entries"], label),
        ]
        spilled_b = MetricFamily(
            "paddle_tpu_serving_spill_spilled_blocks_total", "counter",
        )
        spilled_y = MetricFamily(
            "paddle_tpu_serving_spill_spilled_bytes_total", "counter",
        )
        restored_b = MetricFamily(
            "paddle_tpu_serving_spill_restored_blocks_total", "counter",
        )
        restored_y = MetricFamily(
            "paddle_tpu_serving_spill_restored_bytes_total", "counter",
        )
        for cls in _CLASSES:
            cl = {**label, "class": cls}
            spilled_b.add(s["spilled_blocks"].get(cls, 0), cl)
            spilled_y.add(s["spilled_bytes"].get(cls, 0), cl)
            restored_b.add(s["restored_blocks"].get(cls, 0), cl)
            restored_y.add(s["restored_bytes"].get(cls, 0), cl)
        fams += [spilled_b, spilled_y, restored_b, restored_y]
        if s["restore_hit_rate"] is not None:
            fams.append(MetricFamily(
                "paddle_tpu_serving_spill_restore_hit_rate", "gauge",
            ).add(s["restore_hit_rate"], label))
        errors = MetricFamily(
            "paddle_tpu_serving_spill_errors_total", "counter",
        )
        errors.add(s["spill_errors"], {**label, "stage": "spill"})
        errors.add(s["restore_errors"], {**label, "stage": "restore"})
        fams.append(errors)
        return fams

    (registry or get_registry()).register_collector(
        f"serving.spill.{engine_id}", collect
    )
