"""paddle_tpu.serving — continuous-batching LLM serving with a paged KV
cache.

The multi-tenant layer over the single-stream decode path: ``generation``
gives one request a compiled decode loop; this package gives MANY requests
one fixed-shape compiled step (Orca-style iteration-level scheduling) over
a vLLM-style ref-counted block pool (``kv_cache``), with per-request
sampling (``request``/``sampler``, reusing ``generation.warp_logits``) and
engine counters pluggable into the profiler (``metrics``). See
docs/serving.md for the architecture walkthrough.

    from paddle_tpu import serving

    engine = serving.Engine(model, serving.EngineConfig(
        max_batch_slots=8, max_model_len=512, page_size=16))
    outs = engine.generate(prompt_token_lists,
                           serving.SamplingParams(max_new_tokens=64))
"""
from .access_log import AccessLog
from .adapter import LlamaServingAdapter, build_adapter
from .engine import Engine, EngineConfig, EngineOverloadedError
from .fleet import (
    Fleet,
    FleetConfig,
    FleetMetrics,
    FleetRequest,
    NoReplicaError,
)
from .journal import Journal, ReplayEntry
from .kv_cache import BlockManager, KVPool
from .metrics import EngineMetrics
from .placement import (
    Autoscaler,
    PlacementError,
    PlacementPlan,
    ScalingPolicy,
)
from .prefix_cache import PrefixCache, PrefixMatch
from .qos import (
    QoS,
    QoSConfig,
    QoSRejection,
    TenantPolicy,
    UnknownTenantError,
)
from .request import (
    Request,
    RequestOutput,
    RequestState,
    RequestTimeline,
    SamplingParams,
)
from .server import Server, serve
from .sharding import TPSpec, build_tp_mesh
from .spill import HostSpillTier
from .supervisor import ReplicaSupervisor

__all__ = [
    "Engine", "EngineConfig", "EngineOverloadedError", "SamplingParams",
    "Request", "RequestOutput", "RequestState", "RequestTimeline",
    "BlockManager", "KVPool",
    "EngineMetrics", "LlamaServingAdapter", "build_adapter",
    "PrefixCache", "PrefixMatch", "HostSpillTier",
    "Journal", "ReplayEntry", "AccessLog",
    "Fleet", "FleetConfig", "FleetMetrics", "FleetRequest",
    "NoReplicaError", "ReplicaSupervisor", "TPSpec", "build_tp_mesh",
    "PlacementPlan", "PlacementError", "ScalingPolicy", "Autoscaler",
    "Server", "serve", "QoS", "QoSConfig", "QoSRejection",
    "TenantPolicy", "UnknownTenantError",
]
