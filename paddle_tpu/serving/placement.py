"""Pod-scale device placement and elastic scaling policy for the Fleet.

``serving.sharding`` gave ONE replica a tensor-parallel slice; the
Fleet stacked every replica on the same slice (``EngineConfig.devices``
is fleet-wide), so dp=2 tp=2 "used" four chips while serving from two.
This module is the missing placement half:

  * :class:`PlacementPlan` — carves the visible device set into
    DISJOINT per-replica TP slices (DP = replica count, per-replica
    ``tp_degree``). Auto mode takes contiguous slices of ``tp_degree``
    in device-id order; explicit mode pins exact id lists per slice.
    Every way a plan cannot be realized — overlapping slices, more
    replicas than slices (oversubscription), a slice width that does
    not match the engine's ``tp_degree`` (indivisible) — raises ONE
    named error, :class:`PlacementError`, at config construction time
    instead of dying deep inside XLA mesh setup at first launch.

  * :class:`ScalingPolicy` — the elasticity envelope and hysteresis
    knobs: ``min_replicas``/``max_replicas`` bound the fleet size,
    ``up_hold_s``/``down_hold_s`` are how long the scale-up signal
    (sustained SLO burn, or pending depth >= ``up_pending``) and the
    idle signal must persist before acting, and ``cooldown_s`` is the
    refractory period after ANY scaling action — the three together
    are what keeps the fleet from flapping.

  * :class:`Autoscaler` — the pure decision engine the fleet ticks
    once per scheduler step. It holds only timestamps (when the fleet
    became hot / idle / last scaled) and returns ``"up"``, ``"down"``
    or ``None``; executing the decision (spawning onto a free slice,
    migrating work off a draining one) is the fleet's job, behind the
    degradable ``fleet.scale`` fault site.

See docs/serving.md "Elastic fleets" for the operator-facing
walkthrough.
"""
from __future__ import annotations

__all__ = [
    "Autoscaler", "PlacementError", "PlacementPlan", "ScalingPolicy",
]


class PlacementError(ValueError):
    """A device-placement plan that cannot be realized on this host:
    overlapping slices, oversubscribed replicas, or a slice width the
    engine's ``tp_degree`` does not match. Raised at config
    construction time — a bad plan must never reach XLA mesh setup."""


class PlacementPlan:
    """Disjoint per-replica TP slices over the visible device set.

    Auto mode (``PlacementPlan(tp_degree=2)``) carves contiguous
    slices of ``tp_degree`` device ids in visible-id order: slice i is
    ids ``[i*tp, (i+1)*tp)``. Explicit mode
    (``PlacementPlan(slices=[[0, 1], [4, 5]])``) pins exact id lists —
    e.g. to keep slices inside ICI domains. Replica index -> slice
    index is stable for the fleet's lifetime: a crash-restarted or
    rolling-restarted replica rebuilds onto ITS slice, and scale-up
    takes the lowest free slice.

    ``total_devices`` overrides the visible-device probe (tests,
    capacity planning off-host); ``None`` asks jax at validation time.
    """

    def __init__(self, tp_degree=None, slices=None, total_devices=None):
        if slices is None and tp_degree is None:
            raise PlacementError(
                "PlacementPlan needs tp_degree= (auto-carved slices) "
                "or slices= (explicit per-replica device ids)"
            )
        self.slices = None
        if slices is not None:
            self.slices = [list(s) for s in slices]
            if not self.slices:
                raise PlacementError(
                    "PlacementPlan(slices=) is empty: a plan must "
                    "provide at least one replica slice"
                )
            widths = {len(s) for s in self.slices}
            if len(widths) != 1:
                raise PlacementError(
                    f"PlacementPlan(slices=) mixes slice widths "
                    f"{sorted(widths)}: every replica shares one "
                    f"EngineConfig, so every slice must have exactly "
                    f"tp_degree devices"
                )
            inferred = widths.pop()
            if tp_degree is not None and int(tp_degree) != inferred:
                raise PlacementError(
                    f"PlacementPlan slices are {inferred} device(s) "
                    f"wide but tp_degree={tp_degree}: the slice width "
                    f"IS the replica's tensor-parallel degree"
                )
            tp_degree = inferred
            seen: dict = {}
            for i, s in enumerate(self.slices):
                for d in s:
                    if not isinstance(d, int) or d < 0:
                        raise PlacementError(
                            f"PlacementPlan slice {i} names device "
                            f"{d!r}: slices are lists of non-negative "
                            f"integer device ids"
                        )
                    if d in seen:
                        raise PlacementError(
                            f"PlacementPlan slices overlap: device "
                            f"{d} appears in slice {seen[d]} and "
                            f"slice {i} — per-replica slices must be "
                            f"disjoint"
                        )
                    seen[d] = i
        self.tp_degree = int(tp_degree)
        if self.tp_degree < 2:
            # EngineConfig(devices=) refuses tp_degree == 1 (a
            # single-chip engine runs on the process default device);
            # the plan inherits the same floor rather than producing
            # slices the engine cannot be placed on
            raise PlacementError(
                f"PlacementPlan needs tp_degree >= 2, got "
                f"{self.tp_degree}: single-chip engines run on the "
                f"process's default device and cannot be pinned "
                f"(EngineConfig(devices=) requires tp_degree > 1)"
            )
        self._total = (
            None if total_devices is None else int(total_devices)
        )

    def _visible(self):
        """Total devices the plan is judged against (cached after the
        first probe: the jax device set is fixed per process)."""
        if self._total is None:
            from .sharding import visible_device_ids

            self._total = len(visible_device_ids())
        return self._total

    def capacity(self):
        """How many replicas this plan can place (slice count)."""
        if self.slices is not None:
            return len(self.slices)
        return self._visible() // self.tp_degree

    def slice_ids(self, index):
        """Device ids of slice ``index`` (replica index -> chips)."""
        cap = self.capacity()
        if not 0 <= index < cap:
            raise PlacementError(
                f"placement slice {index} does not exist: the plan "
                f"holds {cap} slice(s) of {self.tp_degree} device(s)"
            )
        if self.slices is not None:
            return list(self.slices[index])
        start = index * self.tp_degree
        return list(range(start, start + self.tp_degree))

    def validate(self, num_replicas):
        """Raise :class:`PlacementError` unless ``num_replicas``
        replicas fit on this host — called at FleetConfig
        construction so a bad plan fails before any engine exists."""
        total = self._visible()
        cap = self.capacity()
        if num_replicas > cap:
            raise PlacementError(
                f"placement plan is oversubscribed: num_replicas="
                f"{num_replicas} replicas x tp_degree="
                f"{self.tp_degree} need "
                f"{num_replicas * self.tp_degree} devices but the "
                f"plan holds {cap} slice(s) over {total} visible "
                f"device(s)"
            )
        if self.slices is not None:
            bad = sorted(
                d for s in self.slices for d in s if d >= total
            )
            if bad:
                raise PlacementError(
                    f"placement plan names device id(s) {bad} but "
                    f"only {total} device(s) are visible (ids 0.."
                    f"{total - 1})"
                )
        return self

    def __repr__(self):
        if self.slices is not None:
            return f"PlacementPlan(slices={self.slices})"
        return f"PlacementPlan(tp_degree={self.tp_degree})"


class ScalingPolicy:
    """Elasticity envelope + hysteresis for :class:`Autoscaler`.

    ``min_replicas``/``max_replicas`` bound the fleet size
    (``max_replicas=None`` means the placement plan's capacity). The
    scale-up signal is sustained fleet-level SLO burn — the pooled
    ``sustained_burn`` predicate PR 12 exports — or, when
    ``up_pending`` is set, a parked backlog at/over that depth. The
    scale-down signal is a fleet that could drop a replica without
    feeling it: nothing parked, no burn, and total queued+running load
    at/below ``down_load_per_replica`` per REMAINING replica (the
    default 0.0 releases chips only when the fleet is fully idle).
    Signals must hold for ``up_hold_s``/``down_hold_s`` and every
    action is followed by ``cooldown_s`` of no action — hysteresis on
    both edges, so burn that flickers at the threshold never flaps the
    fleet."""

    def __init__(self, min_replicas=1, max_replicas=None,
                 up_hold_s=3.0, down_hold_s=30.0, cooldown_s=10.0,
                 up_pending=None, down_load_per_replica=0.0):
        if min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {min_replicas}"
            )
        if max_replicas is not None and max_replicas < min_replicas:
            raise ValueError(
                f"max_replicas={max_replicas} is below min_replicas="
                f"{min_replicas}"
            )
        for nm, v in (("up_hold_s", up_hold_s),
                      ("down_hold_s", down_hold_s),
                      ("cooldown_s", cooldown_s)):
            if v < 0:
                raise ValueError(f"{nm} must be >= 0, got {v}")
        if up_pending is not None and up_pending < 1:
            raise ValueError(
                f"up_pending must be >= 1 or None (burn-only scale "
                f"up), got {up_pending}"
            )
        if down_load_per_replica < 0:
            raise ValueError(
                f"down_load_per_replica must be >= 0, got "
                f"{down_load_per_replica}"
            )
        self.min_replicas = int(min_replicas)
        self.max_replicas = (
            None if max_replicas is None else int(max_replicas)
        )
        self.up_hold_s = float(up_hold_s)
        self.down_hold_s = float(down_hold_s)
        self.cooldown_s = float(cooldown_s)
        self.up_pending = (
            None if up_pending is None else int(up_pending)
        )
        self.down_load_per_replica = float(down_load_per_replica)


class Autoscaler:
    """The hysteresis state machine over one fleet's scaling signals.

    Pure host-side bookkeeping: :meth:`decide` is fed a snapshot
    (burning? pending depth? live replicas? load?) once per fleet
    step and returns ``"up"``, ``"down"`` or ``None``. It never
    touches the fleet — the caller executes (and may fail to execute)
    the decision, then reports back via :meth:`note_action` so the
    cooldown clock starts even for a failed attempt (a spawn that
    died must not be retried every step)."""

    def __init__(self, policy):
        if not isinstance(policy, ScalingPolicy):
            raise TypeError(
                f"Autoscaler needs a ScalingPolicy, got "
                f"{type(policy).__name__}"
            )
        self.policy = policy
        self._hot_since = None
        self._idle_since = None
        self._last_action = None

    def note_action(self, now):
        """Anchor the cooldown window and reset both hysteresis
        clocks (the fleet just changed shape: signals must re-earn
        their hold time against the new size)."""
        self._last_action = now
        self._hot_since = None
        self._idle_since = None

    def _cooling(self, now):
        return (self._last_action is not None
                and now - self._last_action < self.policy.cooldown_s)

    def decide(self, now, *, burning, pending, live, capacity,
               free_slice, load):
        """One tick. ``burning`` is the pooled sustained-burn
        predicate, ``pending`` the parked-request depth, ``live`` the
        non-failed replica count, ``capacity`` the placement plan's
        slice count, ``free_slice`` whether an unused slice exists,
        ``load`` total queued+running requests across live engines."""
        pol = self.policy
        max_r = (
            capacity if pol.max_replicas is None
            else min(pol.max_replicas, capacity)
        )
        hot = burning or (
            pol.up_pending is not None and pending >= pol.up_pending
        )
        idle = (
            not hot and pending == 0
            and load <= pol.down_load_per_replica * max(live - 1, 0)
        )
        if hot:
            if self._hot_since is None:
                self._hot_since = now
        else:
            self._hot_since = None
        if idle:
            if self._idle_since is None:
                self._idle_since = now
        else:
            self._idle_since = None
        if self._cooling(now):
            return None
        if live < pol.min_replicas and free_slice:
            # below the floor (permanent failures shrank the fleet):
            # recover capacity regardless of hold times
            return "up"
        if (hot and live < max_r and free_slice
                and now - self._hot_since >= pol.up_hold_s):
            return "up"
        if (idle and live > pol.min_replicas
                and now - self._idle_since >= pol.down_hold_s):
            return "down"
        return None
