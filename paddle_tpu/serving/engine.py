"""Continuous-batching LLM serving engine.

The Orca (OSDI '22) iteration-level scheduler on TPU-native constraints:
every XLA program must have a FIXED shape, so the batch is a static array
of ``max_batch_slots`` slots and occupancy is data, not shape — requests
join and leave mid-flight by mutating the slot arrays (tokens, positions,
block tables, active mask) while the compiled step is reused unchanged.
Two program families cover the whole serving loop after warmup (each in
a greedy-only and, when a sampled request is present, a with-sampler
variant — the mode is a static compile key, so an all-greedy fleet never
pays the vocab-wide sampling warp):

  * PREFILL: one prompt, padded to a length bucket
    (``jit.bucketing.next_bucket`` policy — at most len(buckets)
    compiles), writes the prompt's K/V into its pages and samples the
    first token.
  * DECODE: one token for every slot at once over the paged KV pool
    (``kv_cache.KVPool`` + per-request block tables), batched per-slot
    sampling (``sampler.sample_tokens``), one compile total.

Two more program families join the set when prefix caching or chunked
prefill is enabled (both bit-transparent to greedy outputs):

  * PREFILL_EXT: the bucketed prefill signature extended with a
    cache-length operand — continues a prompt whose first ``cache_len``
    tokens are already in the pages (an earlier chunk, or a shared
    prefix forked from the ``prefix_cache``), attending chunk tokens
    over the gathered page timeline in the exact ``_sdpa`` form the
    one-shot prefill uses (byte-identical logits and pages).
  * COW: copy one physical block (all layers) — the copy-on-write
    divergence step when a cache match's one-token-to-prefill cap cuts
    into the last shared block. One compile total.

And one more with speculative decoding (``speculate_tokens=K``):

  * VERIFY: score every greedy slot's K+1-token draft window (pending
    token + prompt-lookup drafts) in one launch and return per-position
    argmax targets; the engine accepts the longest target-matching
    draft prefix and emits accepted+1 tokens — byte-identical to plain
    greedy decode in up to (K+1)x fewer launches. One compile total;
    sampled slots keep the plain decode path.

Scheduling policy (host-side, cheap):
  * admission control — FCFS from the waiting queue into free slots,
    gated on KV blocks for the whole prompt plus one decode step;
    ``max_waiting`` bounds the queue. With the prefix cache enabled,
    the longest cached prompt prefix is matched at admission and its
    blocks are ``fork()``ed instead of allocated+recomputed; blocks
    whose only owner is the cache are reclaimed on demand before an
    admission is refused.
  * chunked prefill — ``prefill_chunk_tokens`` splits the remaining
    prompt into fixed-size chunks (padded through the same bucket set)
    and at most ``max_prefill_chunks_per_step`` chunks run per step,
    interleaved with the decode batch — one long prompt no longer
    stalls every running request for its whole prefill (Sarathi-style
    stall-free scheduling), bounding both TTFT and inter-token latency
    under mixed traffic.
  * block growth — each decode step first ensures every running request
    owns a block for the token it is about to write; on pool exhaustion
    the YOUNGEST running request is preempted (blocks freed, request
    requeued at the head). Preemption is recompute-style: the victim's
    tokens are kept and its cache is rebuilt by a later prefill over
    ``prompt + output[:-1]``, which restores its state exactly — greedy
    outputs are unchanged by preemption.

Engine counters live in ``metrics.EngineMetrics``; the compile counters
are incremented inside the traced step bodies, so they move only when XLA
actually retraces — the probe behind the no-recompile-after-warmup
guarantee.

Tensor parallelism (``EngineConfig(tp_degree=N, devices=)``,
serving/sharding.py): the same engine over N chips — weights sharded
col/row-wise and the KV pool's head dim split over a 1 x N mesh, every
program above still ONE single-launch SPMD program (GSPMD places the
collectives; the scheduler and every probe are chip-count-blind), with
``tp_numerics="exact"`` keeping outputs byte-identical to the
unsharded engine. ``tp_degree=1`` (default) is byte-identical to the
engine as it always was: no mesh, no placement, same jaxprs.
"""
from __future__ import annotations

import collections
import itertools
import time
import warnings

import jax
import jax.numpy as jnp
import numpy as np

from ..distributed.watchdog import CommTimeoutError, get_comm_watchdog
from ..jit.bucketing import next_bucket
from ..observability import flight as _flight
from ..observability import jit_events
from ..observability import register_health_provider, span
from ..observability import unregister_health_provider
from ..resilience import faults
from .access_log import record_finish
from .adapter import build_adapter
from .kv_cache import BlockManager, KVPool
from .metrics import EngineMetrics
from . import speculation
from .request import (
    Request,
    RequestOutput,
    RequestState,
    SamplingParams,
    normalize_sampling_params,
)
from .sampler import pack_sampling_params, sample_tokens

__all__ = ["Engine", "EngineConfig", "EngineOverloadedError"]


class EngineOverloadedError(RuntimeError):
    """add_request rejected under KV pressure (load shedding): the
    caller should back off / route elsewhere rather than deepen an
    already-saturated queue."""


# monotonic engine ids: id(self) gets reused by the allocator after an
# engine is collected, which would alias a fresh engine's probes,
# metric labels, and compile-log signatures onto a dead one's (a new
# engine's first compile must never read as a retrace alarm)
_engine_counter = itertools.count(1)


def _unregister_engine_probes(name):
    """weakref.finalize target: drop a collected engine's health
    provider and watchdog probe (module-level so the finalizer holds no
    reference back into the engine)."""
    unregister_health_provider(name)
    wd = get_comm_watchdog()
    if wd is not None and hasattr(wd, "unregister_probe"):
        wd.unregister_probe(name)


def _default_buckets(max_model_len):
    """Doubling ladder from 16 (or smaller) up to max_model_len."""
    buckets = []
    b = min(16, max_model_len)
    while b < max_model_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_model_len)
    return buckets


class EngineConfig:
    def __init__(self, max_batch_slots=8, max_model_len=2048, page_size=16,
                 num_blocks=None, prefill_buckets=None, max_waiting=None,
                 seed=0, kv_shed_threshold=None, analysis_check=None,
                 compile_cache=None, enable_prefix_cache=False,
                 prefix_cache_blocks=None, prefill_chunk_tokens=None,
                 max_prefill_chunks_per_step=1, speculate_tokens=None,
                 speculate_ngram=3, decode_kernel="auto",
                 kv_cache_dtype=None, journal=None, access_log=None,
                 slo=None, tp_degree=1, devices=None,
                 tp_numerics="exact", device_memory_budget=None,
                 stepstats=True, stepstats_ring=256,
                 host_spill_bytes=None, spill_dir=None):
        if max_batch_slots < 1:
            raise ValueError("max_batch_slots must be >= 1")
        if page_size < 1 or max_model_len < 2:
            raise ValueError("need page_size >= 1 and max_model_len >= 2")
        self.max_batch_slots = int(max_batch_slots)
        self.max_model_len = int(max_model_len)
        self.page_size = int(page_size)
        self.pages_per_seq = -(-self.max_model_len // self.page_size)
        self.num_blocks = int(
            num_blocks if num_blocks is not None
            else self.max_batch_slots * self.pages_per_seq
        )
        if self.num_blocks < self.pages_per_seq:
            raise ValueError(
                f"num_blocks ({self.num_blocks}) cannot hold even one "
                f"max-length request ({self.pages_per_seq} pages)"
            )
        self.prefill_buckets = sorted(
            int(b) for b in (prefill_buckets
                             or _default_buckets(self.max_model_len))
        )
        if self.prefill_buckets[-1] < self.max_model_len:
            raise ValueError(
                "largest prefill bucket must cover max_model_len "
                f"({self.prefill_buckets[-1]} < {self.max_model_len})"
            )
        if max_waiting is not None and max_waiting < 1:
            raise ValueError(
                f"max_waiting must be >= 1 or None (unbounded), got "
                f"{max_waiting}"
            )
        self.max_waiting = max_waiting
        if kv_shed_threshold is not None and not 0.0 < kv_shed_threshold <= 1.0:
            raise ValueError(
                f"kv_shed_threshold must be in (0, 1] or None, got "
                f"{kv_shed_threshold}"
            )
        # load shedding: when KV-pool utilization is at/above this
        # fraction AND the request cannot be admitted immediately,
        # add_request raises EngineOverloadedError instead of queueing
        self.kv_shed_threshold = kv_shed_threshold
        if analysis_check not in (None, "warn", "error"):
            raise ValueError(
                'analysis_check must be None, "warn" or "error", got '
                f"{analysis_check!r}"
            )
        # warmup gate: statically analyze the decode step at engine
        # build (paddle_tpu.analysis) and warn/raise on host-sync or
        # retrace findings — the static strengthening of the
        # compile-count probe
        self.analysis_check = analysis_check
        # persistent compile cache (paddle_tpu.compilecache): a path or
        # CompileCache. When set, the engine compiles its FULL program
        # set eagerly at build (every prefill bucket + the decode step),
        # serializes each executable to the cache, and records a warmup
        # manifest — so a restarting engine replays everything from disk
        # BEFORE accepting traffic, with zero fresh traces. None (the
        # default) keeps the lazy-compile behavior.
        self.compile_cache = compile_cache
        # automatic prefix caching (serving/prefix_cache.py): share
        # read-only prompt blocks across requests, retain them after
        # release under an LRU budget of prefix_cache_blocks entries
        # (None -> the whole pool is eligible)
        self.enable_prefix_cache = bool(enable_prefix_cache)
        if prefix_cache_blocks is not None and prefix_cache_blocks < 1:
            raise ValueError(
                f"prefix_cache_blocks must be >= 1 or None, got "
                f"{prefix_cache_blocks}"
            )
        self.prefix_cache_blocks = (
            int(prefix_cache_blocks) if prefix_cache_blocks is not None
            else self.num_blocks
        )
        # chunked prefill: None disables (a prompt prefills in one
        # launch, today's behavior); an int splits the remaining prompt
        # into chunks of that many tokens, each padded through the
        # prefill bucket set — pick a bucket size to avoid pad waste
        if prefill_chunk_tokens is not None:
            if prefill_chunk_tokens < 1:
                raise ValueError(
                    f"prefill_chunk_tokens must be >= 1 or None, got "
                    f"{prefill_chunk_tokens}"
                )
            if prefill_chunk_tokens > self.prefill_buckets[-1]:
                raise ValueError(
                    f"prefill_chunk_tokens ({prefill_chunk_tokens}) "
                    f"exceeds the largest prefill bucket "
                    f"({self.prefill_buckets[-1]})"
                )
        self.prefill_chunk_tokens = (
            None if prefill_chunk_tokens is None
            else int(prefill_chunk_tokens)
        )
        if max_prefill_chunks_per_step < 1:
            raise ValueError(
                f"max_prefill_chunks_per_step must be >= 1, got "
                f"{max_prefill_chunks_per_step}"
            )
        self.max_prefill_chunks_per_step = int(max_prefill_chunks_per_step)
        # speculative decoding: None disables (one decode launch = one
        # token, today's behavior); an int K routes greedy slots
        # through the VERIFY program — up to K prompt-lookup draft
        # tokens scored alongside the pending token in one launch, the
        # longest target-matching prefix accepted. Greedy outputs are
        # byte-identical either way; sampled slots keep the plain
        # decode path (and its key-stream discipline).
        if speculate_tokens is not None:
            if speculate_tokens < 1:
                raise ValueError(
                    f"speculate_tokens must be >= 1 or None (disabled), "
                    f"got {speculate_tokens}"
                )
            if speculate_tokens >= self.max_model_len:
                raise ValueError(
                    f"speculate_tokens ({speculate_tokens}) must be "
                    f"smaller than max_model_len ({self.max_model_len})"
                )
        self.speculate_tokens = (
            None if speculate_tokens is None else int(speculate_tokens)
        )
        if speculate_ngram < 1:
            raise ValueError(
                f"speculate_ngram must be >= 1, got {speculate_ngram}"
            )
        # longest trailing n-gram the prompt-lookup drafter matches on
        self.speculate_ngram = int(speculate_ngram)
        # decode attention path (kernels/pallas/paged_attention):
        # "auto" keeps today's selection (Pallas on TPU under
        # FLAGS_use_pallas_kernels, XLA elsewhere); "pallas" requests
        # the kernel — degrading to the XLA fallback with a warning and
        # a paddle_tpu_kernels_fallbacks_total count when the backend/
        # shape/dtype cannot honor it, never raising; "xla" pins the
        # fallback (the byte-reference path)
        if decode_kernel not in ("auto", "pallas", "xla"):
            raise ValueError(
                f'decode_kernel must be "auto", "pallas" or "xla", got '
                f"{decode_kernel!r}"
            )
        self.decode_kernel = decode_kernel
        # KV-cache quantization: None stores the adapter dtype (byte-
        # exact contracts hold); "int8" stores quantize-on-write int8
        # pages + per-token scales — ~4x smaller than an fp32 pool,
        # within the documented tolerance (docs/kernels.md), byte-exact
        # greedy contracts become tolerance contracts
        if kv_cache_dtype not in (None, "int8"):
            raise ValueError(
                f'kv_cache_dtype must be None or "int8", got '
                f"{kv_cache_dtype!r}"
            )
        self.kv_cache_dtype = kv_cache_dtype
        # durable request journal (serving/journal.py): a directory
        # path or a Journal. When set, every admission/token/finish is
        # WAL-logged and a restarting engine replays the journal
        # BEFORE traffic — unfinished requests re-admitted at the
        # queue head through the resume() re-prefill contract (greedy
        # byte-identical). None (the default) keeps serving state
        # process-local. For a Fleet use FleetConfig(journal_dir=)
        # instead: replicas share one fleet-level journal.
        self.journal = journal
        # structured JSONL access log (serving/access_log.py): a
        # directory path or AccessLog. One line per finished request
        # (rid, trace id, phase breakdown, finish reason), rotating
        # files, every failure degrading via the obs.accesslog fault
        # site — never fatal. None disables.
        self.access_log = access_log
        # latency SLO (observability.latency.SLOConfig): when set, the
        # engine tracks windowed TTFT/TPOT error-budget burn; sustained
        # burn flips health()["flags"] — and /healthz — to degraded.
        if slo is not None:
            from ..observability.latency import SLOConfig

            if not isinstance(slo, SLOConfig):
                raise TypeError(
                    f"slo must be an observability.SLOConfig or None, "
                    f"got {type(slo).__name__}"
                )
        self.slo = slo
        # tensor-parallel sharded serving (serving/sharding.py):
        # tp_degree > 1 builds a 1 x tp mesh over ``devices`` (jax
        # Device objects or integer ids; None takes the first
        # tp_degree of jax.devices()), shards the adapter weights
        # col/row-wise and the KV pool's head dim over it, and runs
        # every serving program as ONE single-launch SPMD program.
        # tp_degree=1 (the default) is byte-identical to the
        # single-chip engine — no mesh, no placement, same jaxprs.
        if int(tp_degree) < 1:
            raise ValueError(
                f"tp_degree must be >= 1, got {tp_degree}"
            )
        self.tp_degree = int(tp_degree)
        # materialized ONCE: a generator argument must not be consumed
        # by validation and then read empty at engine build
        self.devices = list(devices) if devices is not None else None
        if self.devices is not None and self.tp_degree == 1:
            # refusing beats silently ignoring: an operator pinning
            # per-replica chips must not discover at capacity review
            # that every tp=1 replica stacked on the default device
            raise ValueError(
                "EngineConfig(devices=) requires tp_degree > 1: a "
                "single-chip engine runs on the process's default "
                "device (devices= only places the tensor-parallel "
                "mesh)"
            )
        if (self.devices is not None
                and len(self.devices) != self.tp_degree):
            raise ValueError(
                f"EngineConfig(devices=) has {len(self.devices)} "
                f"entries but tp_degree={self.tp_degree} needs "
                f"exactly {self.tp_degree}"
            )
        # cross-chip numerics for the two row-parallel contractions:
        # "exact" (default) gathers the sharded operand so reductions
        # run whole on every chip — greedy outputs byte-identical to
        # the unsharded engine; "fast" is the Megatron partial-sum +
        # all-reduce, ~1 ulp reduction-order drift (docs/serving.md)
        if tp_numerics not in ("exact", "fast"):
            raise ValueError(
                f'tp_numerics must be "exact" or "fast", got '
                f"{tp_numerics!r}"
            )
        self.tp_numerics = tp_numerics
        # per-chip memory budget gate (paddle_tpu.analysis level 3,
        # docs/analysis.md): when set, the engine AOT-lowers its whole
        # program family at build and compares each program's predicted
        # per-chip peak (``compiled.memory_analysis()``) against this
        # byte budget — refusing the config with an AnalysisError
        # (``analysis_check="warn"`` degrades to a warning) BEFORE the
        # KV pool or any step buffer is allocated on a device. None
        # disables the gate.
        if device_memory_budget is not None:
            device_memory_budget = int(device_memory_budget)
            if device_memory_budget < 1:
                raise ValueError(
                    f"device_memory_budget must be >= 1 byte or None, "
                    f"got {device_memory_budget}"
                )
        self.device_memory_budget = device_memory_budget
        # serving step observatory (observability/stepstats.py): every
        # step folds into per-program launch-wall digests, a goodput
        # ledger, and a bounded sample ring of the last
        # ``stepstats_ring`` non-idle steps — host-side bumps on the
        # hot path, rendered pull-time only. stepstats=False removes
        # the sampler entirely (the bench overhead floor).
        self.stepstats = bool(stepstats)
        stepstats_ring = int(stepstats_ring)
        if stepstats_ring < 1:
            raise ValueError(
                f"stepstats_ring must be >= 1, got {stepstats_ring}"
            )
        self.stepstats_ring = stepstats_ring
        # hierarchical KV spill tier (serving/spill.py): when
        # host_spill_bytes is set, prefix-cache eviction and
        # preemption/release demote KV blocks to a host-RAM LRU of
        # this many bytes (restored instead of recomputed); spill_dir
        # adds the compilecache-style disk third tier under it —
        # host-LRU victims demote to disk and survive the process.
        if host_spill_bytes is not None:
            host_spill_bytes = int(host_spill_bytes)
            if host_spill_bytes < 1:
                raise ValueError(
                    f"host_spill_bytes must be >= 1 byte or None, got "
                    f"{host_spill_bytes}"
                )
        self.host_spill_bytes = host_spill_bytes
        if spill_dir is not None and host_spill_bytes is None:
            raise ValueError(
                "EngineConfig(spill_dir=) is the DISK tier under the "
                "host spill tier: set host_spill_bytes= too"
            )
        self.spill_dir = str(spill_dir) if spill_dir is not None else None
        self.seed = int(seed)


class Engine:
    """Multi-tenant serving over a single model replica.

        engine = serving.Engine(model, serving.EngineConfig(...))
        engine.add_request([1, 2, 3], serving.SamplingParams(max_new_tokens=8))
        while engine.has_unfinished():
            for out in engine.step():
                print(out.request_id, out.token_ids)
    """

    def __init__(self, model, config=None):
        self.config = config or EngineConfig()
        self.adapter = build_adapter(model)
        self.engine_id = f"{next(_engine_counter):x}"
        # the metrics object doubles as a registry collector view
        # (paddle_tpu_serving_* series labeled engine=<id>)
        self.metrics = EngineMetrics(engine_id=self.engine_id)
        cfg = self.config
        # per-request observability: the JSONL access log (shared per
        # directory — fleet replicas append to one log) and the SLO
        # burn tracker the collector view + health() read
        self.access_log = None
        if cfg.access_log is not None:
            from .access_log import resolve_access_log

            self.access_log = resolve_access_log(cfg.access_log)
        self.slo = None
        if cfg.slo is not None:
            from ..observability.latency import SLOTracker

            self.slo = SLOTracker(cfg.slo)
            self.metrics.slo = self.slo
        # tensor-parallel sharding (serving/sharding.py): validated and
        # built BEFORE the pool exists so a bad degree raises one clear
        # ValueError/TypeError naming the flag and dimension instead of
        # a deep XLA mesh failure at first launch
        self.tp = None
        if cfg.tp_degree > 1:
            from .sharding import build_tp_spec

            self.tp = build_tp_spec(self.adapter, cfg)
        # pool dtype: the adapter may declare it; default to the embed
        # table's dtype for dict-shaped weights (the Llama adapter)
        dtype = getattr(self.adapter, "dtype", None)
        if dtype is None:
            dtype = self.adapter.weights["embed"].dtype
        self._pool_dtype = dtype
        # shape-only pool twin (zero device allocation): the program
        # family is traced, lowered, and memory-gated against THIS, so
        # a config whose predicted per-chip peak exceeds
        # EngineConfig(device_memory_budget=) is refused before the
        # real pool ever allocates a byte — the level-3 strengthening
        # of the pool's shard-direct allocation discipline
        self._pool_abstract = KVPool.abstract(
            self.adapter.num_layers, self.adapter.num_kv_heads,
            cfg.num_blocks, cfg.page_size, self.adapter.head_dim, dtype,
            quant_dtype=cfg.kv_cache_dtype,
            sharding=(
                self.tp.pool_sharding if self.tp is not None else None
            ),
        )
        # decode-kernel selection lives on the adapter (the traced
        # decode body reads it). ALWAYS assigned when the knob exists —
        # an adapter reused across engines must not leak a previous
        # engine's selection into this one's traced programs (whose
        # cache signatures and health claim THIS config). A non-default
        # request against an adapter without the knob fails HERE with
        # the config flag named, not at first trace.
        self._decode_kernel = cfg.decode_kernel
        if self.tp is not None and cfg.decode_kernel != "xla":
            # the Pallas paged kernel has no SPMD partitioning rule: a
            # sharded pool routes decode attention through the XLA
            # gather path. An EXPLICIT "pallas" request degrades —
            # warned once, counted, never fatal (the fallback computes
            # the same math); "auto" just resolves to the available
            # path, no warning.
            if cfg.decode_kernel == "pallas":
                from ..kernels.pallas._compat import record_fallback

                record_fallback(
                    "paged_attention", "sharding",
                    hint=(
                        "tensor-parallel serving "
                        f"(EngineConfig(tp_degree={cfg.tp_degree})) "
                        "shards the KV pool; the kernel cannot run "
                        "under SPMD yet"
                    ),
                )
            self._decode_kernel = "xla"
        if hasattr(self.adapter, "decode_kernel"):
            self.adapter.decode_kernel = self._decode_kernel
        elif cfg.decode_kernel != "auto":
            raise TypeError(
                f"{type(self.adapter).__name__} has no decode_kernel "
                f"attribute, but EngineConfig(decode_kernel="
                f"{cfg.decode_kernel!r}) needs an adapter that can "
                "select its decode attention path"
            )
        # TP spec mirrors the decode-kernel discipline: always
        # (re)assigned when the attribute exists so a reused adapter
        # cannot leak a previous engine's mesh into this one's traced
        # programs; a sharded engine over an adapter without the knob
        # fails HERE with the flag named.
        if hasattr(self.adapter, "tp_spec"):
            self.adapter.tp_spec = self.tp
        elif self.tp is not None:
            raise TypeError(
                f"{type(self.adapter).__name__} has no tp_spec "
                f"attribute, but EngineConfig(tp_degree="
                f"{cfg.tp_degree}) needs an adapter whose traced "
                "bodies honor a tensor-parallel sharding spec"
            )
        # the weight tree launches pass to the compiled programs. A
        # sharded engine holds its OWN placed copy instead of mutating
        # adapter.weights — a shared adapter must not leak one engine's
        # mesh placement into another engine's launches (the same
        # anti-leak discipline as decode_kernel/tp_spec, but weights
        # cannot be "re-assigned back"). tp_degree=1 keeps reading the
        # adapter's tree dynamically, so ``refresh()`` after a weight
        # swap still propagates; a SHARDED engine binds at build —
        # rebuild it (or ``Fleet.rolling_restart(model=)``) to swap.
        self._tp_weights = None
        if self.tp is not None:
            # placement: weights per the col/row plan (the pool was
            # already allocated sharded above) — health() exports the
            # measured per-chip byte figure either way
            self._tp_weights = self.tp.shard_weights(
                self.adapter.weights
            )
        # exported as the paddle_tpu_serving_tp_degree gauge
        self.metrics.tp_degree = cfg.tp_degree
        self.waiting: collections.deque = collections.deque()
        self.slots: list = [None] * cfg.max_batch_slots
        # outputs for requests aborted between steps: emitted by the
        # NEXT step() so drivers blocked on completion (generate(), a
        # fleet drain) observe the abort instead of waiting forever
        self._aborted: list = []
        self._admit_counter = 0
        self._key_counter = 0
        self._base_key = jax.random.PRNGKey(cfg.seed)
        # shed-retry backoff (generate()): when every pending prompt
        # is shed and nothing is in flight, the submit loop must wait
        # out the pressure instead of spinning on no-op step() calls
        from ..resilience.retry import RetryPolicy

        self._shed_backoff = RetryPolicy(
            max_attempts=None, deadline=float("inf"),
            base_delay=0.001, max_delay=0.05, jitter=0.1, seed=cfg.seed,
        )
        # programs FIRST, against the abstract pool twin (a compile
        # cache warms the whole family here too) — so the memory gate
        # below can refuse a predicted-OOM config while zero pool
        # buffers exist on any device
        self._build_steps()
        if cfg.device_memory_budget is not None:
            self._enforce_memory_budget()
        # under TP the pool allocates DIRECTLY on the mesh (pages
        # sharded on the kv-head dim when GQA allows): a pool sized to
        # N chips' combined KV budget must never transiently
        # materialize whole on one chip — that transient IS the
        # single-chip RESOURCE_EXHAUSTED ceiling this feature removes
        try:
            self.pool = KVPool(
                self.adapter.num_layers, self.adapter.num_kv_heads,
                cfg.num_blocks, cfg.page_size, self.adapter.head_dim,
                dtype,
                quant_dtype=cfg.kv_cache_dtype,
                sharding=(
                    self.tp.pool_sharding if self.tp is not None
                    else None
                ),
                shard_degree=(
                    self.tp.tp_degree
                    if self.tp is not None and self.tp.kv_sharded else 1
                ),
            )
        except Exception as e:
            from .spill import is_resource_exhausted

            if is_resource_exhausted(e):
                # OOM-graceful pool growth: a backend allocation
                # failure becomes an admission-style refusal an
                # operator (or a fleet supervisor) can act on — shrink
                # num_blocks, enable kv_cache_dtype="int8", raise
                # tp_degree — instead of an opaque backend crash
                raise EngineOverloadedError(
                    f"KV pool allocation exhausted device memory "
                    f"({cfg.num_blocks} blocks x {cfg.page_size} "
                    f"tokens): reduce num_blocks, quantize the cache "
                    f"(kv_cache_dtype='int8'), or shard it wider "
                    f"(tp_degree) — {type(e).__name__}: {e}"
                ) from e
            raise
        self.block_manager = BlockManager(cfg.num_blocks, cfg.page_size)
        # host-RAM spill tier under the pool (serving/spill.py): the
        # prefix cache demotes evicted chain blocks into it, and
        # preemption/release park whole-request handles there so
        # re-admission restores instead of recomputing
        self.spill = None
        self._spill_seq = 0
        self._spill_signature = None
        self._spill_warned = False
        if cfg.host_spill_bytes is not None:
            from .spill import HostSpillTier, register_spill_view

            self.spill = HostSpillTier(
                cfg.host_spill_bytes, spill_dir=cfg.spill_dir,
                engine_id=self.engine_id,
            )
            self._spill_signature = self.pool.block_signature()
            register_spill_view(self.spill, self.engine_id)
        self.prefix_cache = None
        if cfg.enable_prefix_cache:
            from .prefix_cache import PrefixCache

            self.prefix_cache = PrefixCache(
                self.block_manager,
                capacity_blocks=cfg.prefix_cache_blocks,
                metrics=self.metrics,
                spill=self.spill, pool=self.pool,
            )
        # step observatory (observability/stepstats.py): per-program
        # launch-wall digests, goodput ledger, bounded sample ring,
        # live MFU — registered as its own weakref collector view. A
        # sampler crash (the obs.stepstats fault site) warns once and
        # disables it; serving never perturbs (_disable_stepstats).
        self.stepstats = None
        self._stepstats_warned = False
        if cfg.stepstats:
            from ..observability.stepstats import (
                StepStats, register_stepstats_view,
            )

            self.stepstats = StepStats(
                adapter=self.adapter, tp_degree=cfg.tp_degree,
                shard_degree=self.pool.shard_degree,
                ring=cfg.stepstats_ring,
            )
            register_stepstats_view(self.stepstats, self.engine_id)
        # KV headroom gauge (free + reclaimable blocks): what the
        # fleet's headroom-aware router weighs; meaningful from build
        # (an engine that never stepped has the whole pool free)
        self.metrics.kv_headroom_blocks = self.block_manager.num_free
        if cfg.analysis_check is not None:
            # the consolidated gate (L1 jaxpr checks over every enabled
            # program family + the L3 compiled checks when summaries
            # are already in hand — a cache-warmed family, or any
            # engine under the memory gate; lazy engines keep their
            # L1-only build cost)
            self.check_programs(
                cfg.analysis_check,
                compiled=bool(self._aot)
                or cfg.device_memory_budget is not None,
            )
        # durable request journal: replayed AFTER the programs exist
        # (a compile cache has already warmed every prefill bucket by
        # now, so recovery re-prefills are zero-trace) and BEFORE any
        # traffic. Unfinished journaled requests join the queue head.
        self.journal = None
        self._journal_replaying = False
        if cfg.journal is not None:
            from .journal import resolve_journal

            self.journal = resolve_journal(cfg.journal, seed=cfg.seed)
            self._replay_journal()
        # observability: a comm watchdog trip dumps this engine's health
        # snapshot next to the thread stacks, and the scrape endpoint's
        # /healthz aggregates the same snapshot. Registered through a
        # weakref so neither consumer pins a dead engine (weights + KV
        # pool) in memory; weakref.finalize unregisters both when the
        # engine is collected, so dead probes don't accumulate across
        # engine lifetimes.
        import weakref

        def _probe(ref=weakref.ref(self)):
            eng = ref()
            return None if eng is None else eng.health()

        probe_name = f"serving.engine.{self.engine_id}"
        register_health_provider(probe_name, _probe)
        wd = get_comm_watchdog()
        if wd is not None and hasattr(wd, "register_probe"):
            wd.register_probe(probe_name, _probe, owner=self)
        weakref.finalize(
            self, _unregister_engine_probes, probe_name
        )

    # -- compiled steps ------------------------------------------------------
    def _build_steps(self):
        adapter, metrics = self.adapter, self.metrics
        # donation keeps the pool single-buffered on TPU; CPU PJRT ignores
        # donation (and warns), so skip it there
        donate = (1, 2) if jax.default_backend() == "tpu" else ()
        # poison isolation needs to know whether a failed launch may
        # have consumed the donated pool buffers (see _decode_subset)
        self._pool_donated = bool(donate)

        # ``any_sample`` is STATIC (python bool): an all-greedy batch —
        # the common serving case — compiles a program with no sampling
        # warp at all, instead of computing and discarding it. At most
        # two decode programs exist (greedy-only and mixed).

        def prefill_fn(w, kp, vp, ids, length, block_table,
                       temperature, top_k, top_p, do_sample, key,
                       any_sample):
            metrics.prefill_compiles += 1   # traced-body compile probe
            jit_events.mark_traced()        # global compile/retrace log
            logits, kp, vp = adapter.prefill(
                w, kp, vp, ids, length, block_table
            )
            u = (
                jax.random.uniform(
                    key, (1,) + logits.shape, jnp.float32, 1e-9, 1.0
                ) if any_sample else None
            )
            tok = sample_tokens(
                logits[None], temperature[None], top_k[None], top_p[None],
                do_sample[None], u,
            )
            return tok[0], kp, vp

        def decode_fn(w, kp, vp, tokens, positions, block_tables, active,
                      temperature, top_k, top_p, do_sample, key,
                      any_sample):
            metrics.decode_compiles += 1    # traced-body compile probe
            jit_events.mark_traced()        # global compile/retrace log
            logits, kp, vp = adapter.decode(
                w, kp, vp, tokens, positions, block_tables, active
            )
            u = (
                jax.random.uniform(
                    key, logits.shape, jnp.float32, 1e-9, 1.0
                ) if any_sample else None
            )
            nxt = sample_tokens(
                logits, temperature, top_k, top_p, do_sample, u
            )
            return nxt, kp, vp

        # chunked prefill / prefix-cache continuation: the bucketed
        # prefill signature with a cache-length operand. ``any_sample``
        # is forced False for non-final chunks host-side (their sampled
        # token is discarded), so only the final chunk of a sampled
        # request pays the warp.
        def prefill_ext_fn(w, kp, vp, ids, length, cache_len, block_table,
                           temperature, top_k, top_p, do_sample, key,
                           any_sample):
            metrics.prefill_ext_compiles += 1  # traced-body compile probe
            jit_events.mark_traced()           # global compile/retrace log
            logits, kp, vp = adapter.prefill_ext(
                w, kp, vp, ids, length, cache_len, block_table
            )
            u = (
                jax.random.uniform(
                    key, (1,) + logits.shape, jnp.float32, 1e-9, 1.0
                ) if any_sample else None
            )
            tok = sample_tokens(
                logits[None], temperature[None], top_k[None], top_p[None],
                do_sample[None], u,
            )
            return tok[0], kp, vp

        # copy-on-write divergence: duplicate one physical block across
        # every layer's pages (the partial shared block a cache match
        # would otherwise write into). tree_map: an int8 pool's scale
        # planes share the [*, blocks, ...] layout and copy the same way
        def cow_fn(kp, vp, src, dst):
            metrics.cow_compiles += 1       # traced-body compile probe
            jit_events.mark_traced()        # global compile/retrace log
            copy = lambda p: p.at[:, dst].set(p[:, src])
            kp = jax.tree_util.tree_map(copy, tuple(kp))
            vp = jax.tree_util.tree_map(copy, tuple(vp))
            return kp, vp

        # speculative verification: score every slot's K+1-token draft
        # window in one launch and return the per-position greedy
        # argmax — the targets the host-side accept loop compares the
        # drafts against. Greedy-only by design (sampled slots keep the
        # plain decode path), so there is no sampling variant and no
        # key operand: ONE program per engine, ever.
        def verify_fn(w, kp, vp, tokens, positions, draft_lens,
                      block_tables, active):
            metrics.verify_compiles += 1    # traced-body compile probe
            jit_events.mark_traced()        # global compile/retrace log
            logits, kp, vp = adapter.verify(
                w, kp, vp, tokens, positions, draft_lens, block_tables,
                active,
            )
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), kp, vp

        self._prefill_fn = prefill_fn   # unjitted: analysis traces these
        self._decode_fn = decode_fn
        self._prefill_ext_fn = prefill_ext_fn
        self._cow_fn = cow_fn
        self._verify_fn = verify_fn
        # tensor parallelism: pin the traced bodies' OUT shardings to
        # the pool's placement (tokens replicated). Outputs must
        # round-trip the input sharding exactly — a drifting output
        # placement would miss the compiled program's input layout on
        # the next launch and retrace, breaking the single-compile
        # probe. In shardings ride on the committed input arrays (lazy
        # path) / the sharding-attached abstract args (AOT path).
        if self.tp is not None:
            # out shardings come from the abstract pool twin (leaves
            # carry the same NamedSharding the real pool allocates
            # under), so the jits exist before any pool buffer does
            kp_sh, vp_sh = self.tp.pool_out_shardings(
                self._pool_abstract
            )
            rep = self.tp.replicated
            osh = {
                "prefill": (rep, kp_sh, vp_sh),
                "decode": (rep, kp_sh, vp_sh),
                "prefill_ext": (rep, kp_sh, vp_sh),
                "cow": (kp_sh, vp_sh),
                "verify": (rep, kp_sh, vp_sh),
            }
            jkw = lambda kind: {"out_shardings": osh[kind]}
        else:
            jkw = lambda kind: {}
        # the raw bodies and the exact jit options per program kind —
        # shared by the launch jits below, the L1 analysis checks, and
        # the isolated L3 lowering path (_lower_isolated), so all three
        # always describe the SAME program
        self._step_fns = {
            "prefill": prefill_fn,
            "decode": decode_fn,
            "prefill_ext": prefill_ext_fn,
            "cow": cow_fn,
            "verify": verify_fn,
        }
        self._jit_specs = {
            "prefill": dict(
                donate_argnums=donate, static_argnums=(11,),
                **jkw("prefill"),
            ),
            "decode": dict(
                donate_argnums=donate, static_argnums=(12,),
                **jkw("decode"),
            ),
            "prefill_ext": dict(
                donate_argnums=donate, static_argnums=(12,),
                **jkw("prefill_ext"),
            ),
            "cow": dict(
                donate_argnums=(0, 1) if self._pool_donated else (),
                **jkw("cow"),
            ),
            "verify": dict(donate_argnums=donate, **jkw("verify")),
        }
        self._prefill_jit = jax.jit(
            prefill_fn, **self._jit_specs["prefill"]
        )
        self._decode_jit = jax.jit(
            decode_fn, **self._jit_specs["decode"]
        )
        self._prefill_ext_jit = jax.jit(
            prefill_ext_fn, **self._jit_specs["prefill_ext"]
        )
        self._cow_jit = jax.jit(cow_fn, **self._jit_specs["cow"])
        self._verify_jit = jax.jit(
            verify_fn, **self._jit_specs["verify"]
        )
        cfg = self.config
        self._chunking = cfg.prefill_chunk_tokens is not None
        self._use_ext = self._chunking or cfg.enable_prefix_cache
        self._speculating = cfg.speculate_tokens is not None
        # optional-entry-point gates, at BUILD time: one clear error
        # naming the missing adapter method and the config flag that
        # needs it, instead of a deep trace-time AttributeError on the
        # first launch that would have used it
        if self._use_ext and not hasattr(adapter, "prefill_ext"):
            flags = [
                f for f, on in (
                    ("enable_prefix_cache=True", cfg.enable_prefix_cache),
                    (f"prefill_chunk_tokens={cfg.prefill_chunk_tokens}",
                     self._chunking),
                ) if on
            ]
            raise TypeError(
                f"{type(adapter).__name__} has no prefill_ext entry "
                f"point, but EngineConfig({', '.join(flags)}) needs an "
                "adapter that can continue a prefill at a nonzero "
                "cache length"
            )
        if self._speculating and not hasattr(adapter, "verify"):
            raise TypeError(
                f"{type(adapter).__name__} has no verify entry point, "
                f"but EngineConfig(speculate_tokens="
                f"{cfg.speculate_tokens}) needs an adapter that can "
                "score a K+1-token draft window in one launch"
            )
        # persistent compile cache: with a cache configured, every
        # launch goes through an AOT-compiled executable held in
        # self._aot — loaded from disk on a warm restart (zero fresh
        # traces; the traced-body probes above never fire) or compiled
        # once and serialized on a cold start
        self._cc = None
        self._aot = {}
        self._manifest = None
        self._warming = False
        # L3 compiled-analysis summaries (collective census + memory)
        # per program tag, however obtained: read back from a
        # compile-cache artifact's metadata (warm restart — zero
        # re-analysis), extracted once at store time (cold cache), or
        # an isolated AOT lowering (lazy engine under the memory gate /
        # an explicit check_programs() call)
        self._program_analysis: dict = {}
        from ..compilecache import code_fingerprint

        # the adapter's code identity: the engine's programs close over
        # adapter.prefill/decode, whose bytecode the abstract weight
        # tree cannot see — without this an edited model would hit the
        # pre-edit executable. Shallow like every bytecode fingerprint
        # (docs/compilecache.md): callees of these methods are not
        # covered (framework-internal callees are pinned by the env
        # fingerprint's framework version).
        self._adapter_code_fp = "|".join((
            type(self.adapter).__qualname__,
            code_fingerprint(getattr(self.adapter, "prefill", None))
            or "?",
            code_fingerprint(getattr(self.adapter, "decode", None))
            or "?",
            code_fingerprint(getattr(self.adapter, "prefill_ext", None))
            or "?",
            code_fingerprint(getattr(self.adapter, "verify", None))
            or "?",
        ))
        if self.config.compile_cache is not None:
            from .. import compilecache as _cc_mod

            self._cc = _cc_mod.resolve(self.config.compile_cache)
            self._warm_from_cache()

    # -- persistent compile cache (paddle_tpu.compilecache) ------------------
    def _abstract_args(self, kind, bucket=None):
        """ShapeDtypeStructs mirroring exactly what the launch sites
        pass, so an AOT-lowered program is byte-for-byte the program
        the lazy jit path would have compiled (bit-identical outputs by
        construction)."""
        from ..compilecache import abstractify

        cfg = self.config
        n = cfg.max_batch_slots
        sds = jax.ShapeDtypeStruct
        if self.tp is not None:
            # shardings attached: AOT lowering sees the exact operand
            # placements the lazy path's committed arrays carry, so the
            # cached executable IS the program a cold launch compiles
            w = self.tp.abstract(self._launch_weights())
        else:
            w = abstractify(self._launch_weights())
        # the abstract pool twin already carries the pool's exact
        # layout (and placement under TP) and exists before the real
        # pool does — the memory gate lowers from it pre-allocation
        kp = self._pool_abstract.k
        vp = self._pool_abstract.v
        key = sds(self._base_key.shape, self._base_key.dtype)
        if kind == "prefill":
            return (
                w, kp, vp,
                sds((int(bucket),), jnp.int32), sds((), jnp.int32),
                sds((cfg.pages_per_seq,), jnp.int32),
                sds((), jnp.float32), sds((), jnp.int32),
                sds((), jnp.float32), sds((), jnp.bool_), key,
            )
        if kind == "prefill_ext":
            return (
                w, kp, vp,
                sds((int(bucket),), jnp.int32), sds((), jnp.int32),
                sds((), jnp.int32),  # cache_len
                sds((cfg.pages_per_seq,), jnp.int32),
                sds((), jnp.float32), sds((), jnp.int32),
                sds((), jnp.float32), sds((), jnp.bool_), key,
            )
        if kind == "cow":
            return (kp, vp, sds((), jnp.int32), sds((), jnp.int32))
        if kind == "verify":
            return (
                w, kp, vp,
                sds((n, cfg.speculate_tokens + 1), jnp.int32),
                sds((n,), jnp.int32), sds((n,), jnp.int32),
                sds((n, cfg.pages_per_seq), jnp.int32),
                sds((n,), jnp.bool_),
            )
        return (
            w, kp, vp,
            sds((n,), jnp.int32), sds((n,), jnp.int32),
            sds((n, cfg.pages_per_seq), jnp.int32), sds((n,), jnp.bool_),
            sds((n,), jnp.float32), sds((n,), jnp.int32),
            sds((n,), jnp.float32), sds((n,), jnp.bool_), key,
        )

    def _program_meta(self, kind, bucket=None, any_sample=False):
        """``(name, signature, store_key)`` — one program's identity
        under the compile cache (``store_key`` is None without one).
        Factored out of :meth:`_ensure_program` so the L3 summary path
        can address an artifact's metadata sidecar without loading the
        executable."""
        from .. import compilecache as _cc_mod

        aargs = self._abstract_args(kind, bucket)
        name = f"serving.{kind}"
        # no explicit spec-K component: the verify window's K is
        # already pinned by the abstract tokens shape (n, K+1) inside
        # signature_str, and adding a constant to the other kinds'
        # signatures would invalidate every pre-existing on-disk
        # program for nothing
        # tp joins the signature only when sharding is on (keeps every
        # pre-existing single-chip on-disk program valid); dk is the
        # EFFECTIVE kernel (a sharded engine's "pallas" degraded to
        # "xla" must key the program actually built)
        # device ids join the sharded signature too: deserialized
        # executables are DEVICE-PINNED (one compiled for a mesh over
        # [0,1] fails its input-sharding check when launched on [4,5]),
        # so two fleet replicas on different placement slices must
        # never alias to one cached program. devices=None resolves to
        # the first tp ids, so pre-existing sharded caches stay warm.
        tp_sig = (
            f"tp={self.config.tp_degree}:"
            f"tpn={self.config.tp_numerics}:"
            f"dev={','.join(str(i) for i in self.tp.device_ids)}:"
            if self.tp is not None else ""
        )
        sig = (
            f"{kind}:bucket={bucket}:any_sample={bool(any_sample)}:"
            f"dk={self._decode_kernel}:{tp_sig}"
            f"code={self._adapter_code_fp}:"
            + _cc_mod.signature_str(aargs)
        )
        key = self._cc.key(name, sig) if self._cc is not None else None
        return name, sig, key

    def _record_summary(self, kind, bucket, any_sample, summary):
        """Memoize one program's L3 summary and export its predicted
        per-chip peak (``paddle_tpu_serving_program_bytes`` gauge via
        the metrics view, ``health()``'s predicted-peak field)."""
        if summary is None:
            return
        self._program_analysis[
            (kind, bucket, bool(any_sample))
        ] = summary
        mem = summary.get("memory")
        if mem:
            label = kind if bucket is None else f"{kind}[{bucket}]"
            if any_sample:
                label += "+sample"
            self.metrics.program_bytes[label] = int(mem["peak"])

    def _ensure_program(self, kind, bucket=None, any_sample=False):
        """Load-or-compile one serving program under the compile cache.
        A disk hit installs the deserialized executable (recorded as an
        ``aot-hit`` event — zero traces, the compile probes stay
        still) and reads the L3 analysis summary from the artifact's
        metadata sidecar (zero re-analysis); a miss lowers + compiles
        the SAME jitted function once (probes fire normally), extracts
        the summary, serializes both to the store, and appends the
        program to the warmup manifest so the next engine life replays
        everything from disk."""
        any_sample = bool(any_sample)
        tag = (kind, bucket, any_sample)
        exe = self._aot.get(tag)
        if exe is not None:
            return exe
        name, sig, key = self._program_meta(kind, bucket, any_sample)
        aargs = self._abstract_args(kind, bucket)
        summary = None
        got = self._cc.load_executable_bundle(
            key, name=name, signature=sig
        )
        if got is not None:
            exe, meta, _ = got
            summary = meta.get("analysis")
        else:
            exe = None
        if exe is None:
            jitted = {
                "prefill": self._prefill_jit,
                "prefill_ext": self._prefill_ext_jit,
                "decode": self._decode_jit,
                "cow": self._cow_jit,
                "verify": self._verify_jit,
            }[kind]
            if kind in ("prefill", "prefill_ext"):
                ev_sig = (f"{self.engine_id}:bucket={bucket}"
                          f":any_sample={any_sample}")
            elif kind == "decode":
                ev_sig = f"{self.engine_id}:any_sample={any_sample}"
            elif kind == "verify":
                ev_sig = (f"{self.engine_id}"
                          f":k={self.config.speculate_tokens}")
            else:
                ev_sig = self.engine_id
            with jit_events.watch(name, kind="serving", signature=ev_sig):
                if kind in ("cow", "verify"):
                    # no static sampling variant: cow copies blocks,
                    # verify is greedy-only by contract
                    exe = jitted.lower(*aargs).compile()
                else:
                    exe = jitted.lower(*aargs, any_sample).compile()
            try:
                from ..analysis.compiled import program_summary

                summary = program_summary(exe)
            except Exception:
                # analysis: allow(broad-except) the L3 summary is a
                # best-effort sidecar: a backend that cannot render it
                # must never block the compile it describes
                summary = None
            self._cc.store_executable(
                key, exe, name=name, signature=sig,
                extra_meta=(
                    {"analysis": summary} if summary is not None
                    else None
                ),
            )
        self._aot[tag] = exe
        self._record_summary(kind, bucket, any_sample, summary)
        if self._manifest is not None:
            extra = {}
            mem = (summary or {}).get("memory")
            if mem:
                # predicted per-chip peak rides the manifest entry, so
                # an operator can audit a service's byte budget from
                # the manifest alone (docs/compilecache.md)
                extra["memory"] = int(mem["peak"])
            self._manifest.add(
                name, sig, key, kind=kind, bucket=bucket,
                any_sample=any_sample, **extra,
            )
            # warmup batches one save after its replay loop; only a
            # program first traced MID-SERVING flushes immediately
            if not self._warming:
                self._save_manifest()
        return exe

    def _save_manifest(self):
        try:
            self._manifest.save()
        except OSError as e:
            import sys

            sys.stderr.write(
                f"[compilecache] manifest save failed (warm restart "
                f"will miss lazily-added programs): {e}\n"
            )

    def _warm_from_cache(self):
        """Replay the warmup manifest from disk before accepting
        traffic: the baseline program set (every prefill bucket plus
        the greedy decode step) is always warmed; any extra programs a
        previous engine life traced lazily (with-sampler variants) are
        replayed from its manifest. On a cache-warm restart this is
        pure deserialization — zero fresh traces."""
        cfg = self.config
        import hashlib

        from ..compilecache import abstractify, signature_str

        # the abstract pool twin stands in for pool.k: signature_str
        # covers treedef + shape/dtype only, so the service key string
        # is byte-identical to one computed from the real pool — every
        # pre-existing manifest stays live (the adapter code identity
        # is computed in _build_steps, before any cache work)
        svc = (
            signature_str((
                abstractify(self._launch_weights()),
                abstractify(self._pool_abstract.k),
            ))
            + f"|slots={cfg.max_batch_slots}|mml={cfg.max_model_len}"
            + f"|page={cfg.page_size}|blocks={cfg.num_blocks}"
            + f"|buckets={cfg.prefill_buckets}"
            + f"|chunk={cfg.prefill_chunk_tokens}"
            + f"|pfx={int(cfg.enable_prefix_cache)}"
            + f"|spec={cfg.speculate_tokens}"
            # dk is the EFFECTIVE kernel (matches the per-program
            # signatures): sharded engines configured "pallas" and
            # "xla" build byte-identical program sets and must share
            # one manifest; at tp=1 effective == configured, so every
            # pre-existing single-chip service key is unchanged
            + f"|dk={self._decode_kernel}|kvq={cfg.kv_cache_dtype}"
            # tp= keys the service only when sharding is on, so every
            # single-chip manifest written before this existed stays
            # live; a sharded engine warm-restarts from its OWN tp=N
            # manifest (docs/compilecache.md). dev= pins the manifest
            # to the placement slice — cached executables are
            # device-pinned, so each slice warms its own program set
            + (f"|tp={cfg.tp_degree}|tpn={cfg.tp_numerics}"
               f"|dev={','.join(str(i) for i in self.tp.device_ids)}"
               if self.tp is not None else "")
            + f"|code={self._adapter_code_fp}"
        )
        self._service_key = hashlib.sha256(svc.encode()).hexdigest()[:16]
        self._manifest = self._cc.manifest(self._service_key)
        replay = list(self._manifest.load())
        m = self._cc.metrics
        before = (m.hits, m.misses, m.fallbacks)
        self._warming = True
        try:
            self._ensure_program("decode", any_sample=False)
            for b in cfg.prefill_buckets:
                self._ensure_program(
                    "prefill", bucket=b, any_sample=False
                )
            if self._use_ext:
                # the enlarged program set: every bucket's continuation
                # program, plus the COW block copy when sharing is on
                for b in cfg.prefill_buckets:
                    self._ensure_program(
                        "prefill_ext", bucket=b, any_sample=False
                    )
                if cfg.enable_prefix_cache:
                    self._ensure_program("cow")
            if self._speculating:
                self._ensure_program("verify")
            for e in replay:
                kind, bucket = e.get("kind"), e.get("bucket")
                if kind == "prefill" and bucket in cfg.prefill_buckets:
                    self._ensure_program(
                        "prefill", bucket=bucket,
                        any_sample=e.get("any_sample", False),
                    )
                elif (kind == "prefill_ext" and self._use_ext
                        and bucket in cfg.prefill_buckets):
                    self._ensure_program(
                        "prefill_ext", bucket=bucket,
                        any_sample=e.get("any_sample", False),
                    )
                elif kind == "decode":
                    self._ensure_program(
                        "decode", any_sample=e.get("any_sample", False)
                    )
                elif kind == "cow" and cfg.enable_prefix_cache:
                    self._ensure_program("cow")
                elif kind == "verify" and self._speculating:
                    self._ensure_program("verify")
        finally:
            self._warming = False
        self._save_manifest()  # one fsync'd rewrite for the whole set
        _flight.record(
            "compilecache", "warm-start", engine=self.engine_id,
            hits=m.hits - before[0], misses=m.misses - before[1],
            fallbacks=m.fallbacks - before[2],
        )

    # -- durable request journal (serving/journal.py) ------------------------
    def _replay_journal(self):
        """Crash recovery: fold the journal into unfinished requests
        and re-admit them at the HEAD of the waiting queue (they have
        been waiting longest), oldest first. Each carries its emitted
        tokens, so the resume() re-prefill rebuilds its KV over
        ``prompt + output[:-1]`` — greedy continuation is
        byte-identical to an uninterrupted run and no journaled token
        is re-emitted. Requests whose TTL lapsed while the process was
        down are retired with ``"timeout"`` instead of re-prefilled
        (deadline-aware recovery). The re-admissions are re-journaled
        (ADMIT with cursor) so the dead incarnation's segments can
        compact as soon as the recovered work drains."""
        from .journal import restore_entries

        entries = self.journal.replay()
        if not entries:
            self.journal.flush()
            return
        live, expired = restore_entries(
            self.journal, entries,
            lambda e, params: Request(e.prompt, params,
                                      request_id=e.rid),
        )
        self.metrics.requests_timeout += expired
        self._journal_replaying = True
        try:
            for req in reversed(live):
                self.resume(req)
        finally:
            self._journal_replaying = False
        for req in live:   # re-ADMIT in admission order, cursor kept
            self.journal.admit(req)
        self.journal.flush()
        _flight.record(
            "serving", "journal-recovered", engine=self.engine_id,
            requests=len(live),
            expired=len(entries) - len(live),
        )

    # -- static analysis gates (paddle_tpu.analysis L1 + L3) -----------------
    def check_programs(self, mode="error", compiled=True):
        """THE analysis gate over this engine's whole program family.

        Level 1 (jaxpr): the decode step, the continuation prefill +
        COW copy (when enabled), and the speculative verify step (when
        enabled) are traced — never executed — and held to zero
        host-sync / retrace findings, exactly as the per-program
        ``check_decode``/``check_prefill``/``check_verify`` delegates
        always did. Level 3 (compiled, ``compiled=True``): every
        program in the family is AOT-lowered and its optimized HLO +
        memory analysis run through the collective census and the
        per-chip memory budget gate (``analysis.check_compiled``
        rules); findings are enforced per ``mode`` via
        ``analysis.enforce``.

        ``EngineConfig(analysis_check=)`` runs this at build (L3
        included when the family is already compiled — a cache-warmed
        engine — or the memory gate armed it; lazy engines keep their
        L1-only build cost). Returns the merged analysis Report.

        ``mode``: "error" raises ``analysis.AnalysisError`` on a
        blocking finding (and on an analyzer failure); "warn" degrades
        everything to warnings — analysis never takes down serving.
        """
        from .. import analysis

        if mode not in ("warn", "error"):
            raise ValueError(
                f'check_programs mode must be "warn" or "error", got '
                f"{mode!r}"
            )
        report = analysis.Report()
        report.extend(self._check_decode(mode).findings)
        if self._use_ext:
            report.extend(self._check_prefill(mode).findings)
        if self._speculating:
            report.extend(self._check_verify(mode).findings)
        if compiled:
            r3 = self.check_compiled_programs()
            analysis.enforce(
                r3, mode, what="serving compiled program family"
            )
            report.extend(r3.findings)
        return report

    def check_decode(self, mode="error"):
        """Thin delegate: the decode slice of :meth:`check_programs`
        (level 1 only), kept for callers that gate one program."""
        return self._check_decode(mode)

    def check_prefill(self, mode="error"):
        """Thin delegate: the continuation-prefill / COW slice of
        :meth:`check_programs` (level 1 only)."""
        return self._check_prefill(mode)

    def check_verify(self, mode="error"):
        """Thin delegate: the speculative-verify slice of
        :meth:`check_programs` (level 1 only)."""
        return self._check_verify(mode)

    def _program_tags(self):
        """Every ``(kind, bucket, any_sample)`` in this engine's
        baseline program family — the set ``_warm_from_cache`` warms
        and the L3 checks census."""
        cfg = self.config
        tags = [("decode", None, False)]
        tags += [("prefill", b, False) for b in cfg.prefill_buckets]
        if self._use_ext:
            tags += [
                ("prefill_ext", b, False) for b in cfg.prefill_buckets
            ]
            if cfg.enable_prefix_cache:
                tags.append(("cow", None, False))
        if self._speculating:
            tags.append(("verify", None, False))
        return tags

    def _lower_isolated(self, kind, bucket=None, any_sample=False):
        """AOT-compile one program for analysis WITHOUT touching the
        launch jits' trace caches or the compile telemetry: a fresh
        lambda owns its own pjit cache entry, so the real first launch
        still traces (and counts) exactly as before; the traced-body
        probes this trace fires are snapshot-restored and the
        compile/retrace event log is masked — the L3 counterpart of
        the L1 harness's isolation discipline."""
        fn = self._step_fns[kind]
        aargs = self._abstract_args(kind, bucket)
        m = self.metrics
        saved = (m.prefill_compiles, m.decode_compiles,
                 m.prefill_ext_compiles, m.cow_compiles,
                 m.verify_compiles)
        self._pin_adapter()
        try:
            with jit_events.suppress():
                fresh = jax.jit(
                    lambda *a: fn(*a), **self._jit_specs[kind]
                )
                if kind in ("cow", "verify"):
                    return fresh.lower(*aargs).compile()
                return fresh.lower(*aargs, bool(any_sample)).compile()
        finally:
            (m.prefill_compiles, m.decode_compiles,
             m.prefill_ext_compiles, m.cow_compiles,
             m.verify_compiles) = saved

    def _program_summary(self, kind, bucket=None, any_sample=False):
        """One program's L3 summary (collective census + per-chip
        memory), cheapest source first: the in-process memo, the
        compile-cache artifact's metadata sidecar (a warm restart
        re-evaluates rules with ZERO re-analysis), the executable
        ``_ensure_program`` holds, or — lazy engines only — one
        isolated AOT lowering."""
        from ..analysis.compiled import program_summary

        tag = (kind, bucket, bool(any_sample))
        s = self._program_analysis.get(tag)
        if s is not None:
            return s
        if self._cc is not None:
            # load-or-compile through the cache: both paths memoize
            # the summary (sidecar read or extract-at-store)
            exe = self._ensure_program(kind, bucket, any_sample)
            s = self._program_analysis.get(tag)
            if s is not None:
                return s
            # artifact predates the analysis sidecar: summarize the
            # live executable once (no re-store; the next cold compile
            # writes the sidecar)
        else:
            exe = self._lower_isolated(kind, bucket, any_sample)
        s = program_summary(exe)
        self._record_summary(kind, bucket, any_sample, s)
        return s

    def check_compiled_programs(self, passes=None):
        """Level-3 analysis over the whole program family: run the
        compiled-program rule set (collective census, per-chip memory
        budget — ``analysis.compiled.COMPILED_PASSES``) over every
        program's summary and return the collected Report. Pure
        collection — callers (:meth:`check_programs`, the build-time
        memory gate) enforce; a crashing pass or an unsummarizable
        program degrades to a warned ``pass-crash`` finding, never an
        exception (the ``analysis.compiled`` fault-site contract)."""
        from .. import analysis
        from ..analysis.compiled import summary_findings

        cfg = self.config
        report = analysis.Report()
        for kind, bucket, any_sample in self._program_tags():
            label = (
                f"serving.{kind}" if bucket is None
                else f"serving.{kind}[{bucket}]"
            )
            try:
                summary = self._program_summary(
                    kind, bucket, any_sample
                )
            except Exception as e:
                # analysis: allow(broad-except) an analyzer compile
                # failure degrades like a crashing pass — L3 must
                # never take down an engine build
                report.add(analysis.Finding(
                    rule="pass-crash",
                    severity=analysis.Severity.WARNING,
                    message=(
                        f"compiled analysis of {label} crashed: {e!r}"
                    ),
                    root=label,
                ))
                continue
            report.extend(summary_findings(
                summary,
                program=label,
                tp_numerics=(
                    cfg.tp_numerics if self.tp is not None else None
                ),
                tp_degree=cfg.tp_degree,
                device_memory_budget=cfg.device_memory_budget,
                mode="collect",
                passes=passes,
            ))
        return report

    def _enforce_memory_budget(self):
        """The build-time memory gate: census the family's predicted
        per-chip peaks against ``EngineConfig(device_memory_budget=)``
        and refuse (``analysis_check=None``/"error") or warn ("warn")
        BEFORE the KV pool exists — a config that would die with
        RESOURCE_EXHAUSTED never allocates its pool."""
        from .. import analysis

        mode = self.config.analysis_check or "error"
        report = self.check_compiled_programs(
            passes=("memory-budget",)
        )
        if self._manifest is not None:
            # the gate may have appended memory= extras after warmup's
            # batched save — persist them for the manifest audit trail
            self._save_manifest()
        analysis.enforce(
            report, mode,
            what=(
                "serving program family under EngineConfig("
                f"device_memory_budget={self.config.device_memory_budget})"
            ),
        )
        return report

    def _check_decode(self, mode="error"):
        """The decode slice of :meth:`check_programs` (level 1): trace
        the decode step over representative inputs and assert it is
        free of host-sync and retrace findings — the serving-loop
        invariant behind the single-compile guarantee, checked WITHOUT
        executing anything. Returns the full analysis Report."""
        from .. import analysis

        if mode not in ("warn", "error"):
            raise ValueError(
                f'check_decode mode must be "warn" or "error", got '
                f"{mode!r}"
            )
        self._pin_adapter()
        cfg = self.config
        n = cfg.max_batch_slots
        params = pack_sampling_params(self.slots)
        m = self.metrics
        saved = (m.prefill_compiles, m.decode_compiles)
        report = analysis.Report()
        try:
            # trace-only: restore the traced-body compile probes after,
            # so an analysis trace never reads as a real (re)compile
            # (the harness isolates the pjit cache, so the real warmup
            # launch still traces — and counts — normally). BOTH static
            # program variants are gated: greedy-only (any_sample=False)
            # and mixed-sampling (True) — a hazard inside the sampling
            # warp must not wait for the first do_sample request.
            seen = set()
            for any_sample in (False, True):
                do_sample = (
                    np.ones(n, bool) if any_sample
                    else params["do_sample"]
                )
                variant = analysis.check(
                    self._decode_fn,
                    self._launch_weights(), self.pool.k, self.pool.v,
                    np.zeros(n, np.int32), np.zeros(n, np.int32),
                    np.zeros((n, cfg.pages_per_seq), np.int32),
                    np.zeros(n, bool),
                    params["temperature"], params["top_k"],
                    params["top_p"], do_sample, self._base_key,
                    any_sample,
                    static_argnums=(12,),
                    donate_argnums=(1, 2) if self._pool_donated else (),
                    mode=mode, root="serving.decode",
                )
                for f in variant.findings:
                    key = (f.rule, f.file, f.line, f.message)
                    if key not in seen:  # shared-path findings once
                        seen.add(key)
                        report.add(f)
        finally:
            m.prefill_compiles, m.decode_compiles = saved
        blocking = report.by_rule("host-sync") + report.by_rule(
            "retrace-hazard"
        )
        if blocking:
            msg = (
                "serving decode step failed static analysis (the "
                "single-compile decode invariant):\n"
                + "\n".join(f.render() for f in blocking)
            )
            if mode == "error":
                raise analysis.AnalysisError(msg, report)
            import warnings

            warnings.warn(msg, stacklevel=2)
        return report

    def _check_prefill(self, mode="error"):
        """The prefix-cache / chunked-prefill slice of
        :meth:`check_programs` (level 1): the continuation prefill
        (both static sampling variants) and the COW block copy, held to
        zero host-sync and retrace findings — a chunk launch sits on
        the same latency-critical path as the decode step. Trace-only;
        compile probes are restored after."""
        from .. import analysis

        if mode not in ("warn", "error"):
            raise ValueError(
                f'check_prefill mode must be "warn" or "error", got '
                f"{mode!r}"
            )
        self._pin_adapter()
        cfg = self.config
        bucket = cfg.prefill_buckets[0]
        m = self.metrics
        saved = (m.prefill_compiles, m.decode_compiles,
                 m.prefill_ext_compiles, m.cow_compiles)
        donate = (1, 2) if self._pool_donated else ()
        report = analysis.Report()
        seen = set()

        def merge(variant):
            for f in variant.findings:
                key = (f.rule, f.file, f.line, f.message)
                if key not in seen:  # shared-path findings once
                    seen.add(key)
                    report.add(f)

        try:
            for any_sample in (False, True):
                merge(analysis.check(
                    self._prefill_ext_fn,
                    self._launch_weights(), self.pool.k, self.pool.v,
                    np.zeros(bucket, np.int32), np.int32(1), np.int32(0),
                    np.zeros(cfg.pages_per_seq, np.int32),
                    np.float32(1.0), np.int32(0), np.float32(1.0),
                    np.bool_(any_sample), self._base_key, any_sample,
                    static_argnums=(12,), donate_argnums=donate,
                    mode=mode, root="serving.prefill_ext",
                ))
            if cfg.enable_prefix_cache:
                merge(analysis.check(
                    self._cow_fn, self.pool.k, self.pool.v,
                    np.int32(0), np.int32(1),
                    donate_argnums=(0, 1) if self._pool_donated else (),
                    mode=mode, root="serving.cow",
                ))
        finally:
            (m.prefill_compiles, m.decode_compiles,
             m.prefill_ext_compiles, m.cow_compiles) = saved
        blocking = report.by_rule("host-sync") + report.by_rule(
            "retrace-hazard"
        )
        if blocking:
            msg = (
                "serving prefill continuation failed static analysis "
                "(the chunked-prefill latency invariant):\n"
                + "\n".join(f.render() for f in blocking)
            )
            if mode == "error":
                raise analysis.AnalysisError(msg, report)
            import warnings

            warnings.warn(msg, stacklevel=2)
        return report

    def _check_verify(self, mode="error"):
        """The speculative-VERIFY slice of :meth:`check_programs`
        (level 1): the draft-window scoring step, held to zero
        host-sync and retrace findings — a verify launch replaces the
        decode launch on the latency-critical greedy path. Trace-only;
        compile probes are restored after."""
        from .. import analysis

        if mode not in ("warn", "error"):
            raise ValueError(
                f'check_verify mode must be "warn" or "error", got '
                f"{mode!r}"
            )
        self._pin_adapter()
        cfg = self.config
        if cfg.speculate_tokens is None:
            raise RuntimeError(
                "check_verify needs EngineConfig(speculate_tokens=): "
                "this engine has speculation disabled"
            )
        n, k = cfg.max_batch_slots, cfg.speculate_tokens
        m = self.metrics
        saved = (m.prefill_compiles, m.decode_compiles,
                 m.verify_compiles)
        try:
            report = analysis.check(
                self._verify_fn,
                self._launch_weights(), self.pool.k, self.pool.v,
                np.zeros((n, k + 1), np.int32), np.zeros(n, np.int32),
                np.zeros(n, np.int32),
                np.zeros((n, cfg.pages_per_seq), np.int32),
                np.zeros(n, bool),
                donate_argnums=(1, 2) if self._pool_donated else (),
                mode=mode, root="serving.verify",
            )
        finally:
            (m.prefill_compiles, m.decode_compiles,
             m.verify_compiles) = saved
        blocking = report.by_rule("host-sync") + report.by_rule(
            "retrace-hazard"
        )
        if blocking:
            msg = (
                "serving verify step failed static analysis (the "
                "speculative-decode latency invariant):\n"
                + "\n".join(f.render() for f in blocking)
            )
            if mode == "error":
                raise analysis.AnalysisError(msg, report)
            import warnings

            warnings.warn(msg, stacklevel=2)
        return report

    def _launch_weights(self):
        """The weight tree every launch (and trace/abstraction site)
        passes to the compiled programs: the engine's own mesh-placed
        copy under TP, the adapter's live tree otherwise — so
        ``adapter.refresh()`` keeps propagating to single-chip engines
        while a sharded engine's placement can never leak through a
        shared adapter."""
        return (
            self._tp_weights if self._tp_weights is not None
            else self.adapter.weights
        )

    def _pin_adapter(self):
        """Re-assert THIS engine's mutable adapter knobs before any
        launch or trace. The traced bodies read ``adapter.tp_spec`` /
        ``adapter.decode_kernel`` at TRACE time, and tracing is lazy
        (first launch, or a mid-serving `_ensure_program` miss) — so a
        shared adapter whose knobs a LATER engine build reassigned
        would otherwise leak that engine's mesh/kernel into this one's
        first trace (exact-mode constraints silently dropped, or a
        single-chip program compiled against another engine's mesh).
        Two attribute writes per launch; already-compiled programs
        never re-read them."""
        if hasattr(self.adapter, "decode_kernel"):
            self.adapter.decode_kernel = self._decode_kernel
        if hasattr(self.adapter, "tp_spec"):
            self.adapter.tp_spec = self.tp

    def _next_key(self):
        self._key_counter += 1
        return jax.random.fold_in(self._base_key, self._key_counter)

    def _request_key(self, req):
        """PRNG key for a single-request launch (prefill / final
        chunk). The engine stream ALWAYS advances — a seeded request in
        the mix never shifts other requests' keys — but a sampled
        request carrying an explicit ``SamplingParams.seed`` draws
        ``fold_in(PRNGKey(seed), n_generated)`` instead: its first
        token is reproducible across restarts, journal replays, and
        failovers regardless of engine history. Batched decode keeps
        the shared per-step stream (docs/serving.md caveat)."""
        key = self._next_key()
        p = req.sampling_params
        if p.do_sample and p.seed is not None:
            return jax.random.fold_in(
                jax.random.PRNGKey(p.seed), len(req.output_token_ids)
            )
        return key

    # -- client API ----------------------------------------------------------
    def add_request(self, prompt_token_ids, sampling_params=None,
                    request_id=None):
        return self.submit(
            Request(prompt_token_ids, sampling_params, request_id)
        )

    def submit(self, req):
        """Admission over a caller-constructed Request — what
        ``add_request`` wraps. Split out so a router (``serving.fleet``)
        can keep ONE Request object across replicas: the same object it
        submits here is what it hands to another replica's
        :meth:`resume` after a failover, tokens intact."""
        cfg = self.config
        if (cfg.max_waiting is not None
                and len(self.waiting) >= cfg.max_waiting):
            raise RuntimeError(
                f"admission queue full ({cfg.max_waiting} waiting)"
            )
        if len(req.prompt_token_ids) >= cfg.max_model_len:
            raise ValueError(
                f"prompt of {len(req.prompt_token_ids)} tokens leaves no "
                f"room to generate under max_model_len={cfg.max_model_len}"
            )
        if cfg.kv_shed_threshold is not None:
            bm = self.block_manager
            reclaimable, util = self._active_pressure()
            admissible_now = (
                not self.waiting and None in self.slots
                and bm.num_free + reclaimable >= bm.blocks_needed(
                    len(req.prompt_token_ids) + 1
                )
            )
            if util >= cfg.kv_shed_threshold and not admissible_now:
                self.metrics.requests_shed += 1
                # generate()'s internal admission retries undo the shed
                # count (flow control, not a rejection) — they must not
                # flood the bounded flight ring either
                if not getattr(self, "_suppress_shed_events", False):
                    _flight.record(
                        "serving", "shed", engine=self.engine_id,
                        request_id=req.request_id, kv_utilization=util,
                        tenant=getattr(req, "tenant", None),
                    )
                raise EngineOverloadedError(
                    f"KV pool at {util:.0%} utilization (threshold "
                    f"{cfg.kv_shed_threshold:.0%}); request shed"
                )
        self.waiting.append(req)
        self.metrics.requests_received += 1
        if self.journal is not None and not self._journal_replaying:
            # WAL the admission (buffered urgent; the next step's group
            # flush makes it durable BEFORE any of its tokens can — an
            # admission is only actionable through step() anyway). The
            # fleet front door flushes per admission instead.
            self.journal.admit(req)
        return req

    def _active_pressure(self):
        """``(reclaimable_blocks, active_utilization)`` — the pressure
        split every consumer (shedding, health, metrics gauges) must
        agree on: cached prefix blocks nobody runs against and idle
        speculative draft headroom are RECLAIMABLE capacity, not
        pressure, so a pool kept warm by the prefix cache (or padded
        by draft headroom) neither sheds admissions nor reads as
        overloaded."""
        bm = self.block_manager
        reclaimable = (
            self.prefix_cache.reclaimable_blocks()
            if self.prefix_cache is not None else 0
        )
        if self._speculating:
            reclaimable += sum(
                self._spec_headroom(r) for r in self.slots
            )
        return reclaimable, (bm.num_used - reclaimable) / bm.num_blocks

    def _spec_headroom(self, req):
        """Idle draft-headroom blocks a greedy RUNNING slot holds
        beyond its required ``num_cached + 1`` coverage (0 for every
        other slot) — THE shared definition behind pressure accounting
        (:meth:`_active_pressure`) and reclaim
        (:meth:`_reclaim_spec_headroom`); they must agree or admission
        would see capacity reclaim cannot actually deliver."""
        if (req is None or req.state is not RequestState.RUNNING
                or req.sampling_params.do_sample):
            return 0
        return max(
            len(req.block_ids)
            - self.block_manager.blocks_needed(req.num_cached + 1), 0,
        )

    def resume(self, req):
        """Re-enqueue a request whose KV state was lost OUTSIDE the
        engine's control — a fleet failover hands a dead replica's
        in-flight Request to a healthy engine here. The externally
        driven form of recompute preemption: scheduling state is reset,
        prompt and already-generated tokens are kept, so the next
        prefill rebuilds the cache over ``prompt + output[:-1]`` and
        greedy continuation is bit-identical to an uninterrupted run.
        Joins the HEAD of the queue (it has been waiting longest) and
        deliberately bypasses ``max_waiting``/shedding: recovered work
        must not be dropped by admission control."""
        if req.state is RequestState.FINISHED:
            raise ValueError(
                f"cannot resume finished request {req.request_id!r}"
            )
        req.block_ids = []
        req.num_cached = 0
        req.slot = None
        req.state = RequestState.WAITING
        # goodput attribution: the re-prefill recomputes context built
        # on another replica — migration waste, not preemption
        req.resume_cause = "migration"
        self.waiting.appendleft(req)
        self.metrics.requests_received += 1
        req.timeline.resumes += 1
        if self.journal is not None and not self._journal_replaying:
            # re-ADMIT with the emit cursor: replay must not re-count
            # the tokens this request already produced elsewhere
            self.journal.admit(req)
        return req

    def abort(self, request_id):
        """Drop a request wherever it is; returns True if found. The
        abort goes through the normal finish accounting (finish_time,
        ``requests_finished``, a RequestOutput with
        ``finish_reason="aborted"`` emitted by the NEXT ``step()``), so
        drivers blocked on the request's completion — ``generate()``,
        a fleet drain — observe it instead of waiting forever. Aborts
        are not failures (no error probe, no postmortem dump), but the
        request's timeline still lands in the flight timeline ring and
        the access log — excluded from the finish-time latency
        digests/SLO window (see docs/observability.md)."""
        for req in list(self.waiting):
            if req.request_id == request_id:
                self.waiting.remove(req)
                self._finish(req, "aborted", self._aborted)
                return True
        for req in self.slots:
            if req is not None and req.request_id == request_id:
                self._finish(req, "aborted", self._aborted)
                return True
        return False

    def release(self, request_id):
        """Detach an unfinished request from this engine WITHOUT
        finishing it — the fleet's migration primitive (scale-down,
        rolling restart). KV blocks and the slot are freed, scheduling
        state resets to WAITING with ``num_cached=0``, and the Request
        object — prompt, generated tokens, tenant tag, arrival/deadline
        clocks — is returned intact for :meth:`resume` on another
        replica (re-prefill over ``prompt + output[:-1]``; greedy
        continuation byte-identical). No finish accounting, no
        RequestOutput: from the caller's point of view the request is
        still in flight, just homeless. Returns None when the id is not
        here or already finished."""
        req = None
        for r in list(self.waiting):
            if r.request_id == request_id:
                self.waiting.remove(r)
                req = r
                break
        if req is None:
            for r in self.slots:
                if r is not None and r.request_id == request_id:
                    req = r
                    break
        if req is None or req.state is RequestState.FINISHED:
            return None
        # same-host migration rides the spill tier: park the cached
        # blocks under a handle the SURVIVOR's admission can restore
        # (tiers cross-lookup within the process; the handle key rides
        # the Request and the fleet's re-ADMIT journal record). A
        # cross-host resume simply misses and re-prefills as before.
        self._spill_request(req)
        self._release(req)
        req.state = RequestState.WAITING
        req.num_cached = 0
        return req

    def has_unfinished(self):
        return bool(self._aborted) or bool(self.waiting) or any(
            r is not None for r in self.slots
        )

    def generate(self, prompts, sampling_params=None):
        """Convenience driver: submit everything, step until drained,
        return RequestOutputs in submission order. ``sampling_params`` may
        be one SamplingParams for all prompts or a list per prompt.
        Submission respects ``max_waiting`` by feeding the queue as it
        drains instead of raising mid-batch."""
        params = normalize_sampling_params(prompts, sampling_params)
        cap = self.config.max_waiting
        pending = collections.deque(zip(prompts, params))
        reqs, done = [], {}
        stalls = 0
        while pending or self.has_unfinished():
            admitted = False
            while pending and (cap is None or len(self.waiting) < cap):
                p, sp = pending.popleft()
                try:
                    self._suppress_shed_events = True
                    try:
                        reqs.append(self.add_request(p, sp))
                        admitted = True
                    finally:
                        self._suppress_shed_events = False
                except EngineOverloadedError:
                    # flow control, not a caller-visible rejection: the
                    # prompt is resubmitted once the batch drains, so
                    # undo the shed count the internal retry incurred
                    self.metrics.requests_shed -= 1
                    pending.appendleft((p, sp))
                    break
            outs = self.step()
            for out in outs:
                done[out.request_id] = out
            if (pending and not admitted and not outs
                    and not self.has_unfinished()):
                # every prompt shed with nothing in flight: step() is a
                # no-op, so spinning on it burns a core without moving
                # the pressure — back off (exponential + jitter) until
                # admission clears
                stalls += 1
                self._shed_backoff.pause(stalls + 1)
            else:
                stalls = 0
        return [done[r.request_id] for r in reqs]

    # -- scheduler -----------------------------------------------------------
    def step(self):
        """One scheduler iteration: expire TTLs, admit + prefill
        joiners, then one decode step over the occupied slots. Returns
        RequestOutputs for requests that finished during this step.

        Failure containment: a request whose prefill or decode raises is
        finished with ``finish_reason="error"`` (the exception recorded
        on ``RequestOutput.error``) while the engine keeps stepping the
        remaining requests — one poison request cannot take down the
        batch. Comm-watchdog aborts are NOT contained: a cluster-level
        abort must propagate. Anything that does escape (watchdog
        abort, donated-pool loss) dumps the flight recorder with this
        engine's health snapshot on the way out — the engine is about
        to die, so leave the postmortem."""
        finished: list = []
        if self._aborted:
            # requests aborted since the last step finish HERE (see
            # abort()): their slots/blocks were already released
            finished.extend(self._aborted)
            self._aborted.clear()
        if self.stepstats is not None:
            self.stepstats.begin_step()
        try:
            self._expire(finished)
            self._admit(finished)
            self._prefill_chunks(finished)
            running = RequestState.RUNNING
            if any(r is not None and r.state is running
                   for r in self.slots):
                self._ensure_capacity()
                if any(r is not None and r.state is running
                       for r in self.slots):
                    self._decode(finished)
        except Exception as e:
            _flight.record(
                "serving", "engine-error", engine=self.engine_id,
                error=f"{type(e).__name__}: {e}",
            )
            # the engine is broken by definition here — health() itself
            # may raise over torn state, and nothing on the postmortem
            # path may displace the exception we are re-raising
            try:
                probe = self.health()
            except Exception as he:
                probe = {"error": f"health() failed: {he!r}"}
            _flight.dump(
                "engine-error",
                probes={f"serving.engine.{self.engine_id}": probe},
            )
            raise
        if self.journal is not None:
            # batched EMIT + group write (finished requests already
            # buffered theirs in _finish). Steady-state steps are a
            # near-no-op: tokens batch on the Request objects until
            # the write interval elapses or a completion makes the
            # buffer urgent — a lost interval's tokens are re-derived
            # byte-identically by replay's recompute.
            self.journal.step_flush(self.slots)
        m, bm = self.metrics, self.block_manager
        m.queue_depth = len(self.waiting)
        m.num_running = sum(r is not None for r in self.slots)
        m.cache_utilization = bm.utilization()
        m.kv_reclaimable_blocks, m.kv_active_utilization = (
            self._active_pressure()
        )
        if self.prefix_cache is not None:
            m.prefix_cache_blocks = len(self.prefix_cache)
        m.pool_high_water = bm.high_water
        m.kv_headroom_blocks = bm.num_free + m.kv_reclaimable_blocks
        st = self.stepstats
        if st is not None:
            try:
                faults.fire("obs.stepstats", engine=self.engine_id)
                sample = st.end_step(
                    occupancy=(
                        m.num_running / self.config.max_batch_slots
                    ),
                    queue_depth=m.queue_depth,
                    kv_free_blocks=bm.num_free,
                    kv_reclaimable_blocks=m.kv_reclaimable_blocks,
                )
                if sample is not None:
                    # the flight recorder's bounded step-sample ring:
                    # a postmortem shows the last N steps' attribution
                    _flight.record_step_sample(
                        dict(sample, engine=self.engine_id)
                    )
            except Exception as e:  # analysis: allow(broad-except)
                # degradable by contract: the observatory must never
                # take the step down with it
                self._disable_stepstats(e)
        return finished

    def health(self):
        """One-call health snapshot (scrape-endpoint / watchdog probe /
        fleet router): ``status`` is "ok", "degraded" (poisoned/expired
        requests or a tripped comm watchdog), or "overloaded"
        (admission queue full or KV pressure at the shedding
        threshold). ``status`` keeps its single-string precedence
        (overloaded beats degraded) for back-compat; ``flags`` carries
        BOTH signals independently — the fleet router gates admission
        on it, where overloaded-masking-degraded would hide a sick
        replica behind a busy one."""
        m, bm, cfg = self.metrics, self.block_manager, self.config
        wd = get_comm_watchdog()
        util = bm.utilization()
        # pressure is judged on ACTIVE utilization (_active_pressure):
        # reclaimable cached prefix blocks are capacity the engine can
        # take back at will, not an overloaded replica
        reclaimable, util_active = self._active_pressure()
        queue_full = (
            cfg.max_waiting is not None
            and len(self.waiting) >= cfg.max_waiting
        )
        shedding = (
            cfg.kv_shed_threshold is not None
            and util_active >= cfg.kv_shed_threshold
        )
        # sustained SLO error-budget burn degrades the replica so an
        # external load balancer rotates it out (503 via /healthz).
        # The in-process fleet router deliberately does NOT unroute on
        # it (supervisor.routable gates on overload/fresh errors):
        # serving slowly beats not serving, and unrouting every slow
        # replica at once would turn a latency incident into an outage
        slo_burning = self.slo is not None and self.slo.burning()
        degraded = bool(
            m.requests_errored or m.requests_timeout or slo_burning
            or (wd is not None and wd.fired is not None)
        )
        overloaded = queue_full or shedding
        status = "ok"
        if degraded:
            status = "degraded"
        if overloaded:
            status = "overloaded"
        return {
            "status": status,
            "flags": [
                f for f, on in (
                    ("degraded", degraded), ("overloaded", overloaded),
                    ("slo_burn", slo_burning),
                ) if on
            ],
            # windowed error-budget burn per signal (None = no SLO /
            # no samples); burn 1.0 = spending the budget as allotted
            "slo_burn_rates": (
                self.slo.burn_rates() if self.slo is not None else None
            ),
            "queue_depth": len(self.waiting),
            "num_running": sum(r is not None for r in self.slots),
            # kernel-path observability: which decode attention path
            # this engine was configured with and what the KV pool
            # stores (degradations are visible in the process-wide
            # paddle_tpu_kernels_fallbacks_total counter)
            "decode_kernel": cfg.decode_kernel,
            # the path programs were actually built with (a sharded
            # engine's "pallas"/"auto" resolves to the XLA gather path)
            "decode_kernel_effective": self._decode_kernel,
            # tensor parallelism: degree + mesh device ids, so /healthz
            # and the fleet router can tell a 4-chip replica from a
            # 1-chip one
            "tp_degree": cfg.tp_degree,
            "tp_numerics": (
                cfg.tp_numerics if self.tp is not None else None
            ),
            "tp_devices": (
                self.tp.device_ids if self.tp is not None else []
            ),
            "kv_cache_dtype": cfg.kv_cache_dtype or str(
                self.pool._dtype
            ),
            "kv_bytes_per_token": self.pool.bytes_per_token(),
            "kv_bytes_per_token_per_chip": (
                self.pool.bytes_per_token_per_chip()
            ),
            # the L3 memory gate's view: the configured per-chip byte
            # budget (None = gate off) and the largest predicted
            # per-chip peak across the analyzed program family (None
            # until any program has been summarized — lazy engines
            # without the gate never pay for the prediction)
            "device_memory_budget": cfg.device_memory_budget,
            "predicted_peak_bytes_per_chip": (
                max(self.metrics.program_bytes.values())
                if self.metrics.program_bytes else None
            ),
            "kv_utilization": util,
            "kv_active_utilization": util_active,
            "kv_reclaimable_blocks": reclaimable,
            # headroom the router weighs: blocks this replica could
            # still absorb (free + reclaimable), plus the per-chip
            # byte view so heterogeneous-width slices compare fairly
            "kv_headroom_blocks": bm.num_free + reclaimable,
            "kv_headroom_bytes_per_chip": int(
                (bm.num_free + reclaimable)
                * self.pool.block_bytes_per_chip()
            ),
            # step observatory summary (None = sampler disabled):
            # per-program step walls, goodput ledger, occupancy, MFU
            "stepstats": (
                self.stepstats.summary()
                if self.stepstats is not None else None
            ),
            "prefix_cache_blocks": (
                len(self.prefix_cache)
                if self.prefix_cache is not None else 0
            ),
            # cached chain keys (wire form): a fleet router matches a
            # request's prompt digests against these to find the
            # replica already holding its prefix (hit-aware routing)
            "prefix_cache_digests": (
                self.prefix_cache.chain_digests()
                if self.prefix_cache is not None else []
            ),
            # host spill tier (serving/spill.py): occupancy, per-class
            # spilled/restored traffic, restore hit rate — None when
            # the tier is disabled (host_spill_bytes unset)
            "spill": (
                self.spill.stats() if self.spill is not None else None
            ),
            # speculation economics: accepted / proposed draft tokens
            # (None until the first proposal)
            "spec_accept_rate": m.spec_accept_rate,
            "requests_errored": m.requests_errored,
            "requests_timeout": m.requests_timeout,
            "requests_shed": m.requests_shed,
            "preemptions": m.preemptions,
            "last_error": m.last_error,
            "watchdog": {
                "enabled": wd is not None,
                "fired": None if wd is None else wd.fired,
            },
        }

    def _expire(self, finished):
        """Finish requests (queued or running) whose TTL has lapsed with
        finish_reason="timeout"."""
        now = time.perf_counter()
        for req in [r for r in self.waiting if r.expired(now)]:
            self.waiting.remove(req)
            self.metrics.requests_timeout += 1
            self._finish(req, "timeout", finished)
        for req in list(self.slots):
            if req is not None and req.expired(now):
                self.metrics.requests_timeout += 1
                self._finish(req, "timeout", finished)

    def _poison(self, req, exc, finished):
        """Contain a per-request failure: record it, finish the request
        with an error, keep the engine stepping."""
        req.error = f"{type(exc).__name__}: {exc}"
        m = self.metrics
        m.requests_errored += 1
        m.last_error = f"request {req.request_id}: {req.error}"
        self._finish(req, "error", finished)

    def _admit(self, finished):
        """FCFS admission into free slots. A request is admitted with
        its FULL block budget (whole prompt plus one decode write) but
        no compute: the prefix cache may cover a prefix via ``fork()``
        (copy-on-write when the one-token cap cuts into the last shared
        block), and the actual prefill runs in :meth:`_prefill_chunks`
        — one launch, or several interleaved chunk launches."""
        bm = self.block_manager
        while self.waiting and None in self.slots:
            req = self.waiting[0]
            tokens = req.tokens_to_prefill()
            # restore-instead-of-recompute: a preempted/released
            # request carrying a live spill handle skips the prefix
            # lookup — its OWN cached blocks come back from the host
            # tier (full block budget still allocated below)
            restore_tokens = self._spill_restorable(req, tokens)
            match = None
            if restore_tokens is None and self.prefix_cache is not None:
                # at least one token must remain to prefill: its logits
                # seed the first sampled token
                match = self.prefix_cache.lookup(
                    tokens, limit=len(tokens) - 1
                )
            n_fork = match.num_shared if match is not None else 0
            n_alloc = bm.blocks_needed(len(tokens) + 1) - n_fork
            if not bm.can_allocate(n_alloc):
                if self.prefix_cache is not None:
                    # retained cache blocks are reclaimable capacity —
                    # but never the ones this very match is about to
                    # fork or copy from
                    protect = set(
                        match.shared_blocks
                    ) if match is not None else set()
                    if match is not None and match.cow_src is not None:
                        protect.add(match.cow_src)
                    self.prefix_cache.reclaim(
                        n_alloc - bm.num_free, protect=protect
                    )
                if not bm.can_allocate(n_alloc):
                    # idle draft headroom is reclaimable capacity too:
                    # an admission must never be refused while
                    # speculation holds unused blocks
                    self._reclaim_spec_headroom(n_alloc - bm.num_free)
                if not bm.can_allocate(n_alloc):
                    break
            self.waiting.popleft()
            if restore_tokens is None and self.prefix_cache is not None:
                # one lookup per ADMISSION (blocked retries don't count;
                # neither do they touch the LRU — see lookup/commit)
                self.metrics.prefix_lookups += 1
            if restore_tokens is not None:
                req.block_ids = bm.allocate(n_alloc)
                # a failed restore keeps the blocks and recomputes:
                # num_cached=0 sends the whole prompt back through
                # prefill — exactly the pre-spill preemption path
                req.num_cached = (
                    restore_tokens
                    if self._spill_restore(req, restore_tokens) else 0
                )
            elif match is not None:
                bm.fork(match.shared_blocks)
                req.block_ids = list(match.shared_blocks) + bm.allocate(
                    n_alloc
                )
                req.num_cached = match.cache_len
                self.prefix_cache.commit(match)
            else:
                req.block_ids = bm.allocate(n_alloc)
                req.num_cached = 0
            req.slot = self.slots.index(None)
            self.slots[req.slot] = req
            req.state = RequestState.PREFILLING
            req.admit_seq = self._admit_counter
            self._admit_counter += 1
            # timeline: queue wait ends at the FIRST slot assignment
            # (re-admissions after preemption keep the original stamp;
            # the hop list tracks which engines admitted it)
            tl = req.timeline
            first_admission = tl.admitted is None
            tl.mark_admitted(self.engine_id)
            if first_admission:
                self.metrics.latency["queue"].record(tl.queue_wait_s)
            if match is not None:
                tl.prefix_hit_tokens += match.cache_len
            if match is not None and match.cow_src is not None:
                # the cap cut into the last shared block: this request
                # will WRITE its final prefill token there, so it gets
                # a private copy (block index n_fork is freshly
                # allocated) instead of a fork
                try:
                    self._cow(match.cow_src, req.block_ids[n_fork])
                except CommTimeoutError:
                    raise  # cluster-level abort, not a poison request
                except Exception as e:
                    if getattr(e, "_kv_pool_unsafe", False):
                        raise  # donated pool may be gone
                    self._poison(req, e, finished)
                    continue
            if (restore_tokens is not None
                    and req.num_cached >= len(tokens)):
                # fully-covered restore: the cache already holds
                # prompt + output[:-1], exactly the pre-preemption
                # decode state — no prefill launch at all. Straight to
                # RUNNING with the last token re-armed; the goodput
                # ledger's preempt_recompute class books ZERO tokens.
                req.state = RequestState.RUNNING
                req.last_token = req.output_token_ids[-1]
                if self.prefix_cache is not None:
                    # publish the restored PROMPT blocks for reuse,
                    # mirroring the post-prefill register
                    self.prefix_cache.register(
                        req.prompt_token_ids, req.block_ids,
                        req.num_cached,
                    )
                reason = req.check_stop(self.config.max_model_len)
                if reason:
                    self._finish(req, reason, finished)

    def _disable_stepstats(self, exc):
        """``obs.stepstats`` degradation: a crashing sampler is warned
        ONCE and dropped — its collector view unregisters through the
        weakref at the next scrape — and serving continues without the
        observatory. The step itself must never pay for a sampler
        failure."""
        if not self._stepstats_warned:
            self._stepstats_warned = True
            warnings.warn(
                f"step observatory disabled for engine "
                f"{self.engine_id} after sampler failure: "
                f"{type(exc).__name__}: {exc}",
                RuntimeWarning, stacklevel=2,
            )
        self.stepstats = None

    def _stepstats_launch(self, program, t0):
        """Record one device launch wall for the observatory. ``t0``
        was taken immediately before the launch block, whose body ends
        with the host-side sync — so the wall is device-inclusive
        block-until-ready time, with zero effect on traced code."""
        st = self.stepstats
        if st is None:
            return
        try:
            st.record_launch(program, time.perf_counter() - t0)
        except Exception as e:  # analysis: allow(broad-except) degradable
            self._disable_stepstats(e)

    def _watch(self, tag):
        """Hung-step detection: launches run under the comm watchdog
        when one is enabled (serving's analogue of watchdog-tracked
        collectives)."""
        wd = get_comm_watchdog()
        if wd is None:
            import contextlib

            return contextlib.nullcontext()
        return wd.watch(tag)

    def _prefill(self, req, tokens):
        self._pin_adapter()
        faults.fire(
            "serving.step", phase="prefill", request_id=req.request_id,
        )
        cfg = self.config
        bucket = next_bucket(len(tokens), cfg.prefill_buckets)
        ids = np.zeros(bucket, np.int32)
        ids[: len(tokens)] = tokens
        table = np.zeros(cfg.pages_per_seq, np.int32)
        table[: len(req.block_ids)] = req.block_ids
        p = req.sampling_params
        _t0 = time.perf_counter()
        with span(
            "serving.prefill", request_id=req.request_id, bucket=bucket,
        ), self._watch("serving.prefill"), jit_events.watch(
            # engine id in the signature: a SECOND engine compiling its
            # own programs is a fresh compile, not a retrace alarm —
            # and any_sample is a static compile key (same as decode's
            # signature), so the first sampled request on a warm bucket
            # is a fresh variant, not a retrace
            "serving.prefill", kind="serving",
            signature=(f"{self.engine_id}:bucket={bucket}"
                       f":any_sample={bool(p.do_sample)}"),
        ):
            try:
                args = (
                    self._launch_weights(), self.pool.k, self.pool.v,
                    ids, np.int32(len(tokens)), table,
                    np.float32(p.temperature), np.int32(p.top_k),
                    np.float32(p.top_p), np.bool_(p.do_sample),
                    self._request_key(req),
                )
                if self._cc is not None:
                    # compile-cache mode: launch the AOT executable
                    # (loaded from disk or compiled once at warmup) —
                    # the static any_sample flag is baked into it
                    exe = self._ensure_program(
                        "prefill", bucket=bucket,
                        any_sample=bool(p.do_sample),
                    )
                    tok, k, v = exe(*args)
                else:
                    tok, k, v = self._prefill_jit(
                        *args, bool(p.do_sample)
                    )
            except Exception as e:
                # same donated-buffer hazard as decode (_launch_decode):
                # a dispatched-program failure may have consumed the
                # donated pool, so containment must not continue over it
                if self._pool_donated:
                    e._kv_pool_unsafe = True
                raise
            tok = int(tok)
        self._stepstats_launch("prefill", _t0)
        self.pool.rebind(k, v)
        req.num_cached = len(tokens)
        self.metrics.prefill_tokens += len(tokens)
        self.metrics.prefill_steps += 1
        req.timeline.prefill_chunks += 1
        req.timeline.prefill_tokens += len(tokens)
        st = self.stepstats
        if st is not None:
            # goodput: a re-prefill over already-produced context
            # (output tokens exist) recomputes, attributed to the
            # preemption or migration that forced it
            st.note_prefill(
                len(tokens),
                cause=(req.resume_cause or "preempt")
                if req.output_token_ids else None,
            )
        self._finish_prefill(req, tok)

    def _finish_prefill(self, req, tok):
        """Book the first token once a request's whole prefill has
        landed (one-shot or final chunk)."""
        if req.output_token_ids:
            # resumed after preemption: the sampled token re-derives
            # output[-1]; keep the one we already have
            req.last_token = req.output_token_ids[-1]
        else:
            req.first_token_time = time.perf_counter()
            req.timeline.first_token = req.first_token_time
            self.metrics.record_ttft(
                req.first_token_time - req.arrival_time
            )
            req.output_token_ids.append(tok)
            req.last_token = tok

    def _prefill_chunks(self, finished):
        """Run prefill launches for PREFILLING slot occupants, oldest
        first. Chunking disabled: every pending prefill completes this
        step (one launch each — the pre-chunking behavior). Chunking
        enabled: at most ``max_prefill_chunks_per_step`` chunk launches
        run, then the decode batch gets the step — a long prompt is
        spread over steps instead of stalling every running request."""
        cfg = self.config
        budget = (
            cfg.max_prefill_chunks_per_step if self._chunking else None
        )
        used = 0
        for req in sorted(
            (r for r in self.slots
             if r is not None and r.state is RequestState.PREFILLING),
            key=lambda r: r.admit_seq,
        ):
            while req.state is RequestState.PREFILLING:
                if budget is not None and used >= budget:
                    return
                used += 1
                tokens = req.tokens_to_prefill()
                remaining = tokens[req.num_cached:]
                chunk = (
                    remaining[:cfg.prefill_chunk_tokens]
                    if self._chunking else remaining
                )
                final = req.num_cached + len(chunk) >= len(tokens)
                try:
                    if req.num_cached == 0 and final:
                        # nothing cached, everything fits: the classic
                        # one-shot program (bit-for-bit today's path)
                        self._prefill(req, tokens)
                    else:
                        self._prefill_chunk(req, chunk, final)
                except CommTimeoutError:
                    raise  # cluster-level abort, not a poison request
                except Exception as e:
                    if getattr(e, "_kv_pool_unsafe", False):
                        raise  # donated pool may be gone
                    self._poison(req, e, finished)
                    break
                if final:
                    req.state = RequestState.RUNNING
                    if self.prefix_cache is not None:
                        # publish the full PROMPT blocks for reuse
                        # (decode never writes them again: writes only
                        # land at positions >= the prompt length)
                        self.prefix_cache.register(
                            req.prompt_token_ids, req.block_ids,
                            req.num_cached,
                        )
                    reason = req.check_stop(cfg.max_model_len)
                    if reason:
                        self._finish(req, reason, finished)

    def _prefill_chunk(self, req, chunk, final):
        """One continuation launch: ``chunk`` tokens appended at cache
        position ``req.num_cached`` through the PREFILL_EXT program.
        Non-final chunks run the greedy-only variant regardless of the
        request's sampling params — their sampled token is discarded,
        so the vocab warp would be wasted compute."""
        self._pin_adapter()
        faults.fire(
            "serving.step", phase="prefill", request_id=req.request_id,
        )
        cfg = self.config
        bucket = next_bucket(len(chunk), cfg.prefill_buckets)
        ids = np.zeros(bucket, np.int32)
        ids[: len(chunk)] = chunk
        table = np.zeros(cfg.pages_per_seq, np.int32)
        table[: len(req.block_ids)] = req.block_ids
        p = req.sampling_params
        cache_len = req.num_cached
        any_sample = bool(p.do_sample) and final
        _t0 = time.perf_counter()
        with span(
            "serving.prefill_ext", request_id=req.request_id,
            bucket=bucket, cache_len=cache_len,
        ), self._watch("serving.prefill"), jit_events.watch(
            "serving.prefill_ext", kind="serving",
            signature=(f"{self.engine_id}:bucket={bucket}"
                       f":any_sample={any_sample}"),
        ):
            try:
                args = (
                    self._launch_weights(), self.pool.k, self.pool.v,
                    ids, np.int32(len(chunk)), np.int32(cache_len),
                    table,
                    np.float32(p.temperature), np.int32(p.top_k),
                    np.float32(p.top_p), np.bool_(p.do_sample),
                    self._request_key(req),
                )
                if self._cc is not None:
                    exe = self._ensure_program(
                        "prefill_ext", bucket=bucket,
                        any_sample=any_sample,
                    )
                    tok, k, v = exe(*args)
                else:
                    tok, k, v = self._prefill_ext_jit(*args, any_sample)
            except Exception as e:
                # same donated-buffer hazard as decode (_launch_decode)
                if self._pool_donated:
                    e._kv_pool_unsafe = True
                raise
            if final:
                tok = int(tok)
        self._stepstats_launch("prefill_ext", _t0)
        self.pool.rebind(k, v)
        req.num_cached = cache_len + len(chunk)
        self.metrics.prefill_tokens += len(chunk)
        self.metrics.prefill_steps += 1
        self.metrics.prefill_chunks += 1
        req.timeline.prefill_chunks += 1
        req.timeline.prefill_tokens += len(chunk)
        st = self.stepstats
        if st is not None:
            # same recompute attribution as _prefill: every chunk of a
            # resumed request rebuilds cache it already had
            st.note_prefill(
                len(chunk),
                cause=(req.resume_cause or "preempt")
                if req.output_token_ids else None,
            )
        if final:
            self._finish_prefill(req, tok)

    def _cow(self, src, dst):
        """Copy-on-write one physical block (every layer's pages) so a
        prefill can diverge from a shared partial block without
        touching the original."""
        self._pin_adapter()
        _t0 = time.perf_counter()
        with span(
            "serving.cow", src=int(src), dst=int(dst),
        ), self._watch("serving.cow"), jit_events.watch(
            "serving.cow", kind="serving", signature=self.engine_id,
        ):
            try:
                args = (
                    self.pool.k, self.pool.v, np.int32(src),
                    np.int32(dst),
                )
                if self._cc is not None:
                    exe = self._ensure_program("cow")
                    k, v = exe(*args)
                else:
                    k, v = self._cow_jit(*args)
            except Exception as e:
                if self._pool_donated:
                    e._kv_pool_unsafe = True
                raise
        self._stepstats_launch("cow", _t0)
        self.pool.rebind(k, v)
        self.metrics.cow_copies += 1

    def _ensure_capacity(self):
        """Every running request needs a block for the KV slot its next
        decode step writes; steal from the youngest on exhaustion."""
        bm = self.block_manager
        for req in sorted(
            (r for r in self.slots if r is not None),
            key=lambda r: r.admit_seq,
        ):
            if req.state is not RequestState.RUNNING:
                continue  # preempted by an older request this pass
            need = bm.blocks_needed(req.num_cached + 1)
            while len(req.block_ids) < need:
                if bm.can_allocate(1):
                    req.block_ids += bm.allocate(1)
                    continue
                if (self.prefix_cache is not None
                        and self.prefix_cache.reclaim(1)):
                    continue  # cached block freed: retry the allocate
                if self._reclaim_spec_headroom(1):
                    continue  # idle draft headroom freed: retry
                victims = [
                    r for r in self.slots
                    if r is not None and r is not req
                ]
                if not victims:
                    raise RuntimeError(
                        "KV pool exhausted by a single request; "
                        "EngineConfig.num_blocks is too small for "
                        "max_model_len"
                    )
                self._preempt(max(victims, key=lambda r: r.admit_seq))
        if self._speculating:
            # opportunistic draft headroom: a greedy slot's verify
            # launch writes up to K positions past the required one,
            # so grab blocks for them while the pool has slack — but
            # NEVER preempt or reclaim for it (the host clamps each
            # slot's draft length to its owned-block slack instead, so
            # speculation degrades to plain decode under pressure
            # rather than adding to it)
            cfg = self.config
            k = cfg.speculate_tokens
            for req in self.slots:
                if (req is None or req.state is not RequestState.RUNNING
                        or req.sampling_params.do_sample):
                    continue
                # a request that can only consume w more drafts before
                # its stop condition must not hold headroom beyond
                # them; clamped at the block-table width too — near the
                # length cap the window is cut by _draft_budget instead
                want = min(
                    k,
                    req.sampling_params.max_new_tokens
                    - len(req.output_token_ids) - 1,
                )
                if want <= 0:
                    continue
                need = min(
                    bm.blocks_needed(req.num_cached + 1 + want),
                    cfg.pages_per_seq,
                )
                while len(req.block_ids) < need and bm.can_allocate(1):
                    req.block_ids += bm.allocate(1)

    def _preempt(self, req):
        # restore-instead-of-recompute: snapshot the victim's cached
        # blocks into the host tier BEFORE _release frees them; a
        # successful spill makes the re-admission a host->device
        # restore (no re-prefill) instead of a recompute
        spilled = self._spill_request(req)
        self._release(req)
        req.state = RequestState.WAITING
        req.num_cached = 0
        # the re-prefill this forces recomputes tokens the ledger
        # already counted — classify that waste as preemption
        req.resume_cause = "preempt"
        self.waiting.appendleft(req)
        self.metrics.preemptions += 1
        req.timeline.preemptions += 1
        _flight.record(
            "serving", "preemption", engine=self.engine_id,
            request_id=req.request_id, spilled=spilled,
        )

    # -- host spill tier (serving/spill.py) ----------------------------------
    def _spill_request(self, req):
        """Park ``req``'s cached KV blocks in the host tier as ONE
        handle (all-or-nothing), keyed on the Request so re-admission
        — here, or on a same-host survivor after ``release()`` — can
        restore them. Best effort: any failure (tier disabled, nothing
        cached, injected ``kv.spill`` fault, host budget) returns
        False and the old free-and-recompute path applies unchanged.
        A successful spill is re-ADMITted to the journal so the handle
        key rides next to the emit cursor — a crash replay re-anchors
        it against the disk tier."""
        if self.spill is None or req.num_cached < 1:
            return False
        bm = self.block_manager
        need = bm.blocks_needed(req.num_cached)
        if need > len(req.block_ids):
            return False
        try:
            snaps = [
                self.pool.read_block(b) for b in req.block_ids[:need]
            ]
        except Exception as e:
            # analysis: allow(broad-except) spill is an optimization:
            # an unreadable pool (donation race, backend error) must
            # degrade to plain recompute preemption, never crash
            self.spill.note_spill_failure("request")
            if not self._spill_warned:
                self._spill_warned = True
                warnings.warn(
                    f"[serving] KV spill read failed "
                    f"({type(e).__name__}: {e}); preemption degrades "
                    "to recompute (warned once, counted)",
                    stacklevel=2,
                )
            return False
        key = f"req:{req.request_id}:{self._spill_seq}"
        self._spill_seq += 1
        if not self.spill.put(
            key, snaps, self._spill_signature,
            num_tokens=req.num_cached, cls="request",
        ):
            return False
        req.spill_key = key
        req.spill_tokens = req.num_cached
        if self.journal is not None and not self._journal_replaying:
            # latest-ADMIT-wins: this re-ADMIT carries both the emit
            # cursor and the spill handle (journal "kv" field)
            self.journal.admit(req)
        return True

    def _spill_restorable(self, req, tokens):
        """Admission peek: the token count a spilled handle would
        restore for ``req``, or None for the normal allocate+prefill
        path. Validates the handle against the live tiers (host, disk,
        same-process peers) and this engine's program family — a
        PARTIAL restore leaves a suffix to prefill, which needs the
        prefill_ext program."""
        if self.spill is None or getattr(req, "spill_key", None) is None:
            return None
        n = int(getattr(req, "spill_tokens", 0) or 0)
        if (n < 1 or n > len(tokens)
                or (n < len(tokens) and not self._use_ext)
                or (n >= len(tokens) and not req.output_token_ids)):
            req.spill_key = None
            return None
        if not self.spill.has(req.spill_key, self._spill_signature):
            # the tier LRU-dropped it (or a cross-host migration):
            # recompute path, and stop re-peeking every step
            req.spill_key = None
            return None
        return n

    def _spill_restore(self, req, n_tokens):
        """Write ``req``'s spilled handle back into its freshly
        allocated blocks. True = restored (``num_cached`` may be set
        to ``n_tokens``); False degrades to recompute — the blocks
        stay allocated and the normal prefill rebuilds them. Runs
        under the OOM guard: a RESOURCE_EXHAUSTED device write
        reclaims cold prefix blocks (spilling them colder, to host)
        and retries once before degrading."""
        from .spill import is_resource_exhausted

        t0 = time.perf_counter()
        key, req.spill_key, req.spill_tokens = req.spill_key, None, 0
        payload = self.spill.get(
            key, self._spill_signature, pop=True
        )
        need = self.block_manager.blocks_needed(n_tokens)
        if payload is None or len(payload) < need:
            return False
        for i, (block, snap) in enumerate(
            zip(req.block_ids[:need], payload)
        ):
            try:
                self.pool.write_block(block, snap)
            except Exception as e:
                # analysis: allow(broad-except) the memory-pressure
                # degradation ladder: reclaim -> spill colder blocks
                # -> recompute; admission never unwinds the step
                if is_resource_exhausted(e) and self.prefix_cache \
                        is not None:
                    self.prefix_cache.reclaim(
                        need - i, protect=req.block_ids
                    )
                    try:
                        self.pool.write_block(block, snap)
                        continue
                    except Exception:
                        # analysis: allow(broad-except) same ladder:
                        # the retry exhausts it; recompute below
                        pass
                self.spill.note_restore_failure("request")
                if not self._spill_warned:
                    self._spill_warned = True
                    warnings.warn(
                        f"[serving] KV restore failed "
                        f"({type(e).__name__}: {e}); degrading to "
                        "recompute (warned once, counted)",
                        stacklevel=2,
                    )
                return False
        # goodput attribution: any residual prefill (partial handle)
        # is real forward progress, not preemption waste
        req.resume_cause = "restored"
        self.spill.note_restored(
            "request", payload, time.perf_counter() - t0
        )
        return True

    def _decode(self, finished):
        # one key per scheduler step, shared by isolation re-launches:
        # greedy rows never consume it, and sampled rows see the same
        # uniforms whether or not a poison request was carved out.
        # Drawn unconditionally (even when only the keyless verify
        # program runs) so the key stream advances once per step
        # regardless of the greedy/sampled split.
        key = self._next_key()
        idxs = [
            i for i, r in enumerate(self.slots)
            if r is not None and r.state is RequestState.RUNNING
        ]
        if not self._speculating:
            self._decode_subset(idxs, key, finished)
            return
        # speculation splits the batch by sampling mode: greedy slots
        # go through the verify program (several tokens per launch),
        # sampled slots keep the plain decode path — speculative
        # acceptance is defined against the greedy argmax, and a
        # sampled row's token depends on the warp + key stream, which
        # the verify program deliberately does not carry
        greedy = [
            i for i in idxs
            if not self.slots[i].sampling_params.do_sample
        ]
        sampled = [
            i for i in idxs if self.slots[i].sampling_params.do_sample
        ]
        # drafts are proposed up front: a step where nothing was
        # drafted (no repetition to exploit anywhere) runs the plain
        # single-launch decode over the whole running set instead —
        # bit-identical, and the decode program is cheaper than a
        # draft-less K+1 verify window, so speculation can never be a
        # strict slowdown on non-repetitive traffic
        drafts = {
            i: speculation.propose(
                self._draft_history(self.slots[i]),
                self._draft_budget(self.slots[i]),
                max_ngram=self.config.speculate_ngram,
            )
            for i in greedy
        }
        if not any(drafts.values()):
            self._decode_subset(idxs, key, finished)
            return
        self._verify_subset(greedy, finished, drafts)
        self._decode_subset(sampled, key, finished)

    def _launch_decode(self, idxs, key):
        """Run the compiled decode step with only ``idxs`` active.
        Per-slot outputs are independent (each slot attends to its own
        pages), so any active-mask subset yields the same tokens for its
        members as the full batch would — the property the poison-
        isolation bisection in _decode_subset relies on."""
        self._pin_adapter()
        cfg = self.config
        n = cfg.max_batch_slots
        tokens = np.zeros(n, np.int32)
        positions = np.zeros(n, np.int32)
        tables = np.zeros((n, cfg.pages_per_seq), np.int32)
        active = np.zeros(n, bool)
        for i in idxs:
            req = self.slots[i]
            tokens[i] = req.last_token
            positions[i] = req.num_cached
            tables[i, : len(req.block_ids)] = req.block_ids
            active[i] = True
        params = pack_sampling_params(self.slots)
        faults.fire(
            "serving.step", phase="decode",
            request_ids=tuple(self.slots[i].request_id for i in idxs),
        )
        any_sample = bool(params["do_sample"].any())
        _t0 = time.perf_counter()
        with span(
            "serving.decode", active=len(idxs),
        ), self._watch("serving.decode"), jit_events.watch(
            "serving.decode", kind="serving",
            signature=f"{self.engine_id}:any_sample={any_sample}",
        ):
            try:
                args = (
                    self._launch_weights(), self.pool.k, self.pool.v,
                    tokens, positions, tables, active,
                    params["temperature"], params["top_k"],
                    params["top_p"], params["do_sample"], key,
                )
                if self._cc is not None:
                    # compile-cache mode: AOT executable per static
                    # variant (greedy / mixed-sampling); a variant first
                    # seen mid-serving compiles once, is persisted, and
                    # joins the manifest for the next warm restart
                    exe = self._ensure_program(
                        "decode", any_sample=any_sample
                    )
                    nxt, k, v = exe(*args)
                else:
                    nxt, k, v = self._decode_jit(*args, any_sample)
            except Exception as e:
                # a failure from the dispatched program may have
                # consumed the DONATED pool buffers — re-launching over
                # them would cascade garbage; mark it so isolation
                # re-raises instead (host-side failures before dispatch,
                # e.g. injected faults above, stay containable)
                if self._pool_donated:
                    e._kv_pool_unsafe = True
                raise
            nxt = np.asarray(nxt)
        self._stepstats_launch("decode", _t0)
        self.pool.rebind(k, v)
        self.metrics.decode_steps += 1
        return nxt

    def _isolate(self, idxs, finished, launch, recurse):
        """Shared poison-isolation protocol for batched launches
        (decode and verify): run ``launch(idxs)``; on failure, carve
        the poison request out — by exception attribution
        (``exc.request_id``) or active-mask bisection via
        ``recurse(subset)`` — and finish it with an error while the
        rest still run this step. Returns the launch result, or None
        when containment consumed the failure. Cluster-level aborts
        (CommTimeoutError) and donated-pool losses re-raise: they are
        not containable."""
        try:
            return launch(idxs)
        except CommTimeoutError:
            raise  # cluster-level abort, not a poison request
        except Exception as e:
            if getattr(e, "_kv_pool_unsafe", False):
                raise  # donated pool may be gone: containment impossible
            rid = getattr(e, "request_id", None)
            hit = [
                i for i in idxs if self.slots[i].request_id == rid
            ] if rid is not None else []
            if hit:
                # attributed failure: finish the culprit, run the rest
                self._poison(self.slots[hit[0]], e, finished)
                recurse([i for i in idxs if i != hit[0]])
            elif len(idxs) == 1:
                self._poison(self.slots[idxs[0]], e, finished)
            else:
                mid = len(idxs) // 2
                recurse(idxs[:mid])
                recurse(idxs[mid:])
            return None

    def _decode_subset(self, idxs, key, finished):
        """Decode ``idxs`` with poison isolation (see ``_isolate``)."""
        if not idxs:
            return
        nxt = self._isolate(
            idxs, finished,
            lambda s: self._launch_decode(s, key),
            lambda s: self._decode_subset(s, key, finished),
        )
        if nxt is None:
            return
        cfg, st = self.config, self.stepstats
        for i in idxs:
            req = self.slots[i]
            req.num_cached += 1
            tok = int(nxt[i])
            req.output_token_ids.append(tok)
            req.last_token = tok
            self.metrics.decode_tokens += 1
            req.timeline.decode_tokens += 1
            if st is not None:
                st.note_decode(1)
            reason = req.check_stop(cfg.max_model_len)
            if reason:
                self._finish(req, reason, finished)

    def _reclaim_spec_headroom(self, need):
        """Free up to ``need`` speculative draft-headroom blocks back
        to the pool — tail blocks beyond a greedy RUNNING slot's
        required ``num_cached + 1`` coverage. They hold at most dead
        draft writes (never published, never shared), so freeing them
        is always safe; the slot's next draft budget just shrinks.
        This is what keeps the headroom grab genuinely opportunistic:
        admission and mandatory block growth take it back BEFORE
        shedding, preempting, or refusing a request. Returns the
        number freed."""
        if not self._speculating:
            return 0
        bm = self.block_manager
        freed = 0
        for req in self.slots:
            if freed >= need:
                break
            extra = self._spec_headroom(req)
            while extra > 0 and freed < need:
                bm.free([req.block_ids.pop()])
                extra -= 1
                freed += 1
        return freed

    def _draft_history(self, req):
        """The drafter's bounded history window (prompt + output
        tail), assembled without copying the whole token history every
        step — the per-step host cost must not grow with context
        length."""
        lb = speculation.DEFAULT_LOOKBACK
        out = req.output_token_ids
        if len(out) >= lb:
            return out[-lb:]
        return req.prompt_token_ids[-(lb - len(out)):] + out

    def _draft_budget(self, req):
        """How many draft tokens slot state allows this step: writes
        must stay inside the request's OWNED blocks (headroom is
        opportunistic — see _ensure_capacity) and inside the model
        length, and the request can consume at most remaining-1 drafts
        before a stop condition ends it (proposals past that are
        guaranteed waste). 0 degrades the slot to plain-decode-
        through-verify."""
        cfg = self.config
        ceiling = min(
            len(req.block_ids) * cfg.page_size, cfg.max_model_len
        )
        remaining = (
            req.sampling_params.max_new_tokens
            - len(req.output_token_ids)
        )
        return max(min(cfg.speculate_tokens,
                       ceiling - (req.num_cached + 1),
                       remaining - 1), 0)

    def _launch_verify(self, idxs, drafts):
        """Run the compiled verify step with only ``idxs`` active:
        score each slot's K+1 window (pending token + its entry in
        ``drafts``, proposed once per step in :meth:`_decode`) in one
        launch, return ``(tokens, draft_lens, targets)`` for the
        host-side accept loop. Per-slot outputs are independent (same
        property as _launch_decode), so the poison-isolation bisection
        applies unchanged — re-launches reuse the same drafts."""
        self._pin_adapter()
        cfg = self.config
        n, k = cfg.max_batch_slots, cfg.speculate_tokens
        tokens = np.zeros((n, k + 1), np.int32)
        positions = np.zeros(n, np.int32)
        draft_lens = np.zeros(n, np.int32)
        tables = np.zeros((n, cfg.pages_per_seq), np.int32)
        active = np.zeros(n, bool)
        for i in idxs:
            req = self.slots[i]
            tokens[i, 0] = req.last_token
            positions[i] = req.num_cached
            tables[i, : len(req.block_ids)] = req.block_ids
            active[i] = True
            draft = drafts.get(i, [])
            draft_lens[i] = len(draft)
            tokens[i, 1: 1 + len(draft)] = draft
        faults.fire(
            "serving.step", phase="verify",
            request_ids=tuple(self.slots[i].request_id for i in idxs),
        )
        _t0 = time.perf_counter()
        with span(
            "serving.verify", active=len(idxs),
            proposed=int(draft_lens.sum()),
        ), self._watch("serving.verify"), jit_events.watch(
            "serving.verify", kind="serving",
            signature=f"{self.engine_id}:k={k}",
        ):
            try:
                args = (
                    self._launch_weights(), self.pool.k, self.pool.v,
                    tokens, positions, draft_lens, tables, active,
                )
                if self._cc is not None:
                    exe = self._ensure_program("verify")
                    tgt, kp, vp = exe(*args)
                else:
                    tgt, kp, vp = self._verify_jit(*args)
            except Exception as e:
                # same donated-buffer hazard as decode (_launch_decode)
                if self._pool_donated:
                    e._kv_pool_unsafe = True
                raise
            tgt = np.asarray(tgt)
        self._stepstats_launch("verify", _t0)
        self.pool.rebind(kp, vp)
        self.metrics.verify_steps += 1
        return tokens, draft_lens, tgt

    def _verify_subset(self, idxs, finished, drafts):
        """Speculative decode for greedy slots ``idxs`` with the same
        poison isolation as _decode_subset (see ``_isolate``). On
        success each slot accepts the longest draft prefix matching
        the target argmax and emits accepted+1 tokens — every appended
        token is exactly what a plain decode step would have produced,
        checked through the same per-token stop conditions."""
        if not idxs:
            return
        res = self._isolate(
            idxs, finished,
            lambda s: self._launch_verify(s, drafts),
            lambda s: self._verify_subset(s, finished, drafts),
        )
        if res is None:
            return
        tokens, draft_lens, tgt = res
        cfg, m = self.config, self.metrics
        st = self.stepstats
        for i in idxs:
            req = self.slots[i]
            dlen = int(draft_lens[i])
            a = speculation.accept_length(
                tokens[i, 1: 1 + dlen], tgt[i, :dlen]
            )
            req.timeline.verify_steps += 1
            if dlen:
                # zero-draft slots (nothing to look up, no block
                # slack) are plain decodes, not speculation samples
                m.spec_proposed += dlen
                m.spec_accepted += a
                m.record_spec_accept(a)
                req.timeline.spec_accepted += a
                if st is not None and dlen > a:
                    # rejected drafts consumed verify compute for
                    # tokens nobody keeps — the goodput ledger's
                    # spec-reject class (== proposed - accepted)
                    st.note_spec_reject(dlen - a)
            # emit targets 0..a: the accepted drafts' successors plus
            # the bonus token the rejected/terminal position scored.
            # Their K/V is already in the pages (draft j == target j-1
            # for accepted j); rejected positions' writes are dead —
            # num_cached stops short of them, every later causal mask
            # ends at its own query position, and the next write at
            # that position overwrites.
            for j in range(a + 1):
                tok = int(tgt[i, j])
                req.num_cached += 1
                req.output_token_ids.append(tok)
                req.last_token = tok
                m.decode_tokens += 1
                req.timeline.decode_tokens += 1
                if st is not None:
                    st.note_decode(1)
                reason = req.check_stop(cfg.max_model_len)
                if reason:
                    # stop inside the window (EOS mid-draft, length):
                    # later accepted tokens are discarded unemitted,
                    # exactly where the plain path would have stopped
                    self._finish(req, reason, finished)
                    break

    # -- teardown ------------------------------------------------------------
    def _release(self, req):
        """Free the request's KV blocks and vacate its slot."""
        if req.block_ids:
            self.block_manager.free(req.block_ids)
            req.block_ids = []
        if req.slot is not None:
            self.slots[req.slot] = None
            req.slot = None

    def _finish(self, req, reason, finished):
        if self.spill is not None and getattr(req, "spill_key", None):
            # a parked handle for a request that will never resume is
            # dead budget: release it now instead of waiting for LRU
            self.spill.discard(req.spill_key)
            req.spill_key = None
            req.spill_tokens = 0
        if reason == "aborted" and self.stepstats is not None:
            # the client walked away from every token this request
            # emitted: reclassify them useful -> wasted in the ledger
            self.stepstats.note_abort(len(req.output_token_ids))
        if reason in ("timeout", "error"):
            # degradation events belong in the postmortem ring; normal
            # completions (length/eos/stop) would only drown them out
            _flight.record(
                "serving", reason, engine=self.engine_id,
                request_id=req.request_id, error=req.error,
            )
        req.finish_reason = reason
        req.state = RequestState.FINISHED
        req.finish_time = time.perf_counter()
        # timeline finalization: close the phase record, then the
        # shared finish accounting (access_log.record_finish) — e2e/
        # tpot digests + SLO window (client aborts excluded: not
        # latency samples), access-log line + flight timeline ring
        # (aborts included). All host-side, once per REQUEST.
        req.timeline.mark_finish(reason, req.finish_time)
        record_finish(
            req, latency=self.metrics.latency, slo=self.slo,
            access_log=self.access_log, engine=self.engine_id,
        )
        self._release(req)
        self.metrics.requests_finished += 1
        if self.journal is not None:
            # trailing tokens + terminal record, buffered; the step's
            # group flush (or the next one, for between-step aborts)
            # makes the completion durable
            self.journal.finish(req, reason)
        finished.append(RequestOutput(req))
