"""Model adapters: the compiled compute behind the serving engine.

The engine schedules REQUESTS; an adapter turns one scheduler decision
into array math over the paged KV pool. Two entry points, both pure
functions of (weights, pool, scheduler arrays) so the engine can jit and
donate them:

  * ``prefill(w, kp, vp, ids, length, block_table)`` — run one prompt
    (padded to a length bucket) through the model, WRITE its K/V into the
    request's pages, return last-valid-position logits.
  * ``decode(w, kp, vp, tokens, positions, block_tables, active)`` — one
    token for every batch slot at once: write each token's K/V at its
    per-slot position, attend over the per-slot block table, return
    [slots, vocab] logits. Inactive slots are masked: their page write is
    routed out of bounds (dropped by XLA scatter semantics, same trick as
    ``paged_attention.update_pages``) and their logits are garbage the
    engine never reads.

``LlamaServingAdapter`` follows the ``models.llama.LlamaPipeline``
precedent of re-owning the model's weights as raw arrays and rebuilding
the block in jnp + ops.impl functions (the same math the Tensor ops
dispatch to, so serving numerics match ``generate``'s). Decode attention
is selected by the adapter's ``decode_kernel`` attribute
(``EngineConfig(decode_kernel=)`` sets it): ``"auto"`` uses the Pallas
paged kernel on TPU and the XLA reference path elsewhere; ``"pallas"``
requests the kernel and DEGRADES to the XLA fallback — warned and
counted in ``paddle_tpu_kernels_fallbacks_total``, never fatal — when
the backend/shape/dtype cannot honor it (``FLAGS_pallas_interpret``
forces the interpreted kernel off-TPU for parity testing); ``"xla"``
pins the fallback.

Quantized KV (``EngineConfig(kv_cache_dtype="int8")``): every per-layer
pool entry is an int8 ``(pages, scales)`` pair. All page writes
quantize-on-write (per-token-per-head absmax, the scale landing in the
same slot of the scale plane) and every read path dequantizes
in-attention — the paged kernel from its scale operands, the gather
paths right after the gather. Nothing else changes shape: the same
routing drives both layouts.

Any object exposing the same five attributes and two methods (see
``required_attrs``) can serve — the engine duck-types, it never imports a
model class. An optional ``dtype`` attribute names the KV-pool dtype;
without it the engine reads ``weights["embed"].dtype``. Two optional
entry points extend the surface: ``prefill_ext(w, kp, vp, ids, length,
cache_len, block_table)`` continues a prefill whose first ``cache_len``
tokens are already in the pages — required only when the engine enables
prefix caching or chunked prefill — and ``verify(w, kp, vp, tokens,
positions, draft_lens, block_tables, active)`` scores a K+1-token draft
window for every slot in one launch — required only when the engine
enables speculative decoding (``EngineConfig(speculate_tokens=)``).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.impl.activation import swiglu as _swiglu
from ..ops.impl.fused_ops import rope_qk as _rope_qk
from ..ops.impl.nn_ops import (
    scaled_dot_product_attention as _sdpa,
)
from ..ops.impl.nn_ops import rms_norm as _rms_norm

__all__ = ["LlamaServingAdapter", "build_adapter", "required_attrs"]

# the duck-typed adapter surface the engine relies on
required_attrs = (
    "num_layers", "num_kv_heads", "head_dim", "vocab_size", "weights",
    "prefill", "decode",
)


def _split_pages(pages):
    """(pages, scales) for an int8-quantized per-layer entry,
    (pages, None) for a plain float one."""
    if isinstance(pages, (tuple, list)):
        return pages[0], pages[1]
    return pages, None


def _row_matmul(x, w, spec):
    """The row-parallel contraction (attention output projection / FFN
    down projection) under tensor parallelism. ``spec`` is the engine's
    ``serving.sharding.TPSpec`` (None on an unsharded engine — this
    compiles the exact ``x @ w`` jaxpr the inline form did). Under
    ``tp_numerics="exact"`` BOTH operands are constrained to replicated
    before the dot — an all-gather of the sharded weight — so the
    reduction runs whole on every chip and the result is bit-identical
    to the unsharded program. ``"fast"`` leaves the operands sharded
    and GSPMD emits the Megatron partial-sum + all-reduce, whose
    cross-chip reduction order drifts ~1 ulp (docs/serving.md)."""
    if spec is not None and spec.exact:
        x = jax.lax.with_sharding_constraint(x, spec.replicated)
        w = jax.lax.with_sharding_constraint(w, spec.replicated)
    return x @ w


def _paged_attn(q, kp, vp, block_tables, lengths, kernel="auto"):
    # pallas imports stay function-scoped (the nn_ops.py pattern): plain
    # `import paddle_tpu` must not load — nor fail on — the TPU kernel
    # stack; these run at trace time only
    from ..core import flags
    from ..kernels.pallas._compat import record_fallback
    from ..kernels.pallas.paged_attention import (
        paged_attention,
        paged_attention_xla,
    )

    on_tpu = jax.default_backend() == "tpu"
    if kernel == "pallas":
        # explicit request: off-TPU it degrades (warn + count) unless
        # FLAGS_pallas_interpret pins the interpreted kernel (tests)
        use_pallas = on_tpu or bool(
            flags.get_flag("FLAGS_pallas_interpret")
        )
        if not use_pallas:
            record_fallback(
                "paged_attention", "backend",
                hint="set FLAGS_pallas_interpret to run the kernel "
                     "under the Pallas interpreter off-TPU instead",
            )
    elif kernel == "auto":
        use_pallas = on_tpu and flags.get_flag("FLAGS_use_pallas_kernels")
    elif kernel == "xla":
        use_pallas = False
    else:
        raise ValueError(
            f'decode_kernel must be "auto", "pallas" or "xla", got '
            f"{kernel!r}"
        )
    if use_pallas and on_tpu:
        # real-TPU tiling constraints: degrade, never raise (the
        # fallback computes the same math). Pages tile at
        # (sublane, 128) with the sublane minimum set by the pool
        # dtype — f32 8, bf16 16, int8 32.
        pages, scales = _split_pages(kp)
        min_sublane = {
            jnp.dtype(jnp.float32): 8,
            jnp.dtype(jnp.bfloat16): 16,
            jnp.dtype(jnp.int8): 32,
        }.get(jnp.dtype(pages.dtype))
        if (q.dtype not in (jnp.float32, jnp.bfloat16)
                or min_sublane is None):
            record_fallback("paged_attention", "dtype")
            use_pallas = False
        elif pages.shape[2] % min_sublane or q.shape[-1] % 128:
            record_fallback("paged_attention", "shape")
            use_pallas = False
    if use_pallas:
        return paged_attention(q, kp, vp, block_tables, lengths)
    return paged_attention_xla(q, kp, vp, block_tables, lengths)


def _write_prompt_pages(pages, kv, block_table, length):
    """Scatter a prompt's [S, kv_heads, d] K or V into its pages. Token t
    lands in page ``block_table[t // block_size]`` slot ``t % block_size``;
    padded tail positions (t >= length) are routed to a nonexistent page
    so the scatter drops them. The degenerate (offset 0) case of
    ``_write_chunk_pages`` — one routing implementation keeps the
    one-shot and chunked write paths bit-identical by construction."""
    return _write_chunk_pages(pages, kv, block_table, length, 0)


def _write_chunk_pages(pages, kv, block_table, length, cache_len):
    """``_write_prompt_pages`` with a position offset: chunk token t
    lands at GLOBAL position ``cache_len + t`` (chunked prefill / cached
    prefix continuation). Padded tail positions route out of bounds; the
    block-table gather clamps for them, then the write is dropped.

    Int8 pools quantize-on-write: the token's per-head scale is
    scattered into the scale plane with the same routing (dropped
    together with its page write)."""
    buf, scales = _split_pages(pages)
    n_blocks = buf.shape[1]
    block_size = buf.shape[2]
    s = kv.shape[0]
    t = jnp.arange(s)
    gpos = cache_len + t
    phys = jnp.where(t < length, block_table[gpos // block_size], n_blocks)
    slot = gpos % block_size
    if scales is None:
        return buf.at[:, phys, slot].set(
            jnp.swapaxes(kv, 0, 1).astype(buf.dtype)
        )
    from ..kernels.pallas.paged_attention import quantize_tokens

    q8, sc = quantize_tokens(kv)           # [S, kvh, d], [S, kvh]
    buf = buf.at[:, phys, slot].set(jnp.swapaxes(q8, 0, 1))
    scales = scales.at[:, phys, slot].set(jnp.swapaxes(sc, 0, 1))
    return (buf, scales)


def _write_window_pages(pages, kv, phys, slot):
    """Batched form of ``_write_chunk_pages``: scatter a [slots, S,
    kv_heads, d] token window into the pages at precomputed physical
    coordinates ``phys``/``slot`` [slots, S] (invalid positions carry
    ``phys == num_blocks`` so the scatter drops them — the same
    out-of-bounds routing every other page write uses)."""
    buf, scales = _split_pages(pages)
    if scales is None:
        vals = jnp.moveaxis(kv, 2, 0).astype(buf.dtype)  # [kv,slots,S,d]
        return buf.at[:, phys, slot].set(vals)
    from ..kernels.pallas.paged_attention import quantize_tokens

    q8, sc = quantize_tokens(kv)           # [slots,S,kvh,d], [slots,S,kvh]
    buf = buf.at[:, phys, slot].set(jnp.moveaxis(q8, 2, 0))
    scales = scales.at[:, phys, slot].set(jnp.moveaxis(sc, 2, 0))
    return (buf, scales)


def _window_routing(block_tables, pos, valid, n_blocks, bs_pg):
    """Physical scatter coordinates (phys, slot) for a [slots, S]
    window of GLOBAL positions: row token ``pos`` lands in page
    ``block_table[pos // bs_pg]`` at slot ``pos % bs_pg``; invalid
    positions route to the nonexistent page ``n_blocks`` so the
    scatter drops them — the out-of-bounds-drop contract every page
    write shares (the gather clamp alone would silently overwrite a
    live slot). One implementation serves the verify window write and
    decode's tensor-parallel write (a 1-token window)."""
    phys = jnp.where(
        valid,
        jnp.take_along_axis(
            block_tables,
            jnp.minimum(pos // bs_pg, block_tables.shape[1] - 1),
            axis=1,
        ),
        n_blocks,
    )
    return phys, pos % bs_pg


def _gather_context_batch(pages, block_tables):
    """``_gather_context`` for every slot at once: ``block_tables``
    [slots, P] gathers to ``[slots, P*bs, kv_heads, d]`` — slot s's
    logical KV timeline, position p at row p. Same layout, same
    reduction order as the single-sequence gather, just batched."""
    buf, scales = _split_pages(pages)
    g = buf[:, block_tables]               # [kv, slots, P, bs, d]
    if scales is not None:
        sc = scales[:, block_tables]       # [kv, slots, P, bs]
        g = g.astype(jnp.float32) * sc[..., None]
    g = jnp.moveaxis(g, 0, 3)              # [slots, P, bs, kv, d]
    return g.reshape(g.shape[0], -1, g.shape[3], g.shape[4])


def _gather_context(pages, block_table):
    """Materialize one sequence's logical KV timeline from its pages:
    ``[kv_heads, blocks, bs, d]`` gathered through ``block_table [P]``
    to ``[P*bs, kv_heads, d]`` — position p is row p. This is the
    chunk-prefill context layout: attention over it is computed in the
    exact ``scaled_dot_product_attention`` form the one-shot prefill
    (and ``generate``'s cached branch) uses, which keeps chunked and
    prefix-cached prefill BIT-identical to the one-shot program (the
    paged-einsum form of ``paged_attention_xla`` reduces in a different
    order and drifts by ~1 ulp — enough to flip a greedy argmax). An
    int8 pool dequantizes right after the gather — the byte-parity
    contract then becomes the documented int8 tolerance contract
    (docs/serving.md)."""
    buf, scales = _split_pages(pages)
    g = buf[:, block_table]                # [kv, P, bs, d]
    if scales is not None:
        sc = scales[:, block_table]        # [kv, P, bs]
        g = g.astype(jnp.float32) * sc[..., None]
    g = jnp.moveaxis(g, 0, 2)              # [P, bs, kv, d]
    return g.reshape(-1, g.shape[2], g.shape[3])


def _pages_geometry(entry):
    """(num_blocks, block_size) of one per-layer pool entry (plain
    array or int8 (pages, scales) pair)."""
    buf, _ = _split_pages(entry)
    return buf.shape[1], buf.shape[2]


class LlamaServingAdapter:
    """Paged-KV serving forward for a ``models.llama.LlamaForCausalLM``.

    Snapshots the model's weights at construction (serving is inference;
    call ``refresh()`` after a weight swap). Tied embeddings resolve the
    LM head to ``embed.T`` inside the staged program.
    """

    # decode attention path: "auto" | "pallas" | "xla" (module
    # docstring); the engine sets this from EngineConfig(decode_kernel=)
    decode_kernel = "auto"
    # tensor-parallel sharding spec (serving.sharding.TPSpec); the
    # engine sets this from EngineConfig(tp_degree=) — None (the
    # default) keeps every traced body byte-identical to the
    # single-chip program. The traced bodies consult it at two points:
    # the row-parallel matmuls (_row_matmul numerics contract) and the
    # decode-step page write (head-sliced scatter that stays
    # shard-local where update_pages' explicit head indices would
    # re-shard the pool under GSPMD).
    tp_spec = None

    def __init__(self, model):
        cfg = model.config
        if getattr(cfg, "num_experts", 0) > 0:
            raise NotImplementedError(
                "serving adapter: MoE Llama not supported yet (dense only)"
            )
        self.num_layers = cfg.num_hidden_layers
        self.num_heads = cfg.num_attention_heads
        self.num_kv_heads = cfg.num_key_value_heads
        self.head_dim = cfg.hidden_size // cfg.num_attention_heads
        self.hidden_size = cfg.hidden_size
        self.vocab_size = cfg.vocab_size
        self.rope_theta = cfg.rope_theta
        self.eps = cfg.rms_norm_eps
        self._model = model
        self.refresh()

    def refresh(self):
        """Re-snapshot weights from the source model."""
        m = self._model
        layers = []
        for blk in m.llama.layers:
            layers.append({
                "ln1": blk.input_layernorm.weight._data,
                "wq": blk.self_attn.q_proj.weight._data,
                "wk": blk.self_attn.k_proj.weight._data,
                "wv": blk.self_attn.v_proj.weight._data,
                "wo": blk.self_attn.o_proj.weight._data,
                "ln2": blk.post_attention_layernorm.weight._data,
                "wg": blk.mlp.gate_proj.weight._data,
                "wu": blk.mlp.up_proj.weight._data,
                "wd": blk.mlp.down_proj.weight._data,
            })
        self.weights = {
            "embed": m.llama.embed_tokens.weight._data,
            "layers": layers,
            "norm": m.llama.norm.weight._data,
            "head": (
                m.lm_head.weight._data if m.lm_head is not None else None
            ),
        }
        self.dtype = self.weights["embed"].dtype  # KV pool dtype

    # -- shared block math ---------------------------------------------------
    def _qkv(self, wl, h, b, s):
        q = (h @ wl["wq"]).reshape(b, s, self.num_heads, self.head_dim)
        k = (h @ wl["wk"]).reshape(b, s, self.num_kv_heads, self.head_dim)
        v = (h @ wl["wv"]).reshape(b, s, self.num_kv_heads, self.head_dim)
        return q, k, v

    def _mlp(self, wl, x):
        h = _rms_norm(x, wl["ln2"], epsilon=self.eps)
        return x + _row_matmul(
            _swiglu(h @ wl["wg"], h @ wl["wu"]), wl["wd"], self.tp_spec
        )

    def _logits(self, w, x):
        head = w["head"]
        if head is None:
            head = jnp.swapaxes(w["embed"], 0, 1)
        return x @ head

    # -- the two serving entry points ---------------------------------------
    def prefill(self, w, kp, vp, ids, length, block_table):
        """ids [S] (padded to a bucket), length scalar, block_table [P].
        Returns (logits [vocab] at position length-1, kp, vp)."""
        s = ids.shape[0]
        x = w["embed"][ids][None]                      # [1, S, hid]
        pos = jnp.arange(s, dtype=jnp.int32)[None]     # prompts start at 0
        kp, vp = list(kp), list(vp)
        for li in range(self.num_layers):
            wl = w["layers"][li]
            h = _rms_norm(x, wl["ln1"], epsilon=self.eps)
            q, k, v = self._qkv(wl, h, 1, s)
            q, k = _rope_qk(q, k, pos, base=self.rope_theta)
            kp[li] = _write_prompt_pages(kp[li], k[0], block_table, length)
            vp[li] = _write_prompt_pages(vp[li], v[0], block_table, length)
            if self.num_kv_heads != self.num_heads:
                rep = self.num_heads // self.num_kv_heads
                k = jnp.repeat(k, rep, axis=2)
                v = jnp.repeat(v, rep, axis=2)
            # causal attention over the in-flight prompt; right-padding is
            # invisible to valid queries under causality
            attn = _sdpa(q, k, v, is_causal=True)
            x = x + _row_matmul(
                attn.reshape(1, s, -1), wl["wo"], self.tp_spec
            )
            x = self._mlp(wl, x)
        x = _rms_norm(x, w["norm"], epsilon=self.eps)
        h_last = jnp.take(x[0], length - 1, axis=0)    # [hid]
        return self._logits(w, h_last), tuple(kp), tuple(vp)

    def prefill_ext(self, w, kp, vp, ids, length, cache_len, block_table):
        """Prefill CONTINUATION: run one chunk of a prompt whose first
        ``cache_len`` tokens are already in the pages (an earlier chunk,
        or a shared prefix forked from the cache). ids [S] (padded to a
        bucket) hold the chunk, length is its valid token count; chunk
        token t sits at global position ``cache_len + t``. Writes the
        chunk's K/V into the pages, attends every chunk token over the
        gathered page timeline (cached prefix + chunk-so-far, causal),
        and returns (logits [vocab] at the chunk's last valid position,
        kp, vp).

        Bit-parity contract: for the same tokens, any chunking of a
        prompt through this entry point yields page contents and final
        logits BYTE-identical to one ``prefill`` call (float32 pool;
        see docs/serving.md for the reduced-precision-pool caveat) —
        the attention is the same ``_sdpa`` masked form over the same
        values, and padded/garbage context rows are exact zeros in the
        softmax."""
        s = ids.shape[0]
        x = w["embed"][ids][None]                       # [1, S, hid]
        pos = (cache_len + jnp.arange(s, dtype=jnp.int32))[None]
        kp, vp = list(kp), list(vp)
        capacity = block_table.shape[0] * _pages_geometry(kp[0])[1]
        # keep[q, c]: context position c visible to chunk token q
        # (causal over the global timeline; unwritten/garbage rows fall
        # outside it and contribute exact zeros after the softmax)
        keep = (
            jnp.arange(capacity, dtype=jnp.int32)[None, :]
            <= pos[0][:, None]
        )[None, None]                                   # [1, 1, S, C]
        for li in range(self.num_layers):
            wl = w["layers"][li]
            h = _rms_norm(x, wl["ln1"], epsilon=self.eps)
            q, k, v = self._qkv(wl, h, 1, s)
            q, k = _rope_qk(q, k, pos, base=self.rope_theta)
            kp[li] = _write_chunk_pages(
                kp[li], k[0], block_table, length, cache_len
            )
            vp[li] = _write_chunk_pages(
                vp[li], v[0], block_table, length, cache_len
            )
            kc = _gather_context(kp[li], block_table)[None]  # [1, C, kv, d]
            vc = _gather_context(vp[li], block_table)[None]
            if self.num_kv_heads != self.num_heads:
                rep = self.num_heads // self.num_kv_heads
                kc = jnp.repeat(kc, rep, axis=2)
                vc = jnp.repeat(vc, rep, axis=2)
            attn = _sdpa(q, kc, vc, keep, is_causal=False)
            x = x + _row_matmul(
                attn.reshape(1, s, -1), wl["wo"], self.tp_spec
            )
            x = self._mlp(wl, x)
        x = _rms_norm(x, w["norm"], epsilon=self.eps)
        h_last = jnp.take(x[0], length - 1, axis=0)     # [hid]
        return self._logits(w, h_last), tuple(kp), tuple(vp)

    def decode(self, w, kp, vp, tokens, positions, block_tables, active):
        """tokens/positions [slots], block_tables [slots, P], active
        [slots] bool. Returns (logits [slots, vocab], kp, vp)."""
        from ..kernels.pallas.paged_attention import update_pages

        b = tokens.shape[0]
        n_blocks, bs_pg = _pages_geometry(kp[0])
        capacity = block_tables.shape[1] * bs_pg
        # inactive slots: write position at capacity -> update_pages drops
        write_pos = jnp.where(active, positions, capacity)
        lengths = positions + 1   # the new token attends to itself
        if self.tp_spec is not None:
            # sharded pool: precompute the head-sliced scatter routing
            # (_write_window_pages with a 1-token window). update_pages
            # scatters with EXPLICIT kv-head indices, which GSPMD
            # cannot prove shard-local on a head-sharded pool — the
            # window form leaves the head dim a full slice, so every
            # chip scatters only its own heads. Values written are
            # identical either way (same routing trick, same casts).
            wpos = write_pos[:, None]                  # [slots, 1]
            dphys, dslot = _window_routing(
                block_tables, wpos, wpos < capacity, n_blocks, bs_pg,
            )
        x = w["embed"][tokens]                         # [slots, hid]
        kp, vp = list(kp), list(vp)
        for li in range(self.num_layers):
            wl = w["layers"][li]
            h = _rms_norm(x, wl["ln1"], epsilon=self.eps)
            q, k, v = self._qkv(wl, h[:, None, :], b, 1)
            q, k = _rope_qk(q, k, positions[:, None], base=self.rope_theta)
            if self.tp_spec is not None:
                kp[li] = _write_window_pages(kp[li], k, dphys, dslot)
                vp[li] = _write_window_pages(vp[li], v, dphys, dslot)
            else:
                kp[li], vp[li] = update_pages(
                    kp[li], vp[li], k[:, 0], v[:, 0], block_tables,
                    write_pos,
                )
            attn = _paged_attn(
                q[:, 0], kp[li], vp[li], block_tables, lengths,
                kernel=self.decode_kernel,
            )                                          # [slots, heads, d]
            x = x + _row_matmul(
                attn.reshape(b, -1), wl["wo"], self.tp_spec
            )
            x = self._mlp(wl, x)
        x = _rms_norm(x, w["norm"], epsilon=self.eps)
        return self._logits(w, x), tuple(kp), tuple(vp)

    def verify(self, w, kp, vp, tokens, positions, draft_lens,
               block_tables, active):
        """Speculative verification: score a K+1-token window for every
        slot in ONE launch. ``tokens`` [slots, S] (S = K+1) holds each
        slot's pending ``last_token`` at column 0 and its drafted
        continuation after it; window token j sits at GLOBAL position
        ``positions[slot] + j``. ``draft_lens`` [slots] counts valid
        draft tokens, so columns 0..draft_lens are real and columns
        with index > ``draft_lens`` are padding: their page writes are
        routed out of bounds and their logits are garbage the engine
        never reads — same for inactive slots.
        Returns (logits [slots, S, vocab], kp, vp) where row j scores
        the token FOLLOWING position ``positions[slot] + j``.

        Bit-parity contract: attention runs in the exact ``_sdpa``
        masked form over the gathered page timeline that ``prefill_ext``
        (and ``generate``'s cached branch) uses — the form PR 8 proved
        byte-identical to the one-shot program — and each slot's rows
        reduce independently of the batch dimension, so row 0's logits
        (and the K/V written for accepted positions) are byte-identical
        to what the plain decode step would have produced. A rejected
        position's write is DEAD: the engine advances ``num_cached``
        only by the accepted count, the causal ``keep`` mask of every
        later launch stops at the query's own position, and a later
        write at the same position overwrites it."""
        b, s = tokens.shape
        n_blocks, bs_pg = _pages_geometry(kp[0])
        capacity = block_tables.shape[1] * bs_pg
        offs = jnp.arange(s, dtype=jnp.int32)[None]        # [1, S]
        pos = positions[:, None] + offs                    # [slots, S]
        valid = (
            active[:, None]
            & (offs <= draft_lens[:, None])
            & (pos < capacity)
        )
        phys, slot = _window_routing(
            block_tables, pos, valid, n_blocks, bs_pg,
        )
        # keep[q, c] per slot: context position c visible to window
        # token q — causal over the global timeline, so a valid query
        # only ever sees history plus THIS launch's earlier writes
        # (stale rejected-draft rows sit beyond it and mask to exact
        # zeros after the softmax)
        keep = (
            jnp.arange(capacity, dtype=jnp.int32)[None, None, :]
            <= pos[:, :, None]
        )[:, None]                                         # [b, 1, S, C]
        x = w["embed"][tokens]                             # [b, S, hid]
        kp, vp = list(kp), list(vp)
        for li in range(self.num_layers):
            wl = w["layers"][li]
            h = _rms_norm(x, wl["ln1"], epsilon=self.eps)
            q, k, v = self._qkv(wl, h, b, s)
            q, k = _rope_qk(q, k, pos, base=self.rope_theta)
            kp[li] = _write_window_pages(kp[li], k, phys, slot)
            vp[li] = _write_window_pages(vp[li], v, phys, slot)
            kc = _gather_context_batch(kp[li], block_tables)
            vc = _gather_context_batch(vp[li], block_tables)
            if self.num_kv_heads != self.num_heads:
                rep = self.num_heads // self.num_kv_heads
                kc = jnp.repeat(kc, rep, axis=2)
                vc = jnp.repeat(vc, rep, axis=2)
            attn = _sdpa(q, kc, vc, keep, is_causal=False)
            x = x + _row_matmul(
                attn.reshape(b, s, -1), wl["wo"], self.tp_spec
            )
            x = self._mlp(wl, x)
        x = _rms_norm(x, w["norm"], epsilon=self.eps)
        return self._logits(w, x), tuple(kp), tuple(vp)


def build_adapter(model):
    """Resolve the adapter for ``model``: pass-through for objects already
    exposing the adapter surface, ``LlamaServingAdapter`` for Llama."""
    if all(hasattr(model, a) for a in required_attrs):
        return model
    from ..models.llama import LlamaForCausalLM

    if isinstance(model, LlamaForCausalLM):
        return LlamaServingAdapter(model)
    raise TypeError(
        f"cannot serve {type(model).__name__}: pass an adapter exposing "
        f"{required_attrs} or a LlamaForCausalLM"
    )
