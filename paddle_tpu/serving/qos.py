"""Multi-tenant QoS under the HTTP front door.

One noisy tenant must not eat another's p99. This module gives the
serving stack tenant identity plus the three controls that make a
shared fleet safe to expose (the fairness/isolation discipline of the
vLLM/Orca serving lineage, applied at the admission boundary PR 11
built):

  * **Identity** — API-key -> tenant mapping (``Authorization: Bearer
    <key>`` on the wire) or a trusted ``X-Tenant`` header; requests
    with no identity fall to ``default_tenant`` (or are rejected when
    it is None).
  * **Scheduling** — strict priority classes, weighted fair-share
    within a class. Start-time-fair-queuing virtual time over the
    fleet's bounded pending queue: each dispatch advances its
    tenant's virtual finish tag by ``cost / weight`` (cost = the
    request's ``max_new_tokens``), and the next dispatch is the
    lowest ``(priority, tag)`` — a 3:1 weight split admits ~3:1
    tokens under saturation, and an idle tenant's first request never
    waits behind a backlog it didn't create.
  * **Shedding** — per-tenant quotas (``max_inflight``) and token-rate
    limits (token bucket over estimated decode tokens) reject with a
    :class:`QoSRejection` the server maps to HTTP 429 +
    ``Retry-After``; a tenant whose own SLO burn is *sustained* is
    shed first once the pending queue crosses
    ``shed_burning_at x max_pending`` — load shedding lands on the
    tenant that is already over budget, not on everyone.

Telemetry: per-tenant latency digests and SLO burn reuse the exact
primitives the engine/fleet use (``LatencyDigest``, ``SLOTracker``,
``burn_from_counts``), exported at pull time as
``paddle_tpu_serving_latency*{tenant=}`` /
``paddle_tpu_serving_slo_*{tenant=}`` /
``paddle_tpu_serving_tenant_*{tenant=}`` series through weakref
collector views (zero hot-path registry cost). Tenant ids also ride
the journal ADMIT record (``"tn"``), so a crash replay restores the
per-tenant inflight accounting.
"""
from __future__ import annotations

import itertools
import threading
import time
import weakref

from ..observability.latency import LatencyDigest, SLOConfig, SLOTracker

__all__ = [
    "TenantPolicy", "QoSConfig", "QoS", "QoSRejection",
    "UnknownTenantError",
]

# monotonic ids for collector-view names (labels/views must never
# alias across QoS lifetimes — the engine/journal counter rationale)
_qos_counter = itertools.count(1)

_DEFAULT_TENANT = "default"


class QoSRejection(Exception):
    """Admission refused by QoS policy; the server maps this to HTTP
    429 with ``Retry-After: ceil(retry_after)``."""

    def __init__(self, tenant, reason, retry_after=1.0, message=None):
        self.tenant = tenant
        self.reason = reason          # "quota" | "rate" | "slo-burn"
        self.retry_after = max(0.0, float(retry_after))
        super().__init__(
            message or f"tenant {tenant!r} shed ({reason}); retry "
            f"after {self.retry_after:.1f}s"
        )


class UnknownTenantError(Exception):
    """No tenant identity could be established (bad API key, or no
    identity with ``default_tenant=None``); the server maps this to
    HTTP 401."""


class TenantPolicy:
    """Per-tenant knobs. ``weight`` is the fair-share proportion
    within a priority class; ``priority`` classes are strict (0 beats
    1 whenever class 0 has pending work); ``max_inflight`` bounds
    concurrently admitted requests; ``tokens_per_s`` caps the
    estimated decode-token admission rate (burst defaults to one
    second of rate, floor 1)."""

    def __init__(self, weight=1.0, priority=1, max_inflight=None,
                 tokens_per_s=None, burst_tokens=None, slo=None):
        if not weight > 0:
            raise ValueError(f"weight must be > 0, got {weight}")
        if max_inflight is not None and max_inflight < 1:
            raise ValueError(
                f"max_inflight must be >= 1 or None, got {max_inflight}"
            )
        if tokens_per_s is not None and not tokens_per_s > 0:
            raise ValueError(
                f"tokens_per_s must be > 0 or None, got {tokens_per_s}"
            )
        self.weight = float(weight)
        self.priority = int(priority)
        self.max_inflight = (
            None if max_inflight is None else int(max_inflight)
        )
        self.tokens_per_s = (
            None if tokens_per_s is None else float(tokens_per_s)
        )
        self.burst_tokens = (
            max(1.0, float(burst_tokens)) if burst_tokens is not None
            else (
                max(1.0, self.tokens_per_s)
                if self.tokens_per_s is not None else None
            )
        )
        if slo is not None and not isinstance(slo, SLOConfig):
            raise ValueError(
                f"slo must be an SLOConfig or None, got "
                f"{type(slo).__name__}"
            )
        self.slo = slo


class QoSConfig:
    """QoS layer configuration.

    ``tenants`` maps tenant name -> :class:`TenantPolicy` (unknown
    tenant names get a fresh default policy on first sight);
    ``api_keys`` maps bearer key -> tenant name; ``default_tenant``
    names the tenant for unauthenticated requests (None rejects
    them); ``slo`` is the default per-tenant SLO applied where a
    policy doesn't carry its own; ``shed_burning_at`` is the pending
    backlog fraction past which sustained-burning tenants are shed
    first."""

    def __init__(self, tenants=None, api_keys=None,
                 default_tenant=_DEFAULT_TENANT, slo=None,
                 shed_burning_at=0.5):
        tenants = dict(tenants or {})
        for name, pol in tenants.items():
            if not isinstance(pol, TenantPolicy):
                raise ValueError(
                    f"tenants[{name!r}] must be a TenantPolicy, got "
                    f"{type(pol).__name__}"
                )
        self.tenants = tenants
        self.api_keys = dict(api_keys or {})
        self.default_tenant = default_tenant
        if slo is not None and not isinstance(slo, SLOConfig):
            raise ValueError(
                f"slo must be an SLOConfig or None, got "
                f"{type(slo).__name__}"
            )
        self.slo = slo
        if not 0.0 <= shed_burning_at <= 1.0:
            raise ValueError(
                f"shed_burning_at must be in [0, 1], got "
                f"{shed_burning_at}"
            )
        self.shed_burning_at = float(shed_burning_at)


class _TokenBucket:
    """Classic token bucket over *estimated* decode tokens (charged at
    admission — the cheap place to push back; an admitted request's
    true cost is bounded by the estimate)."""

    __slots__ = ("rate", "burst", "tokens", "stamp")

    def __init__(self, rate, burst):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self.stamp = time.monotonic()

    def try_take(self, n, now=None):
        """Take ``n`` tokens; returns 0.0 on success, else the seconds
        until ``n`` tokens will be available (the Retry-After)."""
        now = time.monotonic() if now is None else now
        self.tokens = min(
            self.burst, self.tokens + (now - self.stamp) * self.rate
        )
        self.stamp = now
        if self.tokens >= n:
            self.tokens -= n
            return 0.0
        return (n - self.tokens) / self.rate


class _TenantState:
    """Live accounting for one tenant (policy + fair-share virtual
    time + inflight set + digests/SLO + counters)."""

    def __init__(self, name, policy, default_slo):
        self.name = name
        self.policy = policy
        self.vtime = 0.0              # fair-queuing virtual finish tag
        self.inflight: set = set()    # admitted-not-finished rids
        self.bucket = (
            _TokenBucket(policy.tokens_per_s, policy.burst_tokens)
            if policy.tokens_per_s is not None else None
        )
        self.latency = {
            p: LatencyDigest() for p in ("queue", "ttft", "tpot", "e2e")
        }
        slo_cfg = policy.slo or default_slo
        self.slo = SLOTracker(slo_cfg) if slo_cfg is not None else None
        # counters (plain attributes; exported by the collector view)
        self.received = 0
        self.finished = 0
        self.aborted = 0
        self.shed_quota = 0
        self.shed_rate = 0
        self.shed_burn = 0
        self.shed_queue = 0
        self.output_tokens = 0
        self.restored = 0
        self.migrated = 0


class QoS:
    """The runtime QoS object: identity resolution, fair-share
    selection over the fleet pending queue, quota/rate/burn shedding,
    and per-tenant telemetry. Thread-safe (the HTTP server calls it
    from handler threads, the fleet from its stepping thread)."""

    def __init__(self, config=None):
        self.config = config or QoSConfig()
        self.qos_id = f"{next(_qos_counter)}"
        self._lock = threading.Lock()
        self._states: dict = {}       # tenant name -> _TenantState
        self._vclock = 0.0            # global virtual time
        _register_view(self, self.qos_id)
        # eagerly materialize configured tenants so their series exist
        # (and their buckets start full) before the first request
        for name in self.config.tenants:
            self._state(name)

    # -- identity ------------------------------------------------------------
    def resolve(self, headers):
        """Tenant name from request headers (case-insensitive keys):
        ``Authorization: Bearer <key>`` through the API-key map wins,
        then a trusted ``X-Tenant`` header, then ``default_tenant``.
        A *presented-but-unknown* key and an identity-free request
        under ``default_tenant=None`` raise
        :class:`UnknownTenantError` (HTTP 401)."""
        lower = {str(k).lower(): v for k, v in dict(headers).items()}
        auth = lower.get("authorization")
        if auth:
            key = auth.strip()
            if key.lower().startswith("bearer "):
                key = key[7:].strip()
            tenant = self.config.api_keys.get(key)
            if tenant is None:
                raise UnknownTenantError("unknown API key")
            return tenant
        tenant = lower.get("x-tenant")
        if tenant:
            return str(tenant)
        if self.config.default_tenant is None:
            raise UnknownTenantError(
                "no tenant identity and anonymous access is disabled"
            )
        return self.config.default_tenant

    def _state(self, tenant):
        name = tenant if tenant is not None else (
            self.config.default_tenant or _DEFAULT_TENANT
        )
        st = self._states.get(name)
        if st is None:
            policy = self.config.tenants.get(name) or TenantPolicy()
            st = _TenantState(name, policy, self.config.slo)
            self._states[name] = st
            _register_tenant_latency_view(self, st)
        return st

    # -- admission -----------------------------------------------------------
    def try_admit(self, tenant, cost_tokens, backlog=0, capacity=None):
        """Policy gate BEFORE the backend sees the request. Raises
        :class:`QoSRejection` (-> 429 + Retry-After) on a quota,
        rate, or burn-shed violation; returns the tenant's state on
        success (nothing is charged until :meth:`on_admit`, except the
        rate bucket, which charges here — the rejected request must
        not consume budget twice on retry)."""
        with self._lock:
            st = self._state(tenant)
            pol = st.policy
            if (pol.max_inflight is not None
                    and len(st.inflight) >= pol.max_inflight):
                st.shed_quota += 1
                raise QoSRejection(
                    st.name, "quota", retry_after=1.0,
                    message=(
                        f"tenant {st.name!r} at max_inflight="
                        f"{pol.max_inflight}"
                    ),
                )
            # sustained-burn shed: once the shared queue is past the
            # threshold, the tenant already burning ITS error budget
            # is pushed back first (everyone else keeps admitting)
            if (capacity is not None and st.slo is not None
                    and backlog >= self.config.shed_burning_at * capacity
                    and st.slo.burning()):
                st.shed_burn += 1
                raise QoSRejection(
                    st.name, "slo-burn", retry_after=1.0,
                    message=(
                        f"tenant {st.name!r} shed: sustained SLO burn "
                        f"with {backlog} request(s) queued"
                    ),
                )
            if st.bucket is not None:
                wait = st.bucket.try_take(max(1.0, float(cost_tokens)))
                if wait > 0.0:
                    st.shed_rate += 1
                    raise QoSRejection(
                        st.name, "rate", retry_after=wait,
                        message=(
                            f"tenant {st.name!r} over "
                            f"{pol.tokens_per_s:g} tokens/s"
                        ),
                    )
            return st

    def on_admit(self, req, restored=False):
        """Account an accepted request (tenant read off the Request —
        the journal-restored path and the live path share it), and
        stamp its fair-queuing virtual tags: start = max(tenant's last
        finish, the global virtual clock), finish = start +
        cost/weight. Stamped ONCE at admission — a parked request's
        tag must age relative to later arrivals, which is what lets a
        backlogged low-weight tenant interleave instead of starve."""
        with self._lock:
            st = self._state(getattr(req, "tenant", None))
            self._stamp(st, req)
            st.inflight.add(req.request_id)
            st.received += 1
            if restored:
                st.restored += 1

    def _stamp(self, st, req):
        cost = float(req.sampling_params.max_new_tokens)
        start = max(st.vtime, self._vclock)
        st.vtime = start + cost / st.policy.weight
        req._qos_vstart = start
        req._qos_vtag = st.vtime

    def on_migrate(self, req):
        """A scale-down / rolling restart moved this in-flight request
        off its replica: count it, NOTHING else. Deliberately no
        re-stamp (the admission-time ``_qos_vstart``/``_qos_vtag``
        fair-queue tags must survive — a migrated request keeps its
        place in the tenant's virtual timeline, it did not arrive
        again), no ``received`` increment (shed/receive accounting
        would see phantom traffic), and the rid stays in the tenant's
        ``inflight`` set (it still is)."""
        with self._lock:
            self._state(getattr(req, "tenant", None)).migrated += 1

    def count_queue_shed(self, tenant):
        """The backend's bounded queue refused (fleet ``max_pending``
        / engine admission): counted per tenant so a saturated
        queue's pushback is attributable."""
        with self._lock:
            self._state(tenant).shed_queue += 1

    # -- weighted fair share over the pending queue --------------------------
    def select(self, pending):
        """Pick the next entry of ``pending`` (fleet ``_pending``
        deque of FleetRequests) to dispatch: delivered-but-parked
        entries first (the caller purges them), else the lowest
        ``(priority class, admission-stamped virtual finish tag)``.
        Ties keep FIFO order. Returns None for an empty queue."""
        with self._lock:
            best = None
            best_key = None
            for freq in pending:
                if freq.done:
                    return freq
                req = freq.request
                st = self._state(getattr(req, "tenant", None))
                tag = getattr(req, "_qos_vtag", None)
                if tag is None:
                    # admitted before this QoS was attached: stamp now
                    self._stamp(st, req)
                    tag = req._qos_vtag
                key = (st.policy.priority, tag)
                if best_key is None or key < best_key:
                    best, best_key = freq, key
            return best

    def on_dispatch(self, req):
        """Advance the global virtual clock to the dispatched
        request's start tag, so tenants arriving after a long idle
        period stamp from the present instead of banking credit."""
        with self._lock:
            start = getattr(req, "_qos_vstart", None)
            if start is not None:
                self._vclock = max(self._vclock, start)

    # -- completion ----------------------------------------------------------
    def on_finish(self, req):
        """Close the accounting for one finished request: inflight
        released, latency digests + SLO window fed (aborts excluded —
        the ``record_finish`` convention), output tokens counted.
        Idempotent per rid."""
        with self._lock:
            st = self._state(getattr(req, "tenant", None))
            if req.request_id not in st.inflight:
                return
            st.inflight.discard(req.request_id)
            st.finished += 1
            n_out = len(req.output_token_ids)
            st.output_tokens += n_out
            if req.finish_reason == "aborted":
                st.aborted += 1
                return
            tl = req.timeline
            tpot = tl.tpot_s(n_out)
            for phase, value in (
                ("queue", tl.queue_wait_s), ("ttft", tl.ttft_s),
                ("tpot", tpot), ("e2e", tl.e2e_s),
            ):
                if value is not None:
                    st.latency[phase].record(value)
            if st.slo is not None:
                st.slo.record(ttft_s=tl.ttft_s, tpot_s=tpot)

    # -- introspection -------------------------------------------------------
    def attach(self, fleet):
        """Install this QoS on a Fleet: the fleet's dispatch sweep
        consults :meth:`select`/:meth:`on_dispatch`, and any requests
        the fleet already holds (journal replay ran in its
        constructor) are folded into the inflight accounting."""
        if fleet.qos is self:
            return  # already attached; don't re-account pending
        fleet.qos = self
        for freq in list(fleet._pending):
            if not freq.done:
                self.on_admit(freq.request, restored=True)

    def tenants(self):
        with self._lock:
            return sorted(self._states)

    def inflight(self, tenant):
        with self._lock:
            return len(self._state(tenant).inflight)

    def snapshot(self):
        """{tenant: counters} — tests and the CLI read this."""
        with self._lock:
            return {
                name: {
                    "inflight": len(st.inflight),
                    "received": st.received,
                    "finished": st.finished,
                    "aborted": st.aborted,
                    "restored": st.restored,
                    "migrated": st.migrated,
                    "shed_quota": st.shed_quota,
                    "shed_rate": st.shed_rate,
                    "shed_burn": st.shed_burn,
                    "shed_queue": st.shed_queue,
                    "output_tokens": st.output_tokens,
                }
                for name, st in self._states.items()
            }


# -- telemetry views ---------------------------------------------------------
_TENANT_COUNTERS = {
    "received": "paddle_tpu_serving_tenant_requests_total",
    "finished": "paddle_tpu_serving_tenant_finished_total",
    "aborted": "paddle_tpu_serving_tenant_aborted_total",
    "restored": "paddle_tpu_serving_tenant_restored_total",
    "migrated": "paddle_tpu_serving_tenant_migrated_total",
    "shed_quota": "paddle_tpu_serving_tenant_shed_quota_total",
    "shed_rate": "paddle_tpu_serving_tenant_shed_rate_total",
    "shed_burn": "paddle_tpu_serving_tenant_shed_burn_total",
    "shed_queue": "paddle_tpu_serving_tenant_shed_queue_total",
    "output_tokens": "paddle_tpu_serving_tenant_output_tokens_total",
}


def _register_view(qos, qos_id):
    """Pull-time collector over every tenant of one QoS (weakref: a
    collected QoS unregisters itself). Best-effort: telemetry must
    never fail admission."""
    try:
        from ..observability import MetricFamily, get_registry
    except Exception:
        # analysis: allow(broad-except) observability is optional here
        return
    ref = weakref.ref(qos)

    def collect():
        q = ref()
        if q is None:
            return None
        fams = []
        with q._lock:
            states = list(q._states.values())
        counters = {
            series: MetricFamily(series, "counter")
            for series in _TENANT_COUNTERS.values()
        }
        inflight = MetricFamily(
            "paddle_tpu_serving_tenant_inflight", "gauge"
        )
        burn = MetricFamily(
            "paddle_tpu_serving_slo_burn_rate", "gauge"
        )
        burning = MetricFamily(
            "paddle_tpu_serving_slo_burning", "gauge"
        )
        for st in states:
            label = {"tenant": st.name}
            for attr, series in _TENANT_COUNTERS.items():
                counters[series].add(getattr(st, attr), label)
            inflight.add(len(st.inflight), label)
            if st.slo is not None:
                for sig, v in sorted(st.slo.burn_rates().items()):
                    if v is not None:
                        burn.add(v, {**label, "signal": sig})
                burning.add(
                    1.0 if st.slo.burning() else 0.0, label
                )
        fams.extend(counters.values())
        fams.append(inflight)
        if burn.samples:
            fams.append(burn)
        if burning.samples:
            fams.append(burning)
        return fams

    try:
        get_registry().register_collector(f"serving.qos.{qos_id}",
                                          collect)
    except Exception:
        # analysis: allow(broad-except) telemetry is best-effort
        pass


def _register_tenant_latency_view(qos, st):
    """Per-tenant latency digest view: the same
    ``paddle_tpu_serving_latency*`` families the engine exports, with
    a ``tenant`` label instead of an ``engine`` one (the registry
    merges same-name families across collectors)."""
    try:
        from ..observability.metrics import register_latency_view
    except Exception:
        # analysis: allow(broad-except) observability is optional here
        return
    ref = weakref.ref(st)

    def latency_view():
        s = ref()
        return None if s is None else s.latency

    try:
        register_latency_view(
            f"serving.qos.{qos.qos_id}.{st.name}", latency_view,
            "paddle_tpu_serving_latency", labels={"tenant": st.name},
        )
    except Exception:
        # analysis: allow(broad-except) telemetry is best-effort
        pass
