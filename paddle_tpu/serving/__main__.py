"""CLI entry point: start the HTTP front door without writing Python.

    python -m paddle_tpu.serving serve --model tiny --port 8000 \
        [--replicas 2 --journal-dir DIR --compile-cache DIR \
         --tp-degree N --api-key KEY=TENANT ...]

Bad configuration exits non-zero with a named error on stderr
(``error: ConfigError: ...``) instead of a stack trace.
"""
from __future__ import annotations

import argparse
import sys
import time


class ConfigError(Exception):
    """Invalid CLI configuration (named in the exit diagnostic)."""


def _build_model(name, tp_degree):
    from ..models.llama import LlamaConfig, LlamaForCausalLM

    presets = {
        "tiny": lambda: LlamaConfig.tiny(),
        "tiny-moe": lambda: LlamaConfig.tiny(num_experts=4),
    }
    factory = presets.get(name)
    if factory is None:
        raise ConfigError(
            f"unknown model {name!r} (available: "
            f"{', '.join(sorted(presets))})"
        )
    cfg = factory()
    if cfg.num_attention_heads % max(tp_degree, 1):
        raise ConfigError(
            f"tp-degree {tp_degree} does not divide "
            f"{cfg.num_attention_heads} attention heads"
        )
    return LlamaForCausalLM(cfg)


def _parse_api_keys(pairs):
    keys = {}
    for pair in pairs or ():
        key, sep, tenant = pair.partition("=")
        if not sep or not key or not tenant:
            raise ConfigError(
                f"--api-key must be KEY=TENANT, got {pair!r}"
            )
        keys[key] = tenant
    return keys


def _build_backend(args):
    from . import Engine, EngineConfig, Fleet, FleetConfig

    if args.tp_degree < 1:
        raise ConfigError(
            f"--tp-degree must be >= 1, got {args.tp_degree}"
        )
    if not 0 <= args.port <= 65535:
        raise ConfigError(f"--port must be in [0, 65535], got {args.port}")
    if args.replicas < 0:
        raise ConfigError(
            f"--replicas must be >= 0, got {args.replicas}"
        )
    model = _build_model(args.model, args.tp_degree)
    try:
        engine_cfg = EngineConfig(
            max_batch_slots=args.max_batch_slots,
            max_model_len=args.max_model_len,
            compile_cache=args.compile_cache,
            tp_degree=args.tp_degree,
            journal=(
                args.journal_dir if args.replicas == 0 else None
            ),
        )
        if args.replicas > 0:
            return Fleet(model, engine_cfg, FleetConfig(
                num_replicas=args.replicas,
                max_pending=args.max_pending,
                journal_dir=args.journal_dir,
            ))
        return Engine(model, engine_cfg)
    except ValueError as e:
        # engine/fleet config validation becomes a named CLI error
        raise ConfigError(str(e))


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.serving",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    sub = parser.add_subparsers(dest="cmd")
    sp = sub.add_parser(
        "serve", help="start the HTTP API server (see docs/serving.md)"
    )
    sp.add_argument("--model", required=True,
                    help="model preset name (e.g. tiny)")
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, default=8000,
                    help="0 binds an ephemeral port (printed at start)")
    sp.add_argument("--journal-dir", default=None,
                    help="durable request journal directory")
    sp.add_argument("--compile-cache", default=None,
                    help="persistent compile cache directory")
    sp.add_argument("--tp-degree", type=int, default=1)
    sp.add_argument("--replicas", type=int, default=0,
                    help="0 = single engine, N >= 1 = fleet of N")
    sp.add_argument("--max-pending", type=int, default=None,
                    help="fleet bounded-admission queue depth")
    sp.add_argument("--max-batch-slots", type=int, default=8)
    sp.add_argument("--max-model-len", type=int, default=2048)
    sp.add_argument("--api-key", action="append", metavar="KEY=TENANT",
                    help="map a bearer API key to a tenant (repeatable)")
    args = parser.parse_args(argv)
    if args.cmd != "serve":
        parser.print_help(sys.stderr)
        return 2
    try:
        # cheap flag validation first, so a bad --api-key fails before
        # the (expensive) model + engine build
        api_keys = _parse_api_keys(args.api_key)
        backend = _build_backend(args)
        from .qos import QoSConfig
        from .server import serve as _serve

        qos_cfg = QoSConfig(api_keys=api_keys)
        srv = _serve(
            backend, host=args.host, port=args.port, qos=qos_cfg
        )
    except ConfigError as e:
        print(f"error: {type(e).__name__}: {e}", file=sys.stderr)
        return 2
    except OSError as e:
        print(f"error: BindError: {e}", file=sys.stderr)
        return 2
    print(
        f"paddle_tpu serving on {srv.url} "
        f"(model={args.model}, "
        f"{'fleet of ' + str(args.replicas) if args.replicas else 'engine'}"
        ")",
        flush=True,
    )
    try:
        # foreground until SIGTERM drains + closes (or Ctrl-C)
        while not srv._closed:
            time.sleep(0.2)
    except KeyboardInterrupt:
        srv.drain(timeout=5.0)
        srv.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
