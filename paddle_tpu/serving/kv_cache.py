"""Paged KV-cache: ref-counted block manager over a preallocated pool.

The vLLM/PagedAttention (SOSP '23) memory design on the TPU-native page
layout already used by ``kernels/pallas/paged_attention``: the physical
cache is ONE preallocated array per layer,
``[num_kv_heads, num_blocks, block_size, head_dim]``, and every request
owns an ordered list of block ids (its block table). Because blocks are
ref-counted, a future prefix-sharing pass only needs ``fork()`` — two
requests mapping the same prompt blocks — with copy-on-write left to the
caller; the free-list is LIFO so hot blocks are reused while still in
cache.

Allocation policy lives in the ENGINE (admission control, preemption);
this module only enforces the invariants: a block is reusable exactly when
its refcount returns to zero, and the pool's high-water mark is tracked so
tests can assert blocks actually return to the free-list.
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["BlockManager", "KVPool"]


class BlockManager:
    """Ref-counted free-list over ``num_blocks`` logical blocks of
    ``block_size`` tokens each."""

    def __init__(self, num_blocks, block_size):
        if num_blocks < 1 or block_size < 1:
            raise ValueError(
                f"need num_blocks >= 1 and block_size >= 1, got "
                f"{num_blocks}/{block_size}"
            )
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # LIFO: most-recently-freed block is re-allocated first
        self._free = list(range(self.num_blocks - 1, -1, -1))
        self._ref = [0] * self.num_blocks
        self.high_water = 0   # max blocks ever simultaneously in use

    # -- accounting ---------------------------------------------------------
    @property
    def num_free(self):
        return len(self._free)

    @property
    def num_used(self):
        return self.num_blocks - len(self._free)

    def utilization(self):
        return self.num_used / self.num_blocks

    def blocks_needed(self, num_tokens):
        """Blocks required to hold ``num_tokens`` cache slots."""
        return -(-int(num_tokens) // self.block_size)

    def ref_count(self, block_id):
        """Current reference count of one block (0 == free). The prefix
        cache uses this to tell reclaimable cached blocks (cache is the
        only owner) from blocks live requests still read."""
        return self._ref[block_id]

    def can_allocate(self, n):
        return len(self._free) >= n

    # -- lifecycle ----------------------------------------------------------
    def allocate(self, n):
        """Take ``n`` blocks off the free-list (refcount 1 each)."""
        if n > len(self._free):
            raise RuntimeError(
                f"KV pool exhausted: need {n} blocks, {len(self._free)} "
                f"free of {self.num_blocks}"
            )
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._ref[b] = 1
        self.high_water = max(self.high_water, self.num_used)
        return out

    def fork(self, block_ids):
        """Share existing blocks with a second owner (prefix sharing):
        refcount++ per block, no data movement."""
        for b in block_ids:
            if self._ref[b] < 1:
                raise RuntimeError(f"fork of free block {b}")
            self._ref[b] += 1

    def free(self, block_ids):
        """Drop one reference per block; blocks return to the free-list
        when the last owner releases them."""
        for b in block_ids:
            if self._ref[b] < 1:
                raise RuntimeError(f"double free of block {b}")
            self._ref[b] -= 1
            if self._ref[b] == 0:
                self._free.append(b)


def _zeros_factory(shape, dtype, sharding):
    """Allocator for one pool plane. With a ``sharding`` the zeros
    program is jitted with ``out_shardings`` so each chip materializes
    ONLY its shard — a pool sized to N chips' combined KV budget must
    never transiently exist whole on one chip (that transient is
    exactly the single-chip RESOURCE_EXHAUSTED ceiling tensor
    parallelism removes). One compile per plane shape; each call runs
    the executable and returns a fresh buffer."""
    if sharding is None:
        return lambda: jnp.zeros(shape, dtype)
    import jax

    return jax.jit(
        lambda: jnp.zeros(shape, dtype), out_shardings=sharding
    )


class KVPool:
    """The physical page pool: one (k, v) array pair per layer, each
    ``[num_kv_heads, num_blocks, block_size, head_dim]`` — the exact
    layout ``kernels/pallas/paged_attention`` consumes. Kept as per-layer
    tuples (not stacked) so the engine can donate them through the
    compiled step without reassembly.

    ``quant_dtype="int8"`` switches each layer entry to an int8
    ``(pages, scales)`` pair — ``scales`` float32
    ``[num_kv_heads, num_blocks, block_size]``, one per cached token per
    kv head, written alongside every page write (quantize-on-write) and
    applied in-attention (dequant-in-kernel / in the XLA gather). Per
    token that is ``head_dim`` int8 bytes + 4 scale bytes instead of
    ``head_dim * itemsize`` — a >= 2x cut for fp32 pools (3.8x at
    head_dim 64), ~1.9x for bf16."""

    def __init__(self, num_layers, num_kv_heads, num_blocks, block_size,
                 head_dim, dtype="float32", quant_dtype=None,
                 sharding=None, shard_degree=1):
        if quant_dtype not in (None, "int8"):
            raise ValueError(
                f'KVPool quant_dtype must be None or "int8", got '
                f"{quant_dtype!r}"
            )
        shape = (num_kv_heads, num_blocks, block_size, head_dim)
        self.quant_dtype = quant_dtype
        # tensor-parallel placement (serving.sharding): pages allocate
        # DIRECTLY under the sharding — never whole on one chip first
        if quant_dtype == "int8":
            sshape = (num_kv_heads, num_blocks, block_size)
            pages_z = _zeros_factory(shape, jnp.int8, sharding)
            # zero scales: unwritten slots dequantize to exact 0,
            # matching the float pool's zero init
            scales_z = _zeros_factory(sshape, jnp.float32, sharding)

            def mk():
                return (pages_z(), scales_z())

            self._shapes = (shape, sshape)
            self._dtypes = (jnp.dtype(jnp.int8), jnp.dtype(jnp.float32))
        else:
            pages_z = _zeros_factory(shape, dtype, sharding)

            def mk():
                return pages_z()

            self._shapes = (shape,)
            self._dtypes = (jnp.zeros((), dtype).dtype,)
        self.k = tuple(mk() for _ in range(num_layers))
        self.v = tuple(mk() for _ in range(num_layers))
        self.num_layers = num_layers
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self._shape = shape
        self._dtype = self._dtypes[0]
        # logical-bytes / per-chip-bytes ratio when the kv-head dim is
        # actually split (1 = replicated or unsharded)
        self.shard_degree = int(shard_degree)
        # measured eagerly while the fresh arrays are guaranteed live
        # (see per_chip_nbytes: later reads would race TPU donation)
        self._per_chip_nbytes = None
        self.per_chip_nbytes()

    @staticmethod
    def abstract(num_layers, num_kv_heads, num_blocks, block_size,
                 head_dim, dtype="float32", quant_dtype=None,
                 sharding=None):
        """Shape-only twin of a :class:`KVPool`: ``.k``/``.v`` trees of
        ``jax.ShapeDtypeStruct`` with the exact per-layer layout (and,
        when given, the tensor-parallel ``sharding`` attached) that
        ``__init__`` would materialize — but ZERO device allocation.
        The engine traces, lowers, and memory-gates its whole program
        family against this twin BEFORE the real pool exists, so a
        config predicted to exceed ``device_memory_budget`` is refused
        without a single pool buffer ever being allocated."""
        import jax

        if quant_dtype not in (None, "int8"):
            raise ValueError(
                f'KVPool quant_dtype must be None or "int8", got '
                f"{quant_dtype!r}"
            )
        shape = (num_kv_heads, num_blocks, block_size, head_dim)

        def sds(shp, dt):
            if sharding is None:
                return jax.ShapeDtypeStruct(shp, jnp.dtype(dt))
            return jax.ShapeDtypeStruct(
                shp, jnp.dtype(dt), sharding=sharding
            )

        if quant_dtype == "int8":
            sshape = (num_kv_heads, num_blocks, block_size)

            def entry():
                return (sds(shape, jnp.int8), sds(sshape, jnp.float32))
        else:
            def entry():
                return sds(shape, dtype)

        class _Abstract:
            pass

        out = _Abstract()
        out.k = tuple(entry() for _ in range(num_layers))
        out.v = tuple(entry() for _ in range(num_layers))
        out.num_layers = int(num_layers)
        out.num_blocks = int(num_blocks)
        out.block_size = int(block_size)
        return out

    def _layer_leaves(self, entry):
        """The validated leaves of one per-layer entry: (pages,) for a
        float pool, (pages, scales) for a quantized one."""
        if self.quant_dtype is None:
            return (entry,)
        if not isinstance(entry, (tuple, list)) or len(entry) != 2:
            raise ValueError(
                "rebind: quantized pool expects (pages, scales) pairs "
                f"per layer, got {type(entry).__name__}"
            )
        return tuple(entry)

    def rebind(self, k, v):
        """Adopt the updated pool arrays returned by a compiled step.

        Validates that the adopted arrays actually ARE this pool's
        layout — per-layer count, page (and scale-plane) shape, and
        dtype — instead of silently adopting a mismatched tree (which
        would surface much later as garbage attention reads or a shape
        error inside a compiled step)."""
        k, v = tuple(k), tuple(v)
        if len(k) != self.num_layers or len(v) != self.num_layers:
            raise ValueError(
                f"rebind: expected {self.num_layers} k/v layers, got "
                f"{len(k)}/{len(v)}"
            )
        for name, layers in (("k", k), ("v", v)):
            for li, entry in enumerate(layers):
                for a, shape, dtype in zip(
                    self._layer_leaves(entry), self._shapes, self._dtypes
                ):
                    if tuple(a.shape) != shape:
                        raise ValueError(
                            f"rebind: {name}[{li}] shape "
                            f"{tuple(a.shape)} does not match pool "
                            f"shape {shape}"
                        )
                    if a.dtype != dtype:
                        raise ValueError(
                            f"rebind: {name}[{li}] dtype {a.dtype} does "
                            f"not match pool dtype {dtype}"
                        )
        # normalize quantized entries to tuples (jit may hand lists back)
        if self.quant_dtype is not None:
            k = tuple(tuple(e) for e in k)
            v = tuple(tuple(e) for e in v)
        self.k = k
        self.v = v

    # -- host spill primitives (serving/spill.py) ---------------------------
    def block_signature(self):
        """Stable wire form of the PER-BLOCK layout: layer count, block
        size, quantization, and each leaf's block-slice shape/dtype.
        Two pools with equal signatures can exchange spilled blocks
        even when ``num_blocks`` or the shard layout differ (a
        migration survivor's pool may be a different size or width);
        the spill tier refuses a restore across differing signatures —
        a miss, never a corruption."""
        per = [
            [list(s[:1] + s[2:]), str(d)]
            for s, d in zip(self._shapes, self._dtypes)
        ]
        import json

        return json.dumps(
            [self.num_layers, self.block_size,
             self.quant_dtype or "none", per],
            separators=(",", ":"),
        )

    def read_block(self, block_id):
        """Host snapshot of ONE block: ``(k_layers, v_layers)``, each a
        tuple over layers of per-leaf numpy arrays shaped like a block
        slice (``[num_kv_heads, block_size, head_dim]`` pages,
        ``[num_kv_heads, block_size]`` scales). Sharded pools are read
        PER SHARD via ``addressable_shards`` — never gathering a whole
        plane through one device — and reassembled on host. The block
        index is passed as a dynamic-slice operand, so the underlying
        eager gather caches on shape alone (no per-block compile
        churn, no tracked program family touched)."""
        import jax
        import numpy as np

        b = int(block_id)

        def one(x):
            sl = jax.lax.dynamic_slice_in_dim(x, b, 1, axis=1)
            return np.asarray(sl)[:, 0]

        def leaf_block(a):
            shards = getattr(a, "addressable_shards", None)
            if shards and len(shards) > 1:
                pieces = [(s.index, one(s.data)) for s in shards]
                out = np.zeros(
                    tuple(a.shape[:1]) + tuple(a.shape[2:]),
                    pieces[0][1].dtype,
                )
                for idx, piece in pieces:
                    out[(idx[0],) + tuple(idx[2:])] = piece
                return out
            return one(a)

        def entry_block(entry):
            return tuple(
                leaf_block(leaf) for leaf in self._layer_leaves(entry)
            )

        return (
            tuple(entry_block(e) for e in self.k),
            tuple(entry_block(e) for e in self.v),
        )

    def write_block(self, block_id, snapshot):
        """Write one host snapshot (from :meth:`read_block`, possibly
        of a DIFFERENT pool with the same :meth:`block_signature`) into
        block ``block_id`` — the spill tier's restore primitive.
        Host-side eager data movement only: no tracked program family
        is touched (the zero-new-compiled-programs contract), and each
        leaf keeps its committed sharding (``device_put`` back onto the
        original sharding — a resharded leaf would retrace the serving
        programs). The updated arrays are adopted via :meth:`rebind`,
        re-validating the whole layout on every restore. The copy is
        bytewise: no arithmetic touches the payload, so a restored
        block is byte-identical to the block that was spilled."""
        import jax
        import numpy as np

        b = int(block_id)
        if not 0 <= b < self.num_blocks:
            raise ValueError(
                f"write_block: block {b} outside pool of "
                f"{self.num_blocks}"
            )
        k_snap, v_snap = snapshot
        if len(k_snap) != self.num_layers or len(v_snap) != self.num_layers:
            raise ValueError(
                f"write_block: snapshot has {len(k_snap)}/{len(v_snap)} "
                f"k/v layers, pool has {self.num_layers}"
            )

        def write_entry(entry, leaves_host):
            leaves = self._layer_leaves(entry)
            if len(leaves_host) != len(leaves):
                raise ValueError(
                    f"write_block: snapshot layer has "
                    f"{len(leaves_host)} leaves, pool expects "
                    f"{len(leaves)}"
                )
            out = []
            for a, host in zip(leaves, leaves_host):
                host = np.asarray(host)
                want = tuple(a.shape[:1]) + tuple(a.shape[2:])
                if tuple(host.shape) != want or host.dtype != a.dtype:
                    raise ValueError(
                        f"write_block: snapshot leaf "
                        f"{tuple(host.shape)}/{host.dtype} does not "
                        f"match pool block layout {want}/{a.dtype}"
                    )
                upd = jax.lax.dynamic_update_slice_in_dim(
                    a, jnp.asarray(host)[:, None], b, axis=1
                )
                sharding = getattr(a, "sharding", None)
                if sharding is not None:
                    upd = jax.device_put(upd, sharding)
                out.append(upd)
            return out[0] if self.quant_dtype is None else tuple(out)

        new_k = tuple(
            write_entry(e, s) for e, s in zip(self.k, k_snap)
        )
        new_v = tuple(
            write_entry(e, s) for e, s in zip(self.v, v_snap)
        )
        self.rebind(new_k, new_v)

    def nbytes(self):
        import jax

        return sum(
            a.size * a.dtype.itemsize
            for a in jax.tree_util.tree_leaves((self.k, self.v))
        )

    def bytes_per_token(self):
        """Cache bytes per token slot across all layers and kv heads —
        the byte-budget figure the int8 mode halves. LOGICAL total:
        what the whole pool costs across every chip it spans."""
        return self.nbytes() / (self.num_blocks * self.block_size)

    def per_chip_nbytes(self):
        """Bytes the most-loaded single device actually holds, measured
        ONCE from the real shards (``addressable_shards``) while the
        freshly-allocated arrays are guaranteed live, then cached —
        placement is static after build (the compiled steps pin their
        out shardings), and reading shard buffers later would race the
        donated pool on TPU: between a launch consuming the donated
        arrays and ``rebind()``, ``self.k`` references deleted arrays,
        and a concurrent ``health()`` probe touching their shards would
        raise — flapping a perfectly healthy replica. An unsharded pool
        reports its full size, a tp-sharded one ~1/tp of it."""
        if self._per_chip_nbytes is None:
            import jax

            per: dict = {}
            for a in jax.tree_util.tree_leaves((self.k, self.v)):
                shards = getattr(a, "addressable_shards", None)
                if shards:
                    for s in shards:
                        per[s.device.id] = (
                            per.get(s.device.id, 0)
                            + s.data.size * a.dtype.itemsize
                        )
                else:  # abstract value: fall back to the whole array
                    per[None] = (
                        per.get(None, 0) + a.size * a.dtype.itemsize
                    )
            self._per_chip_nbytes = max(per.values()) if per else 0
        return self._per_chip_nbytes

    def bytes_per_token_per_chip(self):
        """Per-chip counterpart of :meth:`bytes_per_token` — the figure
        tensor-parallel sharding cuts ~tp-fold (``Engine.health()``
        exports both)."""
        return self.per_chip_nbytes() / (
            self.num_blocks * self.block_size
        )

    def block_bytes_per_chip(self):
        """Per-chip bytes one KV block occupies on the most-loaded
        device — the unit the headroom snapshot scales free blocks by,
        so a tp=4 replica's N free blocks read as ~half the per-chip
        bytes a tp=2 replica's N blocks do (``Engine.health()``'s
        ``kv_headroom_bytes_per_chip`` and the fleet router's
        headroom weighting)."""
        return self.per_chip_nbytes() / self.num_blocks
