"""Paged KV-cache: ref-counted block manager over a preallocated pool.

The vLLM/PagedAttention (SOSP '23) memory design on the TPU-native page
layout already used by ``kernels/pallas/paged_attention``: the physical
cache is ONE preallocated array per layer,
``[num_kv_heads, num_blocks, block_size, head_dim]``, and every request
owns an ordered list of block ids (its block table). Because blocks are
ref-counted, a future prefix-sharing pass only needs ``fork()`` — two
requests mapping the same prompt blocks — with copy-on-write left to the
caller; the free-list is LIFO so hot blocks are reused while still in
cache.

Allocation policy lives in the ENGINE (admission control, preemption);
this module only enforces the invariants: a block is reusable exactly when
its refcount returns to zero, and the pool's high-water mark is tracked so
tests can assert blocks actually return to the free-list.
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["BlockManager", "KVPool"]


class BlockManager:
    """Ref-counted free-list over ``num_blocks`` logical blocks of
    ``block_size`` tokens each."""

    def __init__(self, num_blocks, block_size):
        if num_blocks < 1 or block_size < 1:
            raise ValueError(
                f"need num_blocks >= 1 and block_size >= 1, got "
                f"{num_blocks}/{block_size}"
            )
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # LIFO: most-recently-freed block is re-allocated first
        self._free = list(range(self.num_blocks - 1, -1, -1))
        self._ref = [0] * self.num_blocks
        self.high_water = 0   # max blocks ever simultaneously in use

    # -- accounting ---------------------------------------------------------
    @property
    def num_free(self):
        return len(self._free)

    @property
    def num_used(self):
        return self.num_blocks - len(self._free)

    def utilization(self):
        return self.num_used / self.num_blocks

    def blocks_needed(self, num_tokens):
        """Blocks required to hold ``num_tokens`` cache slots."""
        return -(-int(num_tokens) // self.block_size)

    def ref_count(self, block_id):
        """Current reference count of one block (0 == free). The prefix
        cache uses this to tell reclaimable cached blocks (cache is the
        only owner) from blocks live requests still read."""
        return self._ref[block_id]

    def can_allocate(self, n):
        return len(self._free) >= n

    # -- lifecycle ----------------------------------------------------------
    def allocate(self, n):
        """Take ``n`` blocks off the free-list (refcount 1 each)."""
        if n > len(self._free):
            raise RuntimeError(
                f"KV pool exhausted: need {n} blocks, {len(self._free)} "
                f"free of {self.num_blocks}"
            )
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._ref[b] = 1
        self.high_water = max(self.high_water, self.num_used)
        return out

    def fork(self, block_ids):
        """Share existing blocks with a second owner (prefix sharing):
        refcount++ per block, no data movement."""
        for b in block_ids:
            if self._ref[b] < 1:
                raise RuntimeError(f"fork of free block {b}")
            self._ref[b] += 1

    def free(self, block_ids):
        """Drop one reference per block; blocks return to the free-list
        when the last owner releases them."""
        for b in block_ids:
            if self._ref[b] < 1:
                raise RuntimeError(f"double free of block {b}")
            self._ref[b] -= 1
            if self._ref[b] == 0:
                self._free.append(b)


class KVPool:
    """The physical page pool: one (k, v) array pair per layer, each
    ``[num_kv_heads, num_blocks, block_size, head_dim]`` — the exact
    layout ``kernels/pallas/paged_attention`` consumes. Kept as per-layer
    tuples (not stacked) so the engine can donate them through the
    compiled step without reassembly."""

    def __init__(self, num_layers, num_kv_heads, num_blocks, block_size,
                 head_dim, dtype="float32"):
        shape = (num_kv_heads, num_blocks, block_size, head_dim)
        self.k = tuple(jnp.zeros(shape, dtype) for _ in range(num_layers))
        self.v = tuple(jnp.zeros(shape, dtype) for _ in range(num_layers))
        self.num_layers = num_layers
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self._shape = shape
        self._dtype = self.k[0].dtype

    def rebind(self, k, v):
        """Adopt the updated pool arrays returned by a compiled step.

        Validates that the adopted arrays actually ARE this pool's
        layout — per-layer count, page shape, and dtype — instead of
        silently adopting a mismatched tree (which would surface much
        later as garbage attention reads or a shape error inside a
        compiled step)."""
        k, v = tuple(k), tuple(v)
        if len(k) != self.num_layers or len(v) != self.num_layers:
            raise ValueError(
                f"rebind: expected {self.num_layers} k/v layers, got "
                f"{len(k)}/{len(v)}"
            )
        for name, layers in (("k", k), ("v", v)):
            for li, a in enumerate(layers):
                if tuple(a.shape) != self._shape:
                    raise ValueError(
                        f"rebind: {name}[{li}] shape {tuple(a.shape)} "
                        f"does not match pool page shape {self._shape}"
                    )
                if a.dtype != self._dtype:
                    raise ValueError(
                        f"rebind: {name}[{li}] dtype {a.dtype} does not "
                        f"match pool dtype {self._dtype}"
                    )
        self.k = k
        self.v = v

    def nbytes(self):
        return sum(a.size * a.dtype.itemsize for a in self.k + self.v)
