"""Automatic prefix caching over the ref-counted paged KV pool.

The vLLM/PagedAttention (SOSP '23) automatic-prefix-cache design on the
``kv_cache.BlockManager`` primitives shipped for it: prompt tokens are
hashed at BLOCK granularity into a *chain* key (the digest of a block's
tokens folded over its parent's digest, so a block is only ever matched
in the exact prefix context it was computed in), and every full prompt
block a request finishes prefilling is published under its chain key
with one cache-owned reference (``BlockManager.fork``). A later request
whose prompt starts with the same token chain forks the shared blocks —
no data movement, no recompute — and prefills only the uncovered
suffix.

Sharing rules that keep greedy outputs bit-identical (docs/serving.md):

  * only FULL blocks are published and matched — a partially-filled
    block would be written by its owner's next decode step;
  * a match never covers the whole token sequence: at least one token
    is always left to prefill, because the prefill of the final token
    produces the logits the next sample needs. When that cap cuts into
    the last matched block, the engine COPIES it (copy-on-write) so the
    re-written slot never touches the shared original;
  * cached blocks are retained after their last request releases them
    ("zero-waiting-ref" blocks) under an LRU entry budget; blocks whose
    ONLY reference is the cache's are *reclaimable* — the engine frees
    them on demand before shedding or preempting, so a warm cache never
    reads as pool pressure.

Eviction is leaf-first along the chains (evicting a middle block would
orphan its descendants' keys while they still hold references), oldest
LRU entry first. All bookkeeping is host-side and deterministic — no
wall-clock, no randomness — so cache behavior is replayable in tests.
"""
from __future__ import annotations

import hashlib
from collections import OrderedDict

__all__ = ["PrefixCache", "PrefixMatch", "prompt_chain_digests"]

# wire form of a chain key: enough hex to make collisions negligible
# at fleet scale while keeping health() snapshots compact
_DIGEST_HEX = 16


def _iter_chain(tokens, block_size):
    """Yield ``(digest, block_index)`` for each FULL block of
    ``tokens``: the digest folds the parent digest in, so equal blocks
    in different prefix contexts never collide. The single hashing
    implementation behind both the cache's keys and the router-facing
    wire digests — they MUST agree byte-for-byte."""
    h = b""
    for i in range(len(tokens) // block_size):
        payload = " ".join(
            str(int(t)) for t in tokens[i * block_size:(i + 1) * block_size]
        )
        h = hashlib.sha256(h + payload.encode()).digest()
        yield h, i


def prompt_chain_digests(tokens, block_size):
    """Chain digests (hex wire form) of every full block of ``tokens``
    — what a router hashes a request's prompt into to match it against
    the ``prefix_cache_digests`` a replica exports via
    ``Engine.health()``."""
    return [
        h.hex()[:_DIGEST_HEX] for h, _i in _iter_chain(tokens, block_size)
    ]


class PrefixMatch:
    """One admission-time cache match: ``cache_len`` prompt tokens are
    covered, ``shared_blocks`` are the full blocks to ``fork()``, and
    ``cow_src`` (when the one-token-to-prefill cap cut into the last
    matched block) is the shared block the engine must copy-on-write
    instead of forking."""

    __slots__ = ("cache_len", "shared_blocks", "cow_src", "_digests")

    def __init__(self, cache_len, shared_blocks, cow_src=None,
                 digests=()):
        self.cache_len = int(cache_len)
        self.shared_blocks = list(shared_blocks)
        self.cow_src = cow_src
        self._digests = tuple(digests)  # matched chain, for commit()

    @property
    def num_shared(self):
        return len(self.shared_blocks)

    def __repr__(self):
        return (
            f"PrefixMatch(cache_len={self.cache_len}, "
            f"shared={self.shared_blocks}, cow_src={self.cow_src})"
        )


class _Entry:
    __slots__ = ("digest", "block", "parent", "children")

    def __init__(self, digest, block, parent):
        self.digest = digest
        self.block = block
        self.parent = parent    # _Entry or None (chain root)
        self.children = 0       # cached entries extending this chain


class PrefixCache:
    """Chain-keyed LRU cache of read-only prompt blocks.

    Holds ONE BlockManager reference per cached block, taken at
    :meth:`register` and released at eviction — so a cached block can
    outlive every request that used it, and ``fork()`` at match time is
    always of a live block. ``capacity_blocks`` bounds the number of
    cached entries (each entry pins one block); exceeding it evicts
    leaf entries oldest-first.
    """

    def __init__(self, block_manager, capacity_blocks, metrics=None,
                 spill=None, pool=None):
        if capacity_blocks < 1:
            raise ValueError(
                f"capacity_blocks must be >= 1, got {capacity_blocks}"
            )
        self._bm = block_manager
        self._bs = block_manager.block_size
        self.capacity_blocks = int(capacity_blocks)
        # digest -> _Entry; OrderedDict order IS the LRU order (oldest
        # first; lookup/register touches move entries to the end)
        self._entries: OrderedDict = OrderedDict()
        self._metrics = metrics
        # host spill tier (serving/spill.py): eviction DEMOTES full
        # chain blocks into it instead of destroying their bytes, and
        # lookup() restores a spilled chain continuation into fresh
        # pool blocks. Needs the physical pool for block reads/writes;
        # without both, eviction behaves exactly as before.
        self._spill = spill if pool is not None else None
        self._pool = pool
        self._sig = pool.block_signature() if (
            spill is not None and pool is not None
        ) else None
        self._digest_cache = ()   # rebuilt lazily after insert/evict

    def __len__(self):
        return len(self._entries)

    # -- chain keys ----------------------------------------------------------
    def _chain(self, tokens):
        """``_iter_chain`` over this cache's block size."""
        return _iter_chain(tokens, self._bs)

    def chain_digests(self):
        """Hex chain keys of every cached entry, insertion (= chain)
        order — the ``Engine.health()`` export a hit-aware router
        matches ``prompt_chain_digests`` results against. Cached
        between membership changes: ``health()`` sits on the fleet's
        per-step routability path, so this must not walk the cache on
        every call."""
        if self._digest_cache is None:
            self._digest_cache = tuple(
                e.digest.hex()[:_DIGEST_HEX]
                for e in self._entries.values()
            )
        return self._digest_cache

    # -- match ---------------------------------------------------------------
    def lookup(self, tokens, limit):
        """Longest cached prefix of ``tokens``, capped at ``limit``
        tokens (the engine passes ``len(tokens) - 1`` so at least one
        token is always left to prefill). Returns a :class:`PrefixMatch`
        or ``None``.

        Pure read against the DEVICE entries: no counters move and no
        LRU position changes — an admission that stays blocked retries
        the lookup every step, and only the attempt that actually
        forks the blocks may count as a hit (:meth:`commit`) or
        deserve an LRU touch. The one side effect is the spill tier:
        a chain walk that runs off the cached entries into a SPILLED
        continuation restores it into fresh pool blocks right here
        (idempotent — the restored entry is a plain cached entry, so
        a blocked retry hits it in ``_entries`` next time). A restore
        may transiently push the entry count past ``capacity_blocks``;
        the next :meth:`register`/:meth:`reclaim` settles it (evicting
        mid-walk would free blocks this very match is about to fork)."""
        matched = []
        parent = None
        for digest, i in self._chain(tokens):
            e = self._entries.get(digest)
            if (e is None and self._spill is not None
                    and i * self._bs < limit):
                e = self._restore(digest, parent)
            if e is None:
                break
            matched.append(e)
            parent = e
        cache_len = min(len(matched) * self._bs, int(limit))
        if cache_len <= 0:
            return None
        n_fork = cache_len // self._bs
        cow_src = (
            matched[n_fork].block if cache_len % self._bs else None
        )
        return PrefixMatch(
            cache_len, [e.block for e in matched[:n_fork]], cow_src,
            digests=[e.digest for e in matched],
        )

    def commit(self, match):
        """Book a match the engine actually used (blocks forked /
        copied): counts the hit and touches the matched chain's LRU
        position."""
        for digest in match._digests:
            if digest in self._entries:
                self._entries.move_to_end(digest)
        if self._metrics is not None:
            self._metrics.prefix_hits += 1
            self._metrics.prefix_hit_tokens += match.cache_len

    # -- publish -------------------------------------------------------------
    def register(self, prompt_tokens, block_ids, max_tokens):
        """Publish the full PROMPT blocks of a request whose prefill
        just completed (``max_tokens`` tokens are in the pool). Each
        newly-published block gains one cache-owned reference; blocks
        whose chain key is already cached are only LRU-touched — the
        first publisher wins, identical later prompts share ITS
        blocks."""
        limit = min(len(prompt_tokens), int(max_tokens))
        parent = None
        for digest, i in self._chain(prompt_tokens):
            if (i + 1) * self._bs > limit or i >= len(block_ids):
                break
            e = self._entries.get(digest)
            if e is not None:
                self._entries.move_to_end(digest)
                parent = e
                continue
            block = block_ids[i]
            self._bm.fork([block])  # the cache's own reference
            e = _Entry(digest, block, parent)
            self._entries[digest] = e
            self._digest_cache = None
            if parent is not None:
                parent.children += 1
            parent = e
        self._enforce_budget()

    # -- spill tier ----------------------------------------------------------
    def _restore(self, digest, parent):
        """Re-materialize a spilled chain block into a fresh pool
        block: one host->device write, byte-identical to the block
        that was evicted. Returns the new (cache-owned) entry, or
        ``None`` on any miss — tier miss, no free pool block, an
        injected ``kv.restore`` fault, a RESOURCE_EXHAUSTED device
        write — in which case the chain walk stops and admission takes
        the old recompute path unchanged."""
        key = f"prefix:{digest.hex()}"
        if not self._spill.has(key, self._sig):
            return None
        if not self._bm.can_allocate(1):
            # allocation pressure: a restore must never deepen it
            return None
        import time

        t0 = time.perf_counter()
        payload = self._spill.get(key, self._sig, pop=True)
        if payload is None:
            return None
        [block] = self._bm.allocate(1)   # the cache's own reference
        try:
            self._pool.write_block(block, payload[0])
        except Exception:
            # analysis: allow(broad-except) the degradation contract:
            # a failed device write (incl. RESOURCE_EXHAUSTED) frees
            # the block and falls back to recompute — never fatal
            self._bm.free([block])
            self._spill.note_restore_failure("prefix")
            return None
        e = _Entry(digest, block, parent)
        self._entries[digest] = e
        self._digest_cache = None
        if parent is not None:
            parent.children += 1
        self._spill.note_restored(
            "prefix", payload, time.perf_counter() - t0
        )
        if self._metrics is not None:
            self._metrics.prefix_restores += 1
        return e

    def _demote(self, e):
        """Best-effort block demotion at eviction: snapshot the block
        into the host tier under its chain key. Any failure (injected
        ``kv.spill`` fault, budget, unreadable device block) means the
        block simply dies the way it did before the tier existed."""
        try:
            snap = self._pool.read_block(e.block)
        except Exception:
            # analysis: allow(broad-except) demotion is an
            # optimization: a failed device read degrades to the old
            # free-and-recompute eviction, counted on the tier
            self._spill.note_spill_failure("prefix")
            return
        self._spill.put(
            f"prefix:{e.digest.hex()}", [snap], self._sig,
            num_tokens=self._bs, cls="prefix",
        )

    # -- eviction / reclaim --------------------------------------------------
    def _evict(self, digest):
        e = self._entries.pop(digest)
        self._digest_cache = None
        if e.parent is not None:
            e.parent.children -= 1
        if self._spill is not None:
            # demote instead of destroy: the bytes move to the host
            # tier (keyed by chain digest) BEFORE the device block is
            # freed; a later chain match restores them
            self._demote(e)
        self._bm.free([e.block])
        if self._metrics is not None:
            self._metrics.prefix_evictions += 1

    def _enforce_budget(self):
        while len(self._entries) > self.capacity_blocks:
            victim = None
            for digest, e in self._entries.items():  # oldest first
                if e.children == 0:
                    victim = digest
                    break
            if victim is None:  # unreachable: chains always have leaves
                break
            self._evict(victim)

    def reclaim(self, n, protect=()):
        """Free up to ``n`` blocks back to the pool by evicting LRU
        leaf entries whose block has no owner besides the cache.
        ``protect``: block ids that must survive (an in-progress match
        about to be forked/copied). Returns the number freed."""
        n = max(int(n), 0)
        protect = set(protect)
        freed = 0
        progress = True
        # one forward pass evicts every eligible leaf in LRU order;
        # repeat only when an eviction turned a parent into a new leaf
        # (parents sit EARLIER in insertion order, behind the cursor)
        while freed < n and progress:
            progress = False
            for digest, e in list(self._entries.items()):
                if freed >= n:
                    break
                if (e.children or e.block in protect
                        or self._bm.ref_count(e.block) != 1):
                    continue
                self._evict(digest)
                freed += 1
                progress = True
        return freed

    def reclaimable_blocks(self):
        """Cached blocks whose only reference is the cache's — pool
        slots an allocation-pressure path can take back at any time."""
        return sum(
            1 for e in self._entries.values()
            if self._bm.ref_count(e.block) == 1
        )

    def clear(self):
        """Drop every entry (releasing the cache's references)."""
        for digest in list(self._entries):
            self._evict(digest)
