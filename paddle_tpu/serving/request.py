"""Request lifecycle for the serving engine.

Capability target: the request/sequence abstractions of continuous-batching
servers (Orca OSDI'22 iteration-level scheduling; vLLM SequenceGroup), cut
down to what a single-replica TPU engine needs: per-request sampling
params, token accounting, and stop conditions. Stop semantics mirror
``generation.GenerationMixin.generate`` — the stop token itself is kept in
the output (generate emits EOS then pads), so a request served through the
engine and one served through ``generate`` produce the same token stream.
"""
from __future__ import annotations

import enum
import itertools
import time

from ..observability.spans import current_trace_id

__all__ = ["RequestState", "SamplingParams", "Request", "RequestOutput",
           "RequestTimeline", "normalize_sampling_params"]


def normalize_sampling_params(prompts, sampling_params):
    """One params-per-prompt list from either a single SamplingParams
    (broadcast) or a per-prompt list — the shared ``generate(prompts,
    sampling_params)`` contract of ``Engine`` and ``Fleet``."""
    if isinstance(sampling_params, (list, tuple)):
        if len(sampling_params) != len(prompts):
            raise ValueError("one SamplingParams per prompt required")
        return list(sampling_params)
    return [sampling_params] * len(prompts)


class RequestState(enum.Enum):
    WAITING = 0     # queued (never scheduled, or preempted back to queue)
    RUNNING = 1     # owns a batch slot + KV blocks, decoding
    FINISHED = 2
    # owns a slot + blocks but its prompt is still being prefilled
    # (chunked prefill spreads the prompt over several steps); excluded
    # from the decode batch until the final chunk samples its token
    PREFILLING = 3


def _check_int(field, value, allow_none=False):
    """Coerce a user-supplied field to int, or raise a ValueError that
    names the field (a bad wire payload must surface as a structured
    4xx, not a deep TypeError from a comparison)."""
    if value is None and allow_none:
        return None
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        raise ValueError(
            f"{field} must be an integer, got "
            f"{type(value).__name__}: {value!r}"
        )
    return int(value)


def _check_float(field, value, allow_none=False):
    if value is None and allow_none:
        return None
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        raise ValueError(
            f"{field} must be a number, got "
            f"{type(value).__name__}: {value!r}"
        )
    return float(value)


class SamplingParams:
    """Per-request sampling knobs, the serving-side analogue of
    ``generation.GenerationConfig`` (same field semantics — greedy unless
    ``do_sample``; warps are temperature -> top-k -> top-p)."""

    def __init__(self, max_new_tokens=16, do_sample=False, temperature=1.0,
                 top_k=0, top_p=1.0, eos_token_id=None, stop_token_ids=(),
                 ttl_s=None, seed=None):
        max_new_tokens = _check_int("max_new_tokens", max_new_tokens)
        temperature = _check_float("temperature", temperature)
        top_k = _check_int("top_k", top_k)
        top_p = _check_float("top_p", top_p)
        eos_token_id = _check_int("eos_token_id", eos_token_id,
                                  allow_none=True)
        ttl_s = _check_float("ttl_s", ttl_s, allow_none=True)
        seed = _check_int("seed", seed, allow_none=True)
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        if temperature <= 0.0:
            raise ValueError(
                f"temperature must be > 0 (got {temperature}); use "
                "do_sample=False for greedy decoding"
            )
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if top_k < 0:
            raise ValueError(f"top_k must be >= 0 (0 disables), got {top_k}")
        self.max_new_tokens = int(max_new_tokens)
        self.do_sample = bool(do_sample)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.eos_token_id = eos_token_id
        if isinstance(stop_token_ids, (str, bytes)) or not hasattr(
                stop_token_ids, "__iter__"):
            raise ValueError(
                "stop_token_ids must be a sequence of integers, got "
                f"{type(stop_token_ids).__name__}: {stop_token_ids!r}"
            )
        self.stop_token_ids = tuple(
            _check_int("stop_token_ids", t) for t in stop_token_ids
        )
        if ttl_s is not None and ttl_s < 0:
            raise ValueError(f"ttl_s must be >= 0 or None, got {ttl_s}")
        # wall-clock budget from arrival; the engine finishes the request
        # with finish_reason="timeout" once it expires (queued or running)
        self.ttl_s = None if ttl_s is None else float(ttl_s)
        # per-request sampling seed: when set on a do_sample request,
        # the request's per-request launches (prefill / final chunk)
        # draw from fold_in(PRNGKey(seed), n_generated) instead of the
        # engine's shared key stream — so the first sampled token is
        # reproducible across restarts, replays, and failovers.
        # Batched decode continuations keep the engine's per-step key
        # stream (the documented sampled-replay caveat; greedy requests
        # ignore this entirely). Journaled in the ADMIT record.
        self.seed = None if seed is None else int(seed)

    @property
    def stop_ids(self):
        """The full stop set: explicit stop tokens plus EOS."""
        ids = set(self.stop_token_ids)
        if self.eos_token_id is not None:
            ids.add(int(self.eos_token_id))
        return ids

    def to_dict(self):
        """JSON-able form (the request journal's ADMIT payload)."""
        return {
            "max_new_tokens": self.max_new_tokens,
            "do_sample": self.do_sample,
            "temperature": self.temperature,
            "top_k": self.top_k,
            "top_p": self.top_p,
            "eos_token_id": self.eos_token_id,
            "stop_token_ids": list(self.stop_token_ids),
            "ttl_s": self.ttl_s,
            "seed": self.seed,
        }

    @classmethod
    def from_dict(cls, d):
        """Inverse of :meth:`to_dict`. Unknown keys are ignored so a
        journal written by a newer build still replays."""
        known = (
            "max_new_tokens", "do_sample", "temperature", "top_k",
            "top_p", "eos_token_id", "stop_token_ids", "ttl_s", "seed",
        )
        return cls(**{k: d[k] for k in known if k in d})


class RequestTimeline:
    """Per-request lifecycle record: monotonic phase stamps plus the
    event counters that explain a tail sample (how many chunks, how
    many prefix-cache tokens, how many preemptions/hops). Every field
    is a plain attribute bumped host-side by the engine — no registry,
    no allocation beyond the hop list — so the timeline rides every
    request at effectively zero per-step cost. Surfaced on
    ``RequestOutput.metrics`` and fed into the engine's latency
    digests at finish (docs/observability.md "Latency & SLO").

    Phase definitions (all from ``arrival``, ``time.perf_counter``):

      queue_wait  arrival -> first slot assignment (``admitted``)
      ttft        arrival -> first generated token
      decode      first token -> finish
      e2e         arrival -> finish
      tpot        decode / (output_tokens - 1), the steady-state
                  inter-token latency (None for single-token outputs)
    """

    __slots__ = (
        "arrival", "admitted", "first_token", "finish", "finish_reason",
        "prefill_chunks", "prefill_tokens", "prefix_hit_tokens",
        "decode_tokens", "verify_steps", "spec_accepted", "preemptions",
        "resumes", "hops", "recovered",
    )

    def __init__(self, arrival):
        self.arrival = arrival      # perf_counter at Request creation
        self.admitted = None        # first slot assignment
        self.first_token = None
        self.finish = None
        self.finish_reason = None
        self.prefill_chunks = 0     # prefill launches (1 = one-shot)
        self.prefill_tokens = 0     # tokens actually computed
        self.prefix_hit_tokens = 0  # prompt tokens served from cache
        self.decode_tokens = 0      # tokens emitted by decode/verify
        self.verify_steps = 0       # speculative verify launches
        self.spec_accepted = 0      # draft tokens accepted
        self.preemptions = 0        # KV-pressure recompute preemptions
        self.resumes = 0            # external resume() calls (failover)
        self.hops = []              # engine ids that admitted it
        self.recovered = False      # re-admitted from the journal

    def mark_admitted(self, engine_id, now=None):
        if now is None:
            now = time.perf_counter()
        if self.admitted is None:
            self.admitted = now
        if not self.hops or self.hops[-1] != engine_id:
            self.hops.append(engine_id)
        return now

    def mark_finish(self, reason, now=None):
        self.finish = now if now is not None else time.perf_counter()
        self.finish_reason = reason
        return self.finish

    # -- derived phases (None until the transition happened) ---------------
    @property
    def queue_wait_s(self):
        return (
            self.admitted - self.arrival
            if self.admitted is not None else None
        )

    @property
    def ttft_s(self):
        return (
            self.first_token - self.arrival
            if self.first_token is not None else None
        )

    @property
    def e2e_s(self):
        return (
            self.finish - self.arrival
            if self.finish is not None else None
        )

    def tpot_s(self, n_output_tokens):
        if (self.finish is None or self.first_token is None
                or n_output_tokens < 2):
            return None
        return (self.finish - self.first_token) / (n_output_tokens - 1)

    def snapshot(self, n_output_tokens=0):
        """JSON-friendly phase breakdown — the access-log line body,
        the flight-recorder timeline entry, and
        ``RequestOutput.metrics``."""
        return {
            "queue_wait_s": self.queue_wait_s,
            "ttft_s": self.ttft_s,
            "tpot_s": self.tpot_s(n_output_tokens),
            "e2e_s": self.e2e_s,
            "finish_reason": self.finish_reason,
            "prefill_chunks": self.prefill_chunks,
            "prefill_tokens": self.prefill_tokens,
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "decode_tokens": self.decode_tokens,
            "verify_steps": self.verify_steps,
            "spec_accepted": self.spec_accepted,
            "preemptions": self.preemptions,
            "resumes": self.resumes,
            "hops": list(self.hops),
            "recovered": self.recovered,
        }


_request_counter = itertools.count()


class Request:
    """One in-flight generation. The engine owns the mutable scheduling
    fields; ``output_token_ids`` accumulates generated tokens (including a
    terminal stop token, matching ``generate``'s EOS handling).

    KV invariant while RUNNING: the cache holds ``num_cached`` tokens =
    prompt + all generated tokens EXCEPT ``last_token`` (the newest token
    is written by the decode step that consumes it). Preemption frees the
    blocks but keeps the token state, so a re-prefill over
    ``prompt + output[:-1]`` restores the cache exactly.
    """

    def __init__(self, prompt_token_ids, sampling_params=None,
                 request_id=None):
        prompt_token_ids = [int(t) for t in prompt_token_ids]
        if not prompt_token_ids:
            raise ValueError("prompt_token_ids must be non-empty")
        self.request_id = (
            request_id if request_id is not None
            else next(_request_counter)
        )
        self.prompt_token_ids = prompt_token_ids
        self.sampling_params = sampling_params or SamplingParams()
        self.state = RequestState.WAITING
        self.output_token_ids: list = []
        self.finish_reason = None
        self.error = None         # "ExcType: msg" when finish_reason="error"
        # scheduling fields (engine-owned while RUNNING)
        self.block_ids: list = []
        self.num_cached = 0       # tokens whose KV is in the pool
        self.last_token = None    # newest token, not yet in the cache
        self.slot = None
        self.admit_seq = -1       # admission order, for preemption policy
        # durability: output tokens already written to the request
        # journal (the emit cursor; journal.admit/emit own it)
        self.journal_cursor = 0
        # goodput attribution for a forced re-prefill: "preempt"
        # (in-engine recompute preemption), "migration" (fleet
        # failover/scale-down resume), or "restored" (KV rebuilt from
        # the host spill tier — counted useful, not wasted) — the step
        # observatory's ledger classifies the recomputed tokens by this
        self.resume_cause = None
        # host spill tier handle (serving/spill.py): set when this
        # request's KV blocks were swapped to host RAM at preemption/
        # release; re-admission restores them instead of re-prefilling.
        # Journaled in ADMIT ("kv") so a crash re-anchors the handle.
        self.spill_key = None
        self.spill_tokens = 0
        # multi-tenant QoS attribution (serving/qos.py); None for
        # in-process callers. Journaled in ADMIT ("tn") so replay
        # restores per-tenant accounting.
        self.tenant = None
        # metrics
        self.arrival_time = time.perf_counter()
        self.first_token_time = None
        self.finish_time = None
        # per-request lifecycle timeline (phase stamps + counters);
        # journal replay re-anchors .arrival at the journaled
        # wall-clock arrival so recovered requests' TTFT/e2e include
        # the downtime instead of reading impossibly fast
        self.timeline = RequestTimeline(self.arrival_time)
        # trace attribution captured at CREATION, on the submitting
        # thread: at finish time the stepping thread's ambient span
        # belongs to whatever batch happened to be running, not to
        # this request's client
        self.trace_id = current_trace_id()
        self.deadline = (
            self.arrival_time + self.sampling_params.ttl_s
            if self.sampling_params.ttl_s is not None else None
        )

    def expired(self, now=None):
        return self.deadline is not None and (
            now if now is not None else time.perf_counter()
        ) >= self.deadline

    @property
    def num_tokens(self):
        return len(self.prompt_token_ids) + len(self.output_token_ids)

    def tokens_to_prefill(self):
        """Tokens whose KV must be (re)built by a prefill: the prompt plus
        every generated token except the newest (see class invariant)."""
        return self.prompt_token_ids + self.output_token_ids[:-1]

    def check_stop(self, max_model_len):
        """Return a finish reason for the current state, or None. Called
        after each appended token, mirroring generate's loop order (stop
        token beats length when both trigger on the same token)."""
        p = self.sampling_params
        if self.output_token_ids and (
            self.output_token_ids[-1] in p.stop_ids
        ):
            return "stop"
        if len(self.output_token_ids) >= p.max_new_tokens:
            return "length"
        if self.num_tokens >= max_model_len:
            return "length"
        return None


class RequestOutput:
    """Immutable result handed back by the engine."""

    def __init__(self, request):
        self.request_id = request.request_id
        self.prompt_token_ids = list(request.prompt_token_ids)
        self.token_ids = list(request.output_token_ids)
        self.finish_reason = request.finish_reason
        self.error = request.error
        self.time_to_first_token = (
            request.first_token_time - request.arrival_time
            if request.first_token_time is not None else None
        )
        self.latency = (
            request.finish_time - request.arrival_time
            if request.finish_time is not None else None
        )
        # phase breakdown + lifecycle counters (queue wait, TTFT,
        # TPOT, e2e, chunks, cache hits, speculation, preemptions,
        # failover hops) — the per-request view the latency digests
        # aggregate
        self.metrics = request.timeline.snapshot(
            len(request.output_token_ids)
        )

    def __repr__(self):
        return (
            f"RequestOutput(id={self.request_id}, "
            f"n_out={len(self.token_ids)}, reason={self.finish_reason!r})"
        )
