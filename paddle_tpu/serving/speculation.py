"""Model-free speculative drafting for the serving engine.

Prompt-lookup (n-gram) decoding: LLM outputs constantly re-emit spans
of their own context — retrieved quotes, code identifiers, repeated
boilerplate, greedy cycles — so a draft for the next K tokens can be
read straight out of the request's prompt+output history instead of a
separate draft model. The drafter finds earlier occurrences of the
trailing n-gram and proposes the tokens that followed; the engine then
scores all K+1 positions in ONE batched ``verify`` launch (adapter
entry point) and accepts the longest draft prefix that matches the
target model's own greedy argmax. Accepted tokens are exactly what
step-by-step decode would have produced, so greedy outputs stay
byte-identical — speculation only changes how many launches it takes
to produce them.

Everything here is host-side pure Python over token lists: no arrays,
no tracing, no randomness. The drafter's output is padded to a fixed
width by the engine so the compiled ``verify`` program never sees a
data-dependent shape (the one-trace-per-signature invariant).
"""
from __future__ import annotations

__all__ = ["propose", "accept_length", "DEFAULT_LOOKBACK"]

# how far back the drafter searches for the trailing n-gram: recent
# history carries the repetition worth exploiting (the current
# quote/cycle/boilerplate span), and an unbounded scan would make the
# host-side cost per step grow linearly with context length — paid on
# the latency-critical path, and highest exactly when nothing matches
DEFAULT_LOOKBACK = 512


def propose(history, k, max_ngram=3, min_ngram=1,
            lookback=DEFAULT_LOOKBACK):
    """Draft up to ``k`` continuation tokens for ``history`` (prompt +
    generated tokens so far) by prompt lookup.

    Tries the trailing ``n``-gram for ``n`` from ``max_ngram`` down to
    ``min_ngram``; the FIRST n with an earlier occurrence wins (longer
    context disambiguates better). Among occurrences, recency tracks
    the current generation phase, but two refinements buy precision —
    a rejected draft costs nothing extra in launch time (the verify
    window has a fixed shape), yet every accepted token is a decode
    launch saved, so the drafter optimizes accept RATE:

      * a match flush against the tail would truncate the draft (a
        period-p cycle matched at distance p proposes only p tokens),
        so the most recent occurrence with a FULL ``k``-token
        continuation is preferred, nearer-but-shorter ones kept only
        as a fallback;
      * quasi-periodic histories carry several variants of the same
        n-gram context; where the two most recent full continuations
        DISAGREE the evidence is ambiguous, so the draft is truncated
        at their longest common prefix (falling back to one token of
        the most recent when they disagree immediately).

    Only the last ``lookback`` tokens are searched (bounded host cost
    per step regardless of context length). Returns at most ``k``
    tokens — possibly fewer or empty (no repetition to exploit, or
    ``k <= 0``). Deterministic, read-only.
    """
    if k <= 0 or lookback <= 0:
        return []
    k = int(k)
    hist = [int(t) for t in history[-int(lookback):]]
    n_hi = min(int(max_ngram), len(hist) - 1)
    for n in range(n_hi, max(int(min_ngram), 1) - 1, -1):
        tail = hist[-n:]
        full = []      # most-recent-first continuations of k tokens
        short = None   # nearest shorter continuation (fallback)
        for start in range(len(hist) - n - 1, -1, -1):
            if hist[start:start + n] == tail:
                cont = hist[start + n:start + n + k]
                if len(cont) == k:
                    full.append(cont)
                    if len(full) == 2:
                        break
                elif short is None:
                    short = cont
        if len(full) == 2:
            a, b = full
            m = 0
            while m < k and a[m] == b[m]:
                m += 1
            return a[:m] if m else a[:1]
        if full:
            return full[0]
        if short is not None:
            return short
    return []


def accept_length(draft, targets):
    """Longest accepted draft prefix: ``draft[j]`` is accepted when it
    equals ``targets[j]`` — the target model's greedy argmax at the
    position draft[j] would occupy (``verify``'s logits row j scores
    the token FOLLOWING position j). Rejection is sticky: the first
    mismatch invalidates everything after it, because later drafts
    were scored in a context containing the rejected token."""
    a = 0
    for d, t in zip(draft, targets):
        if int(d) != int(t):
            break
        a += 1
    return a
