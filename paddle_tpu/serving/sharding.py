"""Tensor-parallel sharded serving: one engine, ``tp_degree`` chips.

Serving has been single-chip end to end — one chip bounds model size,
KV budget, and batch (the bench hits RESOURCE_EXHAUSTED at 747M
params). This module lifts the ceiling the same way the training side
does (``distributed.parallelize``): a 1 x tp device ``Mesh``, the
col/row-wise Megatron plan applied to the adapter's weight pytree via
``jax.sharding.NamedSharding``, and the paged KV pool sharded on its
kv-head dimension over the same mesh. Every serving program
(prefill / prefill_ext / decode / verify / cow) stays ONE single-launch
SPMD program: sharding is expressed through the shardings of the traced
bodies' inputs and outputs — GSPMD places the collectives — never
through per-device python loops, so the engine's scheduler, compile
probes, warmup manifest, and journal are untouched by the chip count.

Partition plan (``SERVING_TP_PLAN`` — the serving-side instantiation of
``distributed.parallelize``'s ColWiseParallel/RowWiseParallel markers
over the adapter's raw weight dict):

  * ``wq/wk/wv`` and ``wg/wu`` col-parallel: output (head / FFN) dim
    sharded, so attention runs ``num_heads / tp`` heads per chip and
    the SwiGLU intermediate lives sharded.
  * ``wo/wd`` row-parallel: contraction dim sharded (the Megatron
    pairing that keeps activation layout consistent).
  * ``embed``/``norm``/``ln*``/``head`` replicated. A vocab-sharded LM
    head would push the sampling warp (top-k/top-p over the full
    vocab) cross-chip; logits stay replicated so sampling and the
    argmax-based verify contract are untouched.
  * KV pages ``[num_kv_heads, blocks, bs, d]`` sharded on dim 0 —
    per-chip KV bytes drop ~tp-fold. GQA-aware: kv heads shard only
    when ``tp`` divides ``num_kv_heads``; with ``num_kv_heads < tp``
    the pool (and wk/wv) replicate instead — still correct, no KV
    saving (documented in docs/serving.md).

Determinism (``EngineConfig(tp_numerics=)``): a sharded CONTRACTION
(the row-parallel matmuls) is computed as per-chip partial sums plus an
all-reduce, whose cross-chip reduction order differs from the
single-chip matmul by ~1 ulp — enough to flip a greedy argmax. The
default ``"exact"`` mode therefore constrains both operands of the two
row-parallel matmuls to replicated (an all-gather of the sharded
weight) so every reduction runs whole on every chip: greedy AND
sampled outputs are byte-identical to the unsharded engine, at the
cost of weight-gather bandwidth per step. ``"fast"`` leaves GSPMD to
the Megatron partial-sum + all-reduce — the production mode for real
ICI, within ~1 ulp of the reference (docs/serving.md has the full
caveat table). Everything else in the plan is reduction-free on the
sharded axis (col-parallel matmuls contract over replicated dims,
attention reduces within a head, page writes/gathers move bytes), so
it is bit-exact in both modes.
"""
from __future__ import annotations

import numpy as np

from ..distributed.parallelize import ColWiseParallel, RowWiseParallel

__all__ = [
    "TPSpec", "SERVING_TP_PLAN", "build_tp_mesh", "build_tp_spec",
    "resolve_devices", "visible_device_ids",
]

# per-weight-key plan over the adapter's raw weight dict (keys are the
# LlamaServingAdapter layer-dict keys, not module paths). Keys absent
# here (ln1/ln2/embed/norm/head) replicate.
SERVING_TP_PLAN = {
    "wq": ColWiseParallel(),
    "wk": ColWiseParallel(),
    "wv": ColWiseParallel(),
    "wg": ColWiseParallel(),
    "wu": ColWiseParallel(),
    "wo": RowWiseParallel(),
    "wd": RowWiseParallel(),
}

# the adapter weight-dict layer keys the plan is defined over — used to
# recognize a shardable weight tree (anything else needs its own plan)
_LAYER_KEYS = (
    "ln1", "wq", "wk", "wv", "wo", "ln2", "wg", "wu", "wd",
)


def visible_device_ids():
    """Ids of every device this process can place on — the universe a
    ``serving.placement.PlacementPlan`` carves into per-replica
    slices."""
    import jax

    return [d.id for d in jax.devices()]


def resolve_devices(devices, tp_degree):
    """The explicit device list behind the mesh: ``devices`` may be
    jax ``Device`` objects or integer device ids (picklable configs);
    ``None`` takes the first ``tp_degree`` of ``jax.devices()``. Raises
    ONE ValueError naming ``tp_degree``/``devices`` when the list
    cannot cover the degree."""
    import jax

    avail = jax.devices()
    if devices is None:
        if len(avail) < tp_degree:
            raise ValueError(
                f"EngineConfig(tp_degree={tp_degree}) needs "
                f"{tp_degree} devices but only {len(avail)} "
                f"{avail[0].platform} device(s) are visible; pass "
                f"devices= or lower tp_degree (CPU tests force more "
                f"via --xla_force_host_platform_device_count)"
            )
        return list(avail[:tp_degree])
    devices = list(devices)
    if len(devices) != tp_degree:
        # exact, not >=: silently truncating an over-long list would
        # run the mesh on fewer chips than the operator pinned — the
        # same silent-misplacement class the tp_degree=1 refusal guards
        raise ValueError(
            f"EngineConfig(devices=) has {len(devices)} entries but "
            f"tp_degree={tp_degree} needs exactly {tp_degree} (the "
            f"mesh's device list, nothing more)"
        )
    by_id = {d.id: d for d in avail}
    out = []
    for d in devices:
        if isinstance(d, int):
            if d not in by_id:
                raise ValueError(
                    f"EngineConfig(devices=) names device id {d} but "
                    f"visible ids are {sorted(by_id)}"
                )
            out.append(by_id[d])
        else:
            if by_id.get(getattr(d, "id", None)) != d:
                # e.g. a Device from another backend/process: placing
                # on it dies as a bare AssertionError inside device_put
                raise ValueError(
                    f"EngineConfig(devices=) names device {d!r} which "
                    f"is not among this process's visible devices "
                    f"(ids {sorted(by_id)})"
                )
            out.append(d)
    if len({d.id for d in out}) != len(out):
        raise ValueError(
            f"EngineConfig(devices=) repeats a device (ids "
            f"{[d.id for d in out]}); a 1 x {tp_degree} mesh needs "
            f"{tp_degree} DISTINCT devices"
        )
    return out


def build_tp_mesh(devices):
    """The 1 x tp serving mesh over an explicit device list: ``dp`` is
    the (degenerate) replica axis — a Fleet scales replicas, the mesh
    scales ONE replica — and ``tp`` is the axis every partition spec
    references."""
    from jax.sharding import Mesh

    return Mesh(
        np.asarray(devices, dtype=object).reshape(1, len(devices)),
        ("dp", "tp"),
    )


def _plan_spec(plan, kv_sharded, key):
    """PartitionSpec for one weight-dict key under the col/row plan."""
    from jax.sharding import PartitionSpec as P

    mark = plan.get(key)
    if mark is None:
        return P()
    if key in ("wk", "wv") and not kv_sharded:
        return P()  # GQA: fewer kv heads than chips -> replicate
    if isinstance(mark, ColWiseParallel):
        return P(None, "tp")
    if isinstance(mark, RowWiseParallel):
        return P("tp", None)
    raise TypeError(
        f"unknown TP plan marker {type(mark).__name__} for {key!r}"
    )


class TPSpec:
    """Everything the engine and adapter need to run one replica as a
    single SPMD program over ``tp_degree`` chips: the mesh, the
    NamedSharding trees for the weight pytree and the KV pool, and the
    numerics mode the adapter's row-parallel matmuls consult at trace
    time (``serving.adapter._row_matmul``)."""

    def __init__(self, mesh, tp_degree, numerics, kv_sharded, plan=None):
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.mesh = mesh
        self.tp_degree = int(tp_degree)
        self.numerics = numerics
        self.exact = numerics == "exact"
        self.kv_sharded = bool(kv_sharded)
        self.plan = dict(SERVING_TP_PLAN if plan is None else plan)
        self.replicated = NamedSharding(mesh, P())
        # KV pages [kv_heads, blocks, bs, d] / int8 scale planes
        # [kv_heads, blocks, bs]: head dim sharded when GQA allows
        pool_spec = P("tp") if self.kv_sharded else P()
        self.pool_sharding = NamedSharding(mesh, pool_spec)

    @property
    def device_ids(self):
        return [d.id for d in self.mesh.devices.flat]

    def weight_shardings(self, weights):
        """NamedSharding tree matching the adapter weight pytree."""
        from jax.sharding import NamedSharding

        named = lambda key: NamedSharding(
            self.mesh, _plan_spec(self.plan, self.kv_sharded, key)
        )
        return {
            "embed": self.replicated,
            "norm": self.replicated,
            "head": (
                self.replicated if weights.get("head") is not None
                else None
            ),
            "layers": [
                {k: named(k) for k in layer}
                for layer in weights["layers"]
            ],
        }

    def shard_weights(self, weights):
        """Place the weight pytree on the mesh per the plan (persistent
        per-chip weight bytes drop for every sharded matrix)."""
        import jax

        return jax.tree_util.tree_map(
            jax.device_put, weights, self.weight_shardings(weights),
        )

    def pool_out_shardings(self, pool):
        """out_shardings tree pinning the traced bodies' returned pool
        to the pool's placement — output sharding must round-trip
        exactly or the next launch would miss the compiled program's
        input layout and retrace."""
        import jax

        tree = jax.tree_util.tree_map(lambda a: a.sharding, pool.k)
        return tree, jax.tree_util.tree_map(
            lambda a: a.sharding, pool.v
        )

    def abstract(self, tree):
        """``compilecache.abstractify`` with shardings attached: the
        AOT path lowers from ShapeDtypeStructs, which carry no
        placement unless told — these mirror the launch-site arrays
        exactly, so the cached executable IS the lazy-path program."""
        import jax

        return jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(
                a.shape, a.dtype, sharding=a.sharding
            ),
            tree,
        )


def build_tp_spec(adapter, config):
    """Validate ``EngineConfig(tp_degree=, devices=, tp_numerics=)``
    against the adapter and return the :class:`TPSpec` — or raise ONE
    clear error naming the flag and the offending dimension (today a
    bad degree surfaces as a deep XLA mesh error at first launch).
    """
    tp = int(config.tp_degree)
    weights = getattr(adapter, "weights", None)
    layers = weights.get("layers") if isinstance(weights, dict) else None
    if (not layers or not isinstance(layers[0], dict)
            or not all(k in layers[0] for k in _LAYER_KEYS)):
        raise TypeError(
            f"{type(adapter).__name__} does not expose the layered "
            f"weight dict the serving TP plan shards "
            f"({'/'.join(_LAYER_KEYS)} per layer), but EngineConfig("
            f"tp_degree={tp}) needs an adapter it can partition"
        )
    head_dim = adapter.head_dim
    num_heads = getattr(adapter, "num_heads", None)
    if num_heads is None:
        num_heads = layers[0]["wq"].shape[1] // head_dim
    num_kv_heads = adapter.num_kv_heads
    ffn = layers[0]["wg"].shape[1]
    if num_heads % tp:
        raise ValueError(
            f"EngineConfig(tp_degree={tp}) does not divide the "
            f"model's num_attention_heads={num_heads}: attention "
            f"heads shard over the tp axis, so tp_degree must divide "
            f"them"
        )
    if ffn % tp:
        raise ValueError(
            f"EngineConfig(tp_degree={tp}) does not divide the "
            f"model's FFN intermediate_size={ffn}: gate/up/down "
            f"shard over the tp axis, so tp_degree must divide it"
        )
    kv_sharded = num_kv_heads >= tp
    if kv_sharded and num_kv_heads % tp:
        raise ValueError(
            f"EngineConfig(tp_degree={tp}) does not divide the "
            f"model's num_key_value_heads={num_kv_heads}: KV heads "
            f"shard over the tp axis when num_kv_heads >= tp_degree "
            f"(use a degree that divides them, or one larger than "
            f"num_kv_heads to replicate the KV pool)"
        )
    devices = resolve_devices(config.devices, tp)
    mesh = build_tp_mesh(devices)
    return TPSpec(mesh, tp, config.tp_numerics, kv_sharded)
