"""Structured JSONL access log: one line per finished request.

Metrics aggregate; the access log keeps the *individuals* — the only
artifact that lets an operator answer "which requests were slow, and
what were they doing?" after the fact. Each line is one JSON object
(rid, trace id, replica/engine, prompt/output lengths, finish reason,
and the full :class:`~.request.RequestTimeline` phase breakdown),
written at request-finish time:

  * **Write discipline** (the journal's, scaled to observability):
    one unbuffered ``write()`` per line — SIGKILL leaves at most one
    torn final line, which the reader skips (torn-tail tolerance) —
    rotation into ``access-<n>.jsonl`` segments at ``rotate_bytes``
    with the oldest segments deleted beyond ``keep_files``. No fsync
    on the line path: this is telemetry, not durability (the journal
    owns delivery).
  * **Degradation contract**: every write/rotate failure — including
    the injected ``obs.accesslog`` fault — degrades to a warn-once
    plus ``paddle_tpu_serving_accesslog_*`` counters (pull-time
    weakref collector view, zero hot-path registry cost). An access
    log must never take down the serving it describes.
  * **Offline reader**: :func:`iter_records` /
    :func:`load_directory` power the
    ``python -m paddle_tpu.observability slo --access-log DIR``
    offline summarizer.

``resolve_access_log`` caches instances per directory, so a fleet's
replicas (same process, shared ``EngineConfig``) append to ONE log
with a ``replica`` field instead of racing rotations.
"""
from __future__ import annotations

import itertools
import json
import os
import re
import threading
import time
import warnings
import weakref

from ..resilience import faults

__all__ = ["AccessLog", "iter_records", "load_directory",
           "record_finish", "resolve_access_log"]

_FILE_RE = re.compile(r"^access-(\d{8})\.jsonl$")

# monotonic ids for the collector-view label (labels must never alias
# across log lifetimes — the engine/journal counter rationale)
_log_counter = itertools.count(1)

_COUNTERS = {
    "records_written": "paddle_tpu_serving_accesslog_records_total",
    "bytes_written": "paddle_tpu_serving_accesslog_bytes_total",
    "write_errors": "paddle_tpu_serving_accesslog_errors_total",
    "rotations": "paddle_tpu_serving_accesslog_rotations_total",
}


def _register_view(log, log_id):
    """Pull-time counter view (weakref: a collected log's view
    unregisters itself). Best-effort — telemetry about telemetry must
    never fail the caller."""
    try:
        from ..observability import MetricFamily, get_registry
    except Exception:
        # analysis: allow(broad-except) observability is optional here
        return
    ref = weakref.ref(log)
    label = {"log": log_id}

    def collect():
        al = ref()
        if al is None:
            return None
        return [
            MetricFamily(series, "counter").add(getattr(al, attr), label)
            for attr, series in _COUNTERS.items()
        ]

    try:
        get_registry().register_collector(
            f"serving.accesslog.{log_id}", collect
        )
    except Exception:
        # analysis: allow(broad-except) telemetry is best-effort
        pass


class AccessLog:
    """Rotating JSONL writer (one line per finished request)."""

    def __init__(self, path, rotate_bytes=1 << 20, keep_files=8):
        if rotate_bytes < 1:
            raise ValueError(
                f"rotate_bytes must be >= 1, got {rotate_bytes}"
            )
        if keep_files < 1:
            raise ValueError(f"keep_files must be >= 1, got {keep_files}")
        self.path = str(path)
        os.makedirs(self.path, exist_ok=True)
        self.rotate_bytes = int(rotate_bytes)
        self.keep_files = int(keep_files)
        self._file = None
        self._name = None
        self._size = 0
        self._warned = False
        # resolve_access_log aliases every same-directory engine in
        # the process to ONE instance, and engines may step on
        # different user threads — serialize the write/rotate path
        # (one uncontended acquire per finished request, not per token)
        self._lock = threading.Lock()
        # counters (plain attributes; exported by the collector view)
        self.records_written = 0
        self.bytes_written = 0
        self.write_errors = 0
        self.rotations = 0
        _register_view(self, f"{next(_log_counter)}")

    def files(self):
        """Log file names on disk, oldest first."""
        try:
            names = os.listdir(self.path)
        except OSError:
            return []
        return sorted(n for n in names if _FILE_RE.match(n))

    def log(self, record):
        """Append one JSON line. NEVER raises: failures (including the
        injected ``obs.accesslog`` fault) degrade to a warn-once plus
        the error counter — the record is dropped, serving goes on."""
        with self._lock:
            try:
                faults.fire(
                    "obs.accesslog", path=self.path,
                    rid=record.get("rid"),
                )
                line = (
                    json.dumps(record, separators=(",", ":")) + "\n"
                ).encode()
                if self._file is None:
                    self._open_file()
                if (self._size
                        and self._size + len(line) > self.rotate_bytes):
                    self._rotate()
                self._file.write(line)  # unbuffered: one syscall/line
                self._size += len(line)
                self.records_written += 1
                self.bytes_written += len(line)
            except Exception as e:
                # analysis: allow(broad-except) the degradation
                # contract: serving never goes fatal because its
                # access log did
                self.write_errors += 1
                if not self._warned:
                    self._warned = True
                    warnings.warn(
                        f"[accesslog] write to {self.path} failed "
                        f"({type(e).__name__}: {e}); record dropped — "
                        "serving continues with a lossy access log "
                        "(further failures are counted, not warned)",
                        stacklevel=2,
                    )

    def close(self):
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None

    # -- segments ----------------------------------------------------------
    def _open_file(self):
        names = self.files()
        nxt = 1 + (
            int(_FILE_RE.match(names[-1]).group(1)) if names else 0
        )
        name = f"access-{nxt:08d}.jsonl"
        self._file = open(
            os.path.join(self.path, name), "ab", buffering=0
        )
        self._name = name
        self._size = os.fstat(self._file.fileno()).st_size

    def _rotate(self):
        self._file.close()
        # cleared BEFORE the reopen: if _open_file raises (transient
        # ENOSPC/EACCES), log()'s reopen guard must retry next call
        # instead of writing to the closed handle forever
        self._file = None
        self._open_file()
        self.rotations += 1
        names = self.files()
        for name in names[: max(0, len(names) - self.keep_files)]:
            try:
                os.remove(os.path.join(self.path, name))
            except OSError:
                pass  # unremovable files retry at the next rotation


def record_finish(req, latency=None, slo=None, access_log=None,
                  **scope):
    """THE finish-time accounting for one completed request — shared
    by ``Engine._finish`` and ``Fleet._finish_local`` so the access-log
    schema and the digest/SLO feeding can never fork between engine-
    finished and fleet-finished requests:

      * ``latency`` (phase-digest dict) gets the e2e/tpot samples and
        ``slo`` the window sample — SKIPPED for client aborts: a
        cancelled request (hedge loser, client hang-up) is not a
        latency sample, and counting it would double-book every
        hedge-resolved request in the merged percentiles;
      * the structured entry (rid, trace, ``scope`` labels such as
        ``engine=``/``fleet=``, lengths, error, full timeline
        snapshot) ALWAYS lands in the flight timeline ring and, when
        ``access_log`` is set, as one JSONL line — aborts included,
        because postmortems and operators need to see them.

    Host-side, once per request; every failure degrades downstream
    (AccessLog.log never raises, flight is best-effort)."""
    import time as _time

    tl = req.timeline
    n_out = len(req.output_token_ids)
    tpot = tl.tpot_s(n_out)
    if req.finish_reason != "aborted":
        if latency is not None:
            latency["e2e"].record(tl.e2e_s)
            if tpot is not None:
                latency["tpot"].record(tpot)
        if slo is not None:
            slo.record(ttft_s=tl.ttft_s, tpot_s=tpot)
    entry = {
        "ts": _time.time(),
        "rid": req.request_id,
        "trace": req.trace_id,
        **scope,
        "prompt_tokens": len(req.prompt_token_ids),
        "output_tokens": n_out,
        "error": req.error,
    }
    # tenant attribution rides the Request itself (set by the QoS
    # front door, restored by journal replay) so engine-finished and
    # fleet-finished lines carry it without forking the callers
    tenant = getattr(req, "tenant", None)
    if tenant is not None and "tenant" not in entry:
        entry["tenant"] = tenant
    entry.update(tl.snapshot(n_out))
    try:
        from ..observability import flight

        flight.record_timeline(entry)
    except Exception:
        # analysis: allow(broad-except) flight telemetry is best-effort
        pass
    if access_log is not None:
        access_log.log(entry)
    return entry


def iter_records(path):
    """Yield the JSON records of every ``access-*.jsonl`` under
    ``path``, oldest first. Torn tails (a crash's partial final line)
    and damaged lines are skipped, not fatal — the reader must work on
    the directory a SIGKILL left behind."""
    try:
        names = sorted(
            n for n in os.listdir(path) if _FILE_RE.match(n)
        )
    except OSError:
        return
    for name in names:
        try:
            with open(os.path.join(path, name), "rb") as f:
                data = f.read()
        except OSError:
            continue
        for line in data.split(b"\n"):
            if not line:
                continue
            try:
                yield json.loads(line)
            except ValueError:
                continue  # torn/damaged line: skip


def load_directory(path):
    """All records under ``path`` as a list (the offline CLI's
    input)."""
    return list(iter_records(path))


# one AccessLog per directory per process: a fleet's replicas share
# the engine config, and two writers rotating the same directory
# would race each other's segment numbering (the lock closes the
# check-then-act window when two threads resolve the same dir at once)
_instances: dict = {}
_instances_lock = threading.Lock()


def resolve_access_log(log):
    """``EngineConfig(access_log=)`` accepts a directory path or a
    pre-built :class:`AccessLog`; same-path resolutions share one
    instance."""
    if isinstance(log, AccessLog):
        return log
    key = os.path.abspath(str(log))
    with _instances_lock:
        ref = _instances.get(key)
        cur = ref() if ref is not None else None
        if cur is None:
            cur = AccessLog(key)
            _instances[key] = weakref.ref(cur)
    return cur
