"""Engine observability: counters + profiler integration.

Counters cover the serving-quality quartet — queue depth, time-to-first-
token, throughput, cache pressure — plus the two TPU-specific health
signals: compile counts (a recompile after warmup means a shape leaked
into the hot path) and preemptions (KV pool pressure). ``RecordEvent``
spans from ``paddle_tpu.profiler`` wrap the prefill/decode steps, so a
profiler session over a serving loop shows them in the UserDefined
summary table and the trace viewer like any other annotated range.

Registry view: every EngineMetrics publishes itself into the
process-wide ``observability`` metrics registry as a pull-time
collector (``paddle_tpu_serving_*`` series labeled by engine id).
Nothing changes on the hot path — the counters stay plain python
attributes (the traced-body compile probes depend on that), the
registry PULLS ``snapshot()`` at scrape time, and a garbage-collected
engine's view unregisters itself through the weakref.
"""
from __future__ import annotations

import time
import weakref

__all__ = ["EngineMetrics"]

# snapshot key -> (exposition kind, suffix); monotonics get the
# prometheus _total suffix, instantaneous values export as gauges
_EXPORT_KINDS = {
    "requests_received": ("counter", "_total"),
    "requests_finished": ("counter", "_total"),
    "preemptions": ("counter", "_total"),
    "requests_errored": ("counter", "_total"),
    "requests_timeout": ("counter", "_total"),
    "requests_shed": ("counter", "_total"),
    "prefill_tokens": ("counter", "_total"),
    "decode_tokens": ("counter", "_total"),
    "prefill_steps": ("counter", "_total"),
    "prefill_chunks": ("counter", "_total"),
    "decode_steps": ("counter", "_total"),
    "prefill_compiles": ("counter", "_total"),
    "prefill_ext_compiles": ("counter", "_total"),
    "decode_compiles": ("counter", "_total"),
    "cow_compiles": ("counter", "_total"),
    "verify_compiles": ("counter", "_total"),
    "verify_steps": ("counter", "_total"),
    "spec_proposed": ("counter", "_total"),
    "spec_accepted": ("counter", "_total"),
    "spec_accept_rate": ("gauge", ""),
    "prefix_lookups": ("counter", "_total"),
    "prefix_hits": ("counter", "_total"),
    "prefix_hit_tokens": ("counter", "_total"),
    "prefix_evictions": ("counter", "_total"),
    "prefix_restores": ("counter", "_total"),
    "cow_copies": ("counter", "_total"),
    "queue_depth": ("gauge", ""),
    "num_running": ("gauge", ""),
    "tp_degree": ("gauge", ""),
    "cache_utilization": ("gauge", ""),
    "kv_active_utilization": ("gauge", ""),
    "kv_reclaimable_blocks": ("gauge", ""),
    "kv_headroom_blocks": ("gauge", ""),
    "prefix_cache_blocks": ("gauge", ""),
    "pool_high_water": ("gauge", ""),
    "mean_ttft_s": ("gauge", ""),
    "tokens_per_s": ("gauge", ""),
}


def _register_view(metrics, engine_id):
    """Collector view over one EngineMetrics: called only at scrape
    time, holds the metrics object by weakref (a dead engine's view
    returns None and the registry drops it)."""
    from ..observability import MetricFamily, get_registry
    from ..observability.metrics import register_latency_view

    ref = weakref.ref(metrics)
    label = {"engine": engine_id}

    def latency_view():
        m = ref()
        return None if m is None else m.latency

    # digest collector-view kind: renders the per-phase quantile
    # summary (paddle_tpu_serving_latency_seconds{phase,quantile})
    # plus the native cumulative histogram, all at pull time
    register_latency_view(
        f"serving.latency.{engine_id}", latency_view,
        "paddle_tpu_serving_latency", labels=label,
    )

    def collect():
        m = ref()
        if m is None:
            return None
        fams = []
        for key, value in m.snapshot().items():
            kind_suffix = _EXPORT_KINDS.get(key)
            if kind_suffix is None or value is None:
                continue  # non-numeric (last_error) / unset latencies
            kind, suffix = kind_suffix
            fams.append(MetricFamily(
                f"paddle_tpu_serving_{key}{suffix}", kind,
            ).add(value, label))
        if m.program_bytes:
            # predicted per-chip peak per compiled serving program
            # (the L3 memory-budget gate's source of truth), one
            # sample per program label
            fam = MetricFamily(
                "paddle_tpu_serving_program_bytes", "gauge",
            )
            for prog, nbytes in sorted(m.program_bytes.items()):
                fam.add(nbytes, {**label, "program": prog})
            fams.append(fam)
        hist = m.spec_accept_hist()
        if hist:
            # per-step accepted-draft-length histogram (Prometheus
            # cumulative-bucket semantics; the observed lengths 0..K
            # ARE the bucket bounds, so every sample lands exactly)
            fam = MetricFamily(
                "paddle_tpu_serving_spec_accept_length", "histogram",
            )
            acc, total = 0, 0.0
            for le in sorted(hist):
                acc += hist[le]
                total += le * hist[le]
                fam.add(acc, {**label, "le": str(le)}, "_bucket")
            fam.add(acc, {**label, "le": "+Inf"}, "_bucket")
            fam.add(total, label, "_sum")
            fam.add(acc, label, "_count")
            fams.append(fam)
        tracker = m.slo
        if tracker is not None:
            # SLO error-budget burn (windowed): burn 1.0 = spending
            # the budget exactly as allotted; the burning gauge is the
            # boolean that also flips health()["flags"]
            fam = MetricFamily(
                "paddle_tpu_serving_slo_burn_rate", "gauge",
            )
            for sig, v in sorted(tracker.burn_rates().items()):
                if v is not None:
                    fam.add(v, {**label, "signal": sig})
            if fam.samples:
                fams.append(fam)
            fams.append(MetricFamily(
                "paddle_tpu_serving_slo_burning", "gauge",
            ).add(1.0 if tracker.burning() else 0.0, label))
        return fams

    get_registry().register_collector(f"serving.engine.{engine_id}",
                                      collect)


class EngineMetrics:
    def __init__(self, engine_id=None):
        self.start_time = time.perf_counter()
        # request flow
        self.requests_received = 0
        self.requests_finished = 0
        self.preemptions = 0
        # degradation accounting (resilience: poison isolation, TTL
        # expiry, KV-pressure load shedding)
        self.requests_errored = 0
        self.requests_timeout = 0
        self.requests_shed = 0
        self.last_error = None
        # token flow: prefill_tokens counts tokens actually COMPUTED by
        # a prefill launch — prefix-cache hits subtract from it, which
        # is exactly the saving the hit-tokens counter measures
        self.prefill_tokens = 0
        self.decode_tokens = 0
        # prefix cache (serving/prefix_cache.py): lookups/hits at
        # admission, hit_tokens = prompt tokens served from shared
        # blocks instead of recomputed, cow_copies = partial-block
        # copy-on-write divergences, evictions = cached blocks released
        self.prefix_lookups = 0
        self.prefix_hits = 0
        self.prefix_hit_tokens = 0
        self.prefix_evictions = 0
        # chain blocks re-materialized from the host spill tier
        # (serving/spill.py) on a lookup that ran into a demoted chain
        self.prefix_restores = 0
        self.cow_copies = 0
        # step/compile accounting (compile counters are bumped from INSIDE
        # the traced step body, so they move only when XLA retraces)
        self.prefill_steps = 0
        self.prefill_chunks = 0   # chunk launches via prefill_ext
        self.decode_steps = 0
        self.prefill_compiles = 0
        self.prefill_ext_compiles = 0
        self.decode_compiles = 0
        self.cow_compiles = 0
        self.verify_compiles = 0
        # speculative decoding: verify launches, draft tokens proposed
        # by the prompt-lookup drafter, and drafts the target argmax
        # accepted (the spec_accept_rate numerator); the per-step
        # accepted-length distribution feeds the histogram view
        self.verify_steps = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self._spec_accept_counts: dict = {}
        # L3 compiled analysis: predicted per-chip peak bytes per
        # serving program ({"decode": ..., "prefill[16]": ...}),
        # populated as programs are summarized (compile-cache sidecar
        # or AOT lowering) — exported per-program as the
        # paddle_tpu_serving_program_bytes{program=} gauge
        self.program_bytes: dict = {}
        # gauges (updated by the engine each step)
        self.queue_depth = 0
        self.num_running = 0
        # tensor-parallel degree of the engine this view belongs to
        # (1 = single-chip; set at engine build, never changes) — lets
        # dashboards tell a 4-chip replica's series from a 1-chip one's
        self.tp_degree = 1
        self.cache_utilization = 0.0
        # KV pressure split: active excludes reclaimable-cached blocks
        # (retained prefix blocks nobody is running against) — shedding
        # and routing must see THIS, not raw utilization
        self.kv_active_utilization = 0.0
        self.kv_reclaimable_blocks = 0
        # free + reclaimable blocks: the capacity this replica could
        # still absorb (set at engine build, refreshed each step) —
        # what headroom-aware fleet routing weighs
        self.kv_headroom_blocks = 0
        self.prefix_cache_blocks = 0
        self.pool_high_water = 0
        # latency digests: one mergeable quantile sketch per phase
        # (observability.latency.LatencyDigest). Recorded once per
        # first-token / finished-request event, read at pull time by
        # the latency collector view; mean_ttft derives from the ttft
        # digest so the back-compat mean_ttft_s gauge and the exported
        # p50 share one source and can never disagree.
        from ..observability.latency import LatencyDigest

        self.latency = {
            phase: LatencyDigest()
            for phase in ("queue", "ttft", "tpot", "e2e")
        }
        # SLO burn tracker (observability.latency.SLOTracker) attached
        # by the engine when EngineConfig(slo=) is set; exported as
        # burn-rate gauges by the collector view
        self.slo = None
        # registry view (see module docstring), registered LAST: a
        # scrape racing engine construction must find every attribute
        # snapshot() reads already in place. The engine id labels this
        # engine's series so replicas stay distinguishable.
        if engine_id is not None:
            _register_view(self, engine_id)

    def record_ttft(self, seconds):
        self.latency["ttft"].record(seconds)

    def record_spec_accept(self, n):
        """One verify launch accepted ``n`` draft tokens for one
        slot."""
        n = int(n)
        self._spec_accept_counts[n] = (
            self._spec_accept_counts.get(n, 0) + 1
        )

    def spec_accept_hist(self):
        """{accepted_length: observations} — the histogram view's
        source (copied so scrapes never race the accept loop)."""
        return dict(self._spec_accept_counts)

    @property
    def spec_accept_rate(self):
        return (
            self.spec_accepted / self.spec_proposed
            if self.spec_proposed else None
        )

    @property
    def mean_ttft(self):
        """Derived from the ttft digest (exact sum/count — NOT a
        bucket approximation), keeping the deprecated ``mean_ttft_s``
        gauge consistent-by-construction with the exported
        percentiles. See docs/observability.md for the deprecation."""
        return self.latency["ttft"].mean

    def tokens_per_second(self):
        dt = time.perf_counter() - self.start_time
        return (self.prefill_tokens + self.decode_tokens) / dt if dt else 0.0

    def snapshot(self):
        """One dict, stable keys — what a scrape endpoint would export."""
        return {
            "requests_received": self.requests_received,
            "requests_finished": self.requests_finished,
            "preemptions": self.preemptions,
            "requests_errored": self.requests_errored,
            "requests_timeout": self.requests_timeout,
            "requests_shed": self.requests_shed,
            "last_error": self.last_error,
            "queue_depth": self.queue_depth,
            "num_running": self.num_running,
            "tp_degree": self.tp_degree,
            "prefill_tokens": self.prefill_tokens,
            "decode_tokens": self.decode_tokens,
            "prefix_lookups": self.prefix_lookups,
            "prefix_hits": self.prefix_hits,
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "prefix_evictions": self.prefix_evictions,
            "prefix_restores": self.prefix_restores,
            "cow_copies": self.cow_copies,
            "prefill_steps": self.prefill_steps,
            "prefill_chunks": self.prefill_chunks,
            "decode_steps": self.decode_steps,
            "verify_steps": self.verify_steps,
            "spec_proposed": self.spec_proposed,
            "spec_accepted": self.spec_accepted,
            "spec_accept_rate": self.spec_accept_rate,
            "prefill_compiles": self.prefill_compiles,
            "prefill_ext_compiles": self.prefill_ext_compiles,
            "decode_compiles": self.decode_compiles,
            "cow_compiles": self.cow_compiles,
            "verify_compiles": self.verify_compiles,
            "cache_utilization": self.cache_utilization,
            "kv_active_utilization": self.kv_active_utilization,
            "kv_reclaimable_blocks": self.kv_reclaimable_blocks,
            "kv_headroom_blocks": self.kv_headroom_blocks,
            "prefix_cache_blocks": self.prefix_cache_blocks,
            "pool_high_water": self.pool_high_water,
            "mean_ttft_s": self.mean_ttft,
            "tokens_per_s": self.tokens_per_second(),
        }
