"""Engine observability: counters + profiler integration.

Counters cover the serving-quality quartet — queue depth, time-to-first-
token, throughput, cache pressure — plus the two TPU-specific health
signals: compile counts (a recompile after warmup means a shape leaked
into the hot path) and preemptions (KV pool pressure). ``RecordEvent``
spans from ``paddle_tpu.profiler`` wrap the prefill/decode steps, so a
profiler session over a serving loop shows them in the UserDefined
summary table and the trace viewer like any other annotated range.
"""
from __future__ import annotations

import time

__all__ = ["EngineMetrics"]


class EngineMetrics:
    def __init__(self):
        self.start_time = time.perf_counter()
        # request flow
        self.requests_received = 0
        self.requests_finished = 0
        self.preemptions = 0
        # degradation accounting (resilience: poison isolation, TTL
        # expiry, KV-pressure load shedding)
        self.requests_errored = 0
        self.requests_timeout = 0
        self.requests_shed = 0
        self.last_error = None
        # token flow
        self.prefill_tokens = 0
        self.decode_tokens = 0
        # step/compile accounting (compile counters are bumped from INSIDE
        # the traced step body, so they move only when XLA retraces)
        self.prefill_steps = 0
        self.decode_steps = 0
        self.prefill_compiles = 0
        self.decode_compiles = 0
        # gauges (updated by the engine each step)
        self.queue_depth = 0
        self.num_running = 0
        self.cache_utilization = 0.0
        self.pool_high_water = 0
        # latency
        self._ttft_sum = 0.0
        self._ttft_count = 0

    def record_ttft(self, seconds):
        self._ttft_sum += seconds
        self._ttft_count += 1

    @property
    def mean_ttft(self):
        return (
            self._ttft_sum / self._ttft_count if self._ttft_count else None
        )

    def tokens_per_second(self):
        dt = time.perf_counter() - self.start_time
        return (self.prefill_tokens + self.decode_tokens) / dt if dt else 0.0

    def snapshot(self):
        """One dict, stable keys — what a scrape endpoint would export."""
        return {
            "requests_received": self.requests_received,
            "requests_finished": self.requests_finished,
            "preemptions": self.preemptions,
            "requests_errored": self.requests_errored,
            "requests_timeout": self.requests_timeout,
            "requests_shed": self.requests_shed,
            "last_error": self.last_error,
            "queue_depth": self.queue_depth,
            "num_running": self.num_running,
            "prefill_tokens": self.prefill_tokens,
            "decode_tokens": self.decode_tokens,
            "prefill_steps": self.prefill_steps,
            "decode_steps": self.decode_steps,
            "prefill_compiles": self.prefill_compiles,
            "decode_compiles": self.decode_compiles,
            "cache_utilization": self.cache_utilization,
            "pool_high_water": self.pool_high_water,
            "mean_ttft_s": self.mean_ttft,
            "tokens_per_s": self.tokens_per_second(),
        }
