"""Replicated, self-healing serving fleet.

One ``Engine`` is a single point of failure: a watchdog trip or an
unhandled ``step()`` error kills every in-flight request with no
recovery path, and there is no way to reload weights without dropping
traffic. ``Fleet`` owns N supervised replicas
(``supervisor.ReplicaSupervisor``) behind the same
``add_request``/``step``/``generate`` facade as a single engine and
layers the tail-tolerance playbook of Dean & Barroso's "The Tail at
Scale" over the primitives the previous PRs built:

  * **Health-gated, hit-aware, least-loaded routing** — a new request
    prefers the routable replica whose prefix cache holds the longest
    chain match for its prompt (``Engine.health()`` exports the cached
    chain digests; a warm system prompt keeps landing where its blocks
    already live), falling back to the live replica with the fewest
    queued+running requests; a replica whose health reports any
    ``flags`` entry (degraded / overloaded) or a tripped comm watchdog
    stops receiving new work. Unroutable moments park requests in a
    fleet-level pending queue.
  * **Deterministic crash recovery** — a replica death (unhandled step
    error, watchdog trip, or an injected ``serving.replica`` fault) is
    quarantined; every in-flight request is re-enqueued on a healthy
    replica via ``Engine.resume``, which re-prefills
    ``prompt + output[:-1]`` — the recompute-preemption path — so
    greedy outputs are bit-identical to an uninterrupted run. The dead
    replica restarts in the background under a
    ``resilience.RetryPolicy`` with a restart budget; exceeding it
    marks the replica permanently failed and the fleet shrinks.
  * **Hedged requests** — a request stuck past
    ``FleetConfig(hedge_after_s=...)`` is dispatched a second time on a
    different replica; the first completion wins and the loser is
    aborted (safe because greedy decode is deterministic; sampled
    requests may win with a different-but-valid continuation — see
    docs/serving.md for the determinism caveats).
  * **Rolling drain/restart** — ``drain(replica)`` stops admission and
    steps the fleet until the replica's in-flight work completes;
    ``rolling_restart(min_available=k)`` cycles replicas through
    migrate → rebuild (weight reload) → rejoin without dropping
    requests (in-flight work moves to the other replicas via the
    journal-backed migration below instead of waiting out the drain).
  * **Elastic pod-scale placement** — ``FleetConfig(placement=...)``
    (``serving.placement.PlacementPlan``) carves the visible device
    set into disjoint per-replica TP slices; spawn, crash-restart and
    rolling restart all rebuild a replica onto ITS slice through the
    ``EngineConfig(devices=)`` path. ``FleetConfig(scaling=...)``
    (``ScalingPolicy``) adds the elasticity loop: sustained pooled SLO
    burn (or pending depth) with a free slice grows the fleet through
    the warm compile cache's zero-trace spawn; sustained idle shrinks
    it — both with hysteresis holds, a min/max envelope, and cooldown.
    Shrink (and rolling restart) move in-flight requests off the
    departing replica with ``Engine.release`` → re-ADMIT at the HEAD
    of the pending queue → ``Engine.resume`` re-prefill: greedy
    outputs stay byte-identical, and the journal's replica-epoch
    records make a mid-shrink crash replay exactly-once. Every
    scaling action is counted, flight-recorded, and degradable behind
    the ``fleet.scale`` / ``fleet.place`` fault sites — a failed
    spawn or placement never takes down serving traffic.

Observability is end-to-end: a pull-time collector view exports
``paddle_tpu_fleet_*`` series (failovers, hedges won/lost, restarts,
per-replica status), route/failover/hedge run under spans, and a
replica death records ``fleet``/``failover`` events and dumps a flight
recorder postmortem before the restart begins.
"""
from __future__ import annotations

import collections
import copy
import itertools
import threading
import time
import weakref

from ..observability import MetricFamily, get_registry
from ..observability import flight as _flight
from ..observability import register_health_provider, span
from ..observability.latency import (
    LatencyDigest,
    SLOTracker,
    burn_from_counts,
    sustained_burn,
)
from ..observability.metrics import register_latency_view
from ..resilience import faults
from .access_log import record_finish
from .engine import Engine, EngineConfig, EngineOverloadedError
from .placement import Autoscaler, PlacementError, PlacementPlan, ScalingPolicy
from .prefix_cache import prompt_chain_digests
from .request import (
    Request,
    RequestOutput,
    RequestState,
    normalize_sampling_params,
)
from .supervisor import ReplicaSupervisor

__all__ = ["Fleet", "FleetConfig", "FleetMetrics", "FleetRequest",
           "NoReplicaError"]


class NoReplicaError(RuntimeError):
    """Every replica has permanently failed: the fleet cannot serve."""


# monotonic fleet ids (same rationale as the engine counter: metric
# labels and collector names must never alias across fleet lifetimes)
_fleet_counter = itertools.count(1)


class FleetConfig:
    def __init__(self, num_replicas=2, hedge_after_s=None, max_restarts=2,
                 restart_policy=None, analysis_check="error",
                 max_pending=None, journal_dir=None, placement=None,
                 scaling=None):
        if num_replicas < 1:
            raise ValueError(
                f"num_replicas must be >= 1, got {num_replicas}"
            )
        if max_pending is not None and max_pending < 1:
            raise ValueError(
                f"max_pending must be >= 1 or None (unbounded), got "
                f"{max_pending}"
            )
        if hedge_after_s is not None and hedge_after_s < 0:
            raise ValueError(
                f"hedge_after_s must be >= 0 or None (disabled), got "
                f"{hedge_after_s}"
            )
        if max_restarts < 0:
            raise ValueError(
                f"max_restarts must be >= 0, got {max_restarts}"
            )
        if analysis_check not in (None, "warn", "error"):
            raise ValueError(
                'analysis_check must be None, "warn" or "error", got '
                f"{analysis_check!r}"
            )
        self.num_replicas = int(num_replicas)
        # hedging deadline: None disables; 0.0 hedges any request not
        # finished by the step after its dispatch
        self.hedge_after_s = (
            None if hedge_after_s is None else float(hedge_after_s)
        )
        # crash-restart budget PER REPLICA (rolling restarts are
        # operator-initiated and do not spend it)
        self.max_restarts = int(max_restarts)
        self.restart_policy = restart_policy
        # decode-loop gate each replica runs at spawn/restart
        # (supervisor forwards to Engine.check_decode)
        self.analysis_check = analysis_check
        # fleet admission bound: add_request raises
        # EngineOverloadedError (the engine's shedding semantics) once
        # this many requests are parked unroutable — an unplaceable
        # backlog must push back on clients, not grow without limit.
        # Failover re-enqueues and journal recovery bypass the bound:
        # recovered work is never shed.
        self.max_pending = (
            None if max_pending is None else int(max_pending)
        )
        # durable request journal (serving/journal.py): a directory
        # path or Journal shared by the WHOLE fleet at its front door.
        # A restarting fleet replays it before traffic; see
        # docs/serving.md "Request durability".
        self.journal_dir = journal_dir
        # device-placement plan (serving/placement.py): disjoint
        # per-replica TP slices over the visible device set. Validated
        # HERE — an overlapping/oversubscribed/indivisible plan raises
        # PlacementError at config construction, before any engine (or
        # XLA mesh) exists.
        if placement is not None:
            if not isinstance(placement, PlacementPlan):
                raise PlacementError(
                    f"FleetConfig(placement=) takes a "
                    f"serving.PlacementPlan, got "
                    f"{type(placement).__name__}"
                )
            placement.validate(num_replicas)
        self.placement = placement
        # elastic scaling policy: needs a placement plan (a scaled-up
        # replica must have a slice to land on)
        if scaling is not None:
            if not isinstance(scaling, ScalingPolicy):
                raise ValueError(
                    f"FleetConfig(scaling=) takes a "
                    f"serving.ScalingPolicy, got "
                    f"{type(scaling).__name__}"
                )
            if placement is None:
                raise ValueError(
                    "FleetConfig(scaling=) requires placement=: the "
                    "autoscaler can only spawn replicas onto placement "
                    "slices"
                )
        self.scaling = scaling


class FleetMetrics:
    """Fleet-level counters (host-side plain attributes, same contract
    as ``EngineMetrics``: the registry PULLS at scrape time through the
    fleet's collector view, nothing is written on the hot path)."""

    def __init__(self):
        self.requests_received = 0
        self.requests_finished = 0
        self.requests_shed = 0        # bounced off the max_pending bound
        self.requests_timeout = 0     # TTL-expired while parked pending
        self.journal_replayed = 0     # requests recovered from the WAL
        self.failovers = 0            # replica deaths recovered from
        self.failover_requests = 0    # in-flight requests re-enqueued
        self.hedges_started = 0
        self.hedges_won = 0           # hedge dispatch delivered the win
        self.hedges_lost = 0          # primary beat its hedge
        self.restarts = 0             # successful rebuilds (crash+rolling)
        self.replicas_failed = 0      # permanent failures (fleet shrank)
        self.route_errors = 0
        self.route_prefix_hits = 0    # placements won by prefix affinity
        self.scale_ups = 0            # replicas added (manual+autoscale)
        self.scale_downs = 0          # replicas released
        self.scale_errors = 0         # degraded scaling ops (fault/spawn)
        self.requests_migrated = 0    # in-flight moved off a departing replica
        # failover recovery timing (the bench [fleet] row): stamped at
        # death detection and at the first token a re-enqueued request
        # produces on its new replica
        self.last_failover_detect_s = None
        self.last_recovered_token_s = None

    @property
    def failover_recovery_s(self):
        """Kill-to-first-recovered-token of the most recent failover,
        or None."""
        if (self.last_failover_detect_s is None
                or self.last_recovered_token_s is None
                or self.last_recovered_token_s
                < self.last_failover_detect_s):
            return None
        return self.last_recovered_token_s - self.last_failover_detect_s


# counter attribute -> exported series name
_FLEET_COUNTERS = {
    "requests_received": "paddle_tpu_fleet_requests_received_total",
    "requests_finished": "paddle_tpu_fleet_requests_finished_total",
    "requests_shed": "paddle_tpu_fleet_requests_shed_total",
    "requests_timeout": "paddle_tpu_fleet_requests_timeout_total",
    "journal_replayed": "paddle_tpu_fleet_journal_replayed_total",
    "failovers": "paddle_tpu_fleet_failovers_total",
    "failover_requests": "paddle_tpu_fleet_failover_requests_total",
    "hedges_started": "paddle_tpu_fleet_hedges_started_total",
    "hedges_won": "paddle_tpu_fleet_hedges_won_total",
    "hedges_lost": "paddle_tpu_fleet_hedges_lost_total",
    "restarts": "paddle_tpu_fleet_restarts_total",
    "replicas_failed": "paddle_tpu_fleet_replicas_failed_total",
    "route_errors": "paddle_tpu_fleet_route_errors_total",
    "route_prefix_hits": "paddle_tpu_fleet_route_prefix_hits_total",
    "scale_ups": "paddle_tpu_fleet_scale_ups_total",
    "scale_downs": "paddle_tpu_fleet_scale_downs_total",
    "scale_errors": "paddle_tpu_fleet_scale_errors_total",
    "requests_migrated": "paddle_tpu_fleet_requests_migrated_total",
}

# supervisor status -> the lifecycle state exported on the
# paddle_tpu_fleet_replicas{state=} gauge (scale events read as edges:
# spawning -> live on scale-up, draining -> released on scale-down)
_REPLICA_STATES = ("spawning", "live", "draining", "released", "failed")
_STATUS_TO_STATE = {
    "offline": "spawning", "quarantined": "spawning",
    "healthy": "live", "draining": "draining",
    "released": "released", "failed": "failed",
}


def _register_view(fleet):
    """Pull-time collector over one fleet (weakref: a collected fleet's
    view unregisters itself, mirroring EngineMetrics)."""
    ref = weakref.ref(fleet)
    name = f"serving.fleet.{fleet.fleet_id}"

    def latency_view():
        fl = ref()
        return None if fl is None else fl.merged_latency()

    # replica digests merged AT PULL TIME (merge == pooled, so the
    # fleet-labeled paddle_tpu_serving_latency_seconds series is
    # exactly what one engine serving all the traffic would export)
    register_latency_view(
        f"serving.fleet.latency.{fleet.fleet_id}", latency_view,
        "paddle_tpu_serving_latency", labels={"fleet": fleet.fleet_id},
    )

    def collect():
        fl = ref()
        if fl is None:
            return None
        label = {"fleet": fl.fleet_id}
        m = fl.metrics
        fams = [
            MetricFamily(series, "counter").add(getattr(m, attr), label)
            for attr, series in _FLEET_COUNTERS.items()
        ]
        fams.append(MetricFamily(
            "paddle_tpu_fleet_replicas_total", "gauge",
        ).add(fl.size(), label))
        fams.append(MetricFamily(
            "paddle_tpu_fleet_replicas_healthy", "gauge",
        ).add(
            sum(s.status == "healthy" for s in fl.replicas), label,
        ))
        fams.append(MetricFamily(
            "paddle_tpu_fleet_pending_requests", "gauge",
        ).add(len(fl._pending), label))
        up = MetricFamily("paddle_tpu_fleet_replica_healthy", "gauge")
        restarts = MetricFamily(
            "paddle_tpu_fleet_replica_restarts_total", "counter",
        )
        # per-replica KV/prefix-cache economics: hit tokens saved,
        # computed prefill tokens, and reclaimable (cached, idle)
        # blocks — the router-facing split of pool pressure
        pfx_hits = MetricFamily(
            "paddle_tpu_fleet_replica_prefix_hits_total", "counter",
        )
        pfx_tokens = MetricFamily(
            "paddle_tpu_fleet_replica_prefix_hit_tokens_total",
            "counter",
        )
        pfill = MetricFamily(
            "paddle_tpu_fleet_replica_prefill_tokens_total", "counter",
        )
        reclaimable = MetricFamily(
            "paddle_tpu_fleet_replica_kv_reclaimable_blocks", "gauge",
        )
        # absorbable capacity per replica (free + reclaimable blocks):
        # the headroom-aware router's input, exported so a capacity
        # review can see WHY requests routed where they did
        headroom = MetricFamily(
            "paddle_tpu_fleet_replica_kv_headroom_blocks", "gauge",
        )
        # tensor-parallel degree per replica: a router/dashboard must
        # tell a 4-chip replica's capacity from a 1-chip one's
        tp_deg = MetricFamily(
            "paddle_tpu_fleet_replica_tp_degree", "gauge",
        )
        # host spill tier per replica (serving/spill.py): occupancy
        # and restore hit rate, so a fleet review sees which replicas
        # are surviving pressure by swapping instead of recomputing
        spill_bytes = MetricFamily(
            "paddle_tpu_fleet_replica_spill_host_bytes", "gauge",
        )
        spill_hit = MetricFamily(
            "paddle_tpu_fleet_replica_spill_restore_hit_rate", "gauge",
        )
        for sup in fl.replicas:
            rl = {**label, "replica": sup.name}
            up.add(1.0 if sup.status == "healthy" else 0.0, rl)
            restarts.add(sup.restarts, rl)
            eng = sup.engine
            if eng is not None:
                em = eng.metrics
                pfx_hits.add(em.prefix_hits, rl)
                pfx_tokens.add(em.prefix_hit_tokens, rl)
                pfill.add(em.prefill_tokens, rl)
                reclaimable.add(em.kv_reclaimable_blocks, rl)
                headroom.add(em.kv_headroom_blocks, rl)
                tp_deg.add(em.tp_degree, rl)
                tier = getattr(eng, "spill", None)
                if tier is not None:
                    ts = tier.stats()
                    spill_bytes.add(ts["host_bytes"], rl)
                    if ts["restore_hit_rate"] is not None:
                        spill_hit.add(ts["restore_hit_rate"], rl)
        fams += [
            up, restarts, pfx_hits, pfx_tokens, pfill, reclaimable,
            headroom, tp_deg,
        ]
        if spill_bytes.samples:
            fams.append(spill_bytes)
        if spill_hit.samples:
            fams.append(spill_hit)
        # replica lifecycle states, zero-filled over every state so a
        # scale event is a visible edge (0->1 spawning, 1->0 live, ...)
        # even on a fleet that has never scaled; released replicas are
        # the retired ring (bounded), not fl.replicas
        states = MetricFamily("paddle_tpu_fleet_replicas", "gauge")
        counts = dict.fromkeys(_REPLICA_STATES, 0)
        for sup in fl.replicas:
            counts[_STATUS_TO_STATE.get(sup.status, "live")] += 1
        counts["released"] += len(fl._retired)
        for st in _REPLICA_STATES:
            states.add(counts[st], {**label, "state": st})
        fams.append(states)
        # device placement: one sample per (replica, device id) — the
        # scrape-side proof that slices are disjoint and scale-ups
        # landed on unused chips
        devs = MetricFamily("paddle_tpu_fleet_replica_devices", "gauge")
        for sup in fl.replicas:
            if sup.devices:
                for did in sup.devices:
                    devs.add(1.0, {
                        **label, "replica": sup.name,
                        "device": f"{did}",
                    })
        if devs.samples:
            fams.append(devs)
        cfg, pooled = fl._slo_pool()
        if cfg is not None:
            # fleet-level burn from POOLED windows (the per-replica
            # math over summed counts — a replica serving 10x the
            # traffic weighs 10x, which per-replica averaging loses);
            # one pool walk feeds both gauges
            burn = MetricFamily("paddle_tpu_fleet_slo_burn_rate",
                                "gauge")
            for sig, v in sorted(burn_from_counts(pooled, cfg).items()):
                if v is not None:
                    burn.add(v, {**label, "signal": sig})
            if burn.samples:
                fams.append(burn)
            fams.append(MetricFamily(
                "paddle_tpu_fleet_slo_burning", "gauge",
            ).add(1.0 if sustained_burn(pooled, cfg) else 0.0, label))
        return fams

    get_registry().register_collector(name, collect)


def _merge_digests(dst, src):
    """Fold a phase→LatencyDigest dict into another (merge-or-copy per
    phase) — the ONE merge semantic behind both the pull-time
    ``merged_latency`` view and the death-time ``_absorb_latency``
    fold, so the two can never diverge."""
    for phase, d in src.items():
        if phase in dst:
            dst[phase].merge(d)
        else:
            dst[phase] = d.copy()


class _Dispatch:
    """One placement of a request on one replica."""

    __slots__ = (
        "fleet_req", "request", "replica", "kind", "time", "cancelled",
        "finished",
    )

    def __init__(self, fleet_req, request, replica, kind):
        self.fleet_req = fleet_req
        self.request = request      # the engine-side Request object
        self.replica = replica      # replica NAME (survives restarts)
        self.kind = kind            # "primary" | "hedge"
        self.time = time.perf_counter()
        self.cancelled = False      # we aborted it (hedge loser)
        self.finished = False       # its engine emitted an output


class FleetRequest:
    """Client-facing handle for one fleet request. The underlying
    engine ``Request`` object travels with it across replicas
    (failover re-submits the SAME object, tokens intact)."""

    def __init__(self, prompt_token_ids, sampling_params, request_id):
        self.request = Request(
            prompt_token_ids, sampling_params, request_id
        )
        self.dispatches: list = []
        self.hedged = False
        self.done = False
        self.output = None
        self._chain_digests: dict = {}   # page_size -> prompt digests

    def chain_digests(self, block_size):
        """This prompt's chain digests at ``block_size`` granularity,
        hashed once per request lifetime (the hit-aware router matches
        them against replicas every sweep the request stays parked)."""
        d = self._chain_digests.get(block_size)
        if d is None:
            d = self._chain_digests[block_size] = prompt_chain_digests(
                self.prompt_token_ids, block_size
            )
        return d

    @property
    def request_id(self):
        return self.request.request_id

    @property
    def prompt_token_ids(self):
        return self.request.prompt_token_ids

    @property
    def sampling_params(self):
        return self.request.sampling_params

    def __repr__(self):
        return (
            f"FleetRequest(id={self.request_id}, done={self.done}, "
            f"dispatches={len(self.dispatches)})"
        )


class Fleet:
    """N supervised Engine replicas behind one engine-shaped facade.

        fleet = serving.Fleet(model, serving.EngineConfig(...),
                              serving.FleetConfig(num_replicas=2))
        outs = fleet.generate(prompts, serving.SamplingParams(...))

    or stream it like an engine::

        fleet.add_request(ids, params)
        while fleet.has_unfinished():
            for out in fleet.step():
                handle(out)
    """

    def __init__(self, model, engine_config=None, config=None):
        self.config = config or FleetConfig()
        self.engine_config = engine_config
        if (engine_config is not None
                and getattr(engine_config, "journal", None) is not None):
            raise ValueError(
                "EngineConfig(journal=) under a Fleet would make every "
                "replica replay — and double-admit — the same journal; "
                "use FleetConfig(journal_dir=) so the fleet journals "
                "once at its front door"
            )
        self._model = model
        self.fleet_id = f"{next(_fleet_counter)}"
        self.metrics = FleetMetrics()
        # fleet-local observability for requests that finish WITHOUT
        # reaching an engine (parked timeout, pending abort,
        # unplaceable): the overload tail is exactly what must not
        # vanish from the digests/SLO/access log, so _finish_local
        # records here and merged_latency()/_slo_pool() fold it in
        self._local_latency = {
            p: LatencyDigest() for p in ("queue", "ttft", "tpot", "e2e")
        }
        # makes absorb-and-drop atomic against a concurrent scrape's
        # merged_latency(): a dying replica's samples must move from
        # its engine digests to the fleet-local set in ONE observable
        # step, or the merged _count double-counts (or dips — either
        # reads as a counter reset to Prometheus) mid-failover
        self._latency_lock = threading.Lock()
        self._local_slo = None
        self._access_log = None
        if engine_config is not None:
            if engine_config.slo is not None:
                self._local_slo = SLOTracker(engine_config.slo)
            if engine_config.access_log is not None:
                from .access_log import resolve_access_log

                self._access_log = resolve_access_log(
                    engine_config.access_log
                )
        plan = self.config.placement
        if plan is not None and (
            engine_config is None
            or engine_config.tp_degree != plan.tp_degree
        ):
            raise PlacementError(
                f"FleetConfig(placement=) carves slices of "
                f"{plan.tp_degree} device(s) but EngineConfig("
                f"tp_degree="
                f"{getattr(engine_config, 'tp_degree', None)}) does "
                f"not match: the slice width IS the replica's "
                f"tensor-parallel degree"
            )
        self.replicas: list = []
        for i in range(self.config.num_replicas):
            sup = self._make_supervisor(
                f"r{i}",
                devices=plan.slice_ids(i) if plan is not None else None,
                slice_index=i if plan is not None else None,
            )
            sup.spawn()
            self.replicas.append(sup)
        # scale-up names continue past the seed replicas and are never
        # reused (metric labels / journal epoch records must not alias
        # a released replica with a later one)
        self._replica_counter = itertools.count(self.config.num_replicas)
        # released supervisors (scale-down), kept for the state gauge
        # and introspection; bounded so a long-lived elastic fleet
        # cannot grow it without limit
        self._retired: list = []
        self._autoscaler = (
            Autoscaler(self.config.scaling)
            if self.config.scaling is not None else None
        )
        self._pending: collections.deque = collections.deque()
        # optional multi-tenant QoS (serving/qos.py): when attached,
        # the dispatch sweep replaces FIFO with weighted fair-share
        # selection and completions feed per-tenant accounting
        self.qos = None
        self._routes: dict = {}     # engine request id -> _Dispatch
        self._ready: list = []      # finished client outputs, buffered
        self._req_counter = itertools.count()
        # (Request, n_tokens_at_failover) pairs awaiting their first
        # post-failover token — the recovery-time probe
        self._recovering: list = []
        # durable request journal at the fleet front door: replayed
        # AFTER the replicas spawn (a shared compile cache has already
        # warmed their programs — recovery re-prefills are zero-trace)
        # and BEFORE any traffic is accepted
        self.journal = None
        if self.config.journal_dir is not None:
            from .journal import resolve_journal

            seed = (
                engine_config.seed if engine_config is not None else 0
            )
            self.journal = resolve_journal(
                self.config.journal_dir, seed=seed
            )
            self._replay_journal()
        _register_view(self)

        def _probe(ref=weakref.ref(self)):
            fl = ref()
            return None if fl is None else fl.health()

        register_health_provider(f"serving.fleet.{self.fleet_id}", _probe)

    def _make_supervisor(self, name, devices=None, slice_index=None):
        cfg = self.config
        # the factory closes over the fleet (not a model snapshot) so
        # rolling_restart(model=...) reloads weights on rebuild
        if devices is None:
            factory = lambda: Engine(self._model, self.engine_config)
        else:
            def factory(devices=list(devices)):
                # the slice is baked into the factory, so EVERY build
                # of this replica — first spawn, background crash
                # restart (restart_policy.call(self._build, ...)),
                # rolling rebuild — lands on ITS devices, never the
                # fleet-wide shared list. fleet.place is the
                # deterministic placement-failure injection point.
                faults.fire(
                    "fleet.place", fleet=self.fleet_id, replica=name,
                    devices=devices,
                )
                ecfg = copy.copy(self.engine_config)
                ecfg.devices = devices
                return Engine(self._model, ecfg)
        return ReplicaSupervisor(
            name,
            factory=factory,
            restart_policy=cfg.restart_policy,
            max_restarts=cfg.max_restarts,
            analysis_check=cfg.analysis_check,
            devices=devices,
            slice_index=slice_index,
        )

    # -- durable request journal ---------------------------------------------
    def _replay_journal(self):
        """Crash recovery at the fleet front door: unfinished journal
        entries become FleetRequests at the HEAD of the pending queue
        (oldest first), tokens intact — dispatch places them through
        the resume() re-prefill path, so greedy continuations are
        byte-identical and no journaled token is re-emitted. TTLs that
        lapsed while the fleet was down retire as ``"timeout"``
        without touching a replica. Recovered work bypasses
        ``max_pending``: bounded admission must never drop requests
        the fleet already accepted."""
        entries = self.journal.replay()
        report = self.journal.replay_report or {}
        if report.get("interrupted_ops"):
            # a scaling op's *-begin with no *-end: the crash landed
            # mid-shrink/mid-restart. Delivery is still exactly-once
            # (the migration re-ADMITs won the latest-ADMIT-wins fold
            # before the epoch bracket closed) — surfaced here so the
            # postmortem shows WHICH op was cut short
            _flight.record(
                "fleet", "scale-interrupted", fleet=self.fleet_id,
                ops=report["interrupted_ops"],
            )
        # fleet rids are "fleet<id>-<n>": a fresh process restarts the
        # counter at 0, which would collide new rids with replayed
        # ones — advance past every journaled suffix
        mx = -1
        prefix = f"fleet{self.fleet_id}-"
        for e in entries:
            if isinstance(e.rid, str) and e.rid.startswith(prefix):
                tail = e.rid[len(prefix):]
                if tail.isdigit():
                    mx = max(mx, int(tail))
        if mx >= 0:
            self._req_counter = itertools.count(mx + 1)
        from .journal import restore_entries

        live, expired = restore_entries(
            self.journal, entries,
            lambda e, params: FleetRequest(e.prompt, params, e.rid),
        )
        self.metrics.requests_timeout += expired
        for freq in live:  # re-ADMIT in order, emit cursor carried
            self.journal.admit(freq.request)
        self.journal.flush()
        self._pending.extendleft(reversed(live))
        self.metrics.journal_replayed += len(live)
        self.metrics.requests_received += len(live)
        if entries:
            _flight.record(
                "fleet", "journal-recovered", fleet=self.fleet_id,
                requests=len(live), expired=len(entries) - len(live),
            )

    # -- introspection -------------------------------------------------------
    def replica(self, name):
        for sup in self.replicas:
            if sup.name == name:
                return sup
        raise KeyError(f"no replica {name!r} in fleet {self.fleet_id}")

    def size(self):
        """Live (non-permanently-failed) replica count."""
        return sum(s.status != "failed" for s in self.replicas)

    def has_unfinished(self):
        return bool(self._pending) or bool(self._routes) or bool(
            self._ready
        ) or any(
            s.engine is not None and s.engine.has_unfinished()
            for s in self.replicas
        )

    def health(self):
        """Fleet health snapshot (scrape /healthz provider): "ok" while
        at least one replica is routable, "degraded" while live-but-
        unroutable replicas remain (or the POOLED SLO window is
        burning — replicas can each sit under the per-replica sample
        floor while the fleet as a whole blows the objective),
        "failed" when the fleet is gone."""
        statuses = {s.name: s.status for s in self.replicas}
        routable = sum(s.routable() for s in self.replicas)
        # ONE pool walk per probe: burning and the rates derive from
        # the same counts (each _slo_pool takes every tracker's lock)
        cfg, pooled = self._slo_pool()
        burning = cfg is not None and sustained_burn(pooled, cfg)
        if not self.size():
            status = "failed"
        elif routable and not burning:
            status = "ok"
        else:
            status = "degraded"
        out = {
            "status": status,
            "replicas": statuses,
            "routable": routable,
            "pending": len(self._pending),
            "in_flight": len(self._routes),
            "slo_burn": burning,
            "slo_burn_rates": (
                burn_from_counts(pooled, cfg)
                if cfg is not None else None
            ),
        }
        if self.config.placement is not None:
            out["placement"] = {
                s.name: list(s.devices or []) for s in self.replicas
            }
        return out

    def _absorb_latency(self, sup):
        """Fold a dying/rebuilding replica's cumulative latency digests
        into the fleet-local set and drop its engine, atomically with
        respect to ``merged_latency`` — the merged summary's
        _count/_sum must stay monotonic across failovers and rolling
        restarts (a concurrent scrape must never see the samples in
        both places, or in neither), and the killed replica's samples
        ARE the failover tail the merged view exists to keep. (The
        replica's short SLO window dies with it: burn is a now-signal
        and a dead replica is not serving.)"""
        with self._latency_lock:
            eng, sup.engine = sup.engine, None
            if eng is not None:
                _merge_digests(self._local_latency, eng.metrics.latency)
        return eng

    def merged_latency(self):
        """Per-phase latency digests merged across live replicas at
        call time — identical to one pooled digest by the merge
        invariant — seeded with the fleet-local samples (requests
        that finished without reaching an engine). The fleet-level
        percentile source (collector view, bench, operators via
        ``observability slo``)."""
        with self._latency_lock:
            # one consistent cut: local copies + the engine refs they
            # do NOT yet include (engine digests have their own locks;
            # merging outside ours is safe once the cut is taken)
            merged = {
                p: d.copy() for p, d in self._local_latency.items()
            }
            engines = [
                s.engine for s in self.replicas if s.engine is not None
            ]
        for eng in engines:
            _merge_digests(merged, eng.metrics.latency)
        return merged

    def _slo_pool(self):
        """``(config, pooled_window_counts)`` across replica SLO
        trackers (None config when no replica tracks an SLO). Pooling
        the raw window counts — not the per-replica burn rates —
        weighs each replica by its actual traffic."""
        cfg, pooled = None, {}
        trackers = [self._local_slo] if self._local_slo else []
        trackers += [
            sup.engine.slo for sup in self.replicas
            if sup.engine is not None and sup.engine.slo is not None
        ]
        for t in trackers:
            if cfg is None:
                cfg = t.config
            for k, v in t.window_counts().items():
                pooled[k] = pooled.get(k, 0) + v
        return cfg, pooled

    def slo_burn_rates(self):
        """Fleet-level burn per signal, or None without an SLO."""
        cfg, pooled = self._slo_pool()
        return burn_from_counts(pooled, cfg) if cfg is not None else None

    def slo_burning(self):
        """Sustained fleet-level burn: the per-engine predicate
        (``latency.sustained_burn``) over pooled counts."""
        cfg, pooled = self._slo_pool()
        return cfg is not None and sustained_burn(pooled, cfg)

    def snapshot(self):
        """Fleet counters + per-replica status, one JSON-friendly
        dict."""
        m = self.metrics
        out = {attr: getattr(m, attr) for attr in _FLEET_COUNTERS}
        out["replicas"] = {
            s.name: {"status": s.status, "restarts": s.restarts,
                     "devices": s.devices}
            for s in self.replicas
        }
        if self._retired:
            out["retired"] = [s.name for s in self._retired]
        out["pending"] = len(self._pending)
        return out

    def _live(self):
        return [s for s in self.replicas if s.status != "failed"]

    # -- client API ----------------------------------------------------------
    def add_request(self, prompt_token_ids, sampling_params=None,
                    request_id=None, tenant=None):
        if not self._live():
            raise NoReplicaError(
                f"fleet {self.fleet_id}: all replicas permanently failed"
            )
        cfg_f = self.config
        if (cfg_f.max_pending is not None
                and sum(not f.done for f in self._pending)
                >= cfg_f.max_pending):
            # counted over LIVE parked requests only: a done entry
            # still parked (its hedge won after the primary's replica
            # died; purged lazily at the queue head) is not backlog
            # bounded admission (the engine's shedding semantics at
            # fleet altitude): an unroutable backlog pushes back on
            # the client instead of growing without limit
            self.metrics.requests_shed += 1
            _flight.record(
                "fleet", "shed", fleet=self.fleet_id,
                pending=len(self._pending), tenant=tenant,
            )
            if self.qos is not None:
                self.qos.count_queue_shed(tenant)
            raise EngineOverloadedError(
                f"fleet {self.fleet_id} pending queue full "
                f"({cfg_f.max_pending} parked); request shed"
            )
        if request_id is None:
            request_id = f"fleet{self.fleet_id}-{next(self._req_counter)}"
        freq = FleetRequest(prompt_token_ids, sampling_params, request_id)
        # tenant set BEFORE the journal ADMIT below so the "tn" field
        # rides the WAL and replay restores the QoS accounting
        freq.request.tenant = tenant
        # surface the engine's admission error NOW, not on a later
        # dispatch attempt deep inside step(). Falls back to the fleet's
        # engine config while every replica is quarantined (engine is
        # None) so an over-long prompt can never park unvalidated.
        cfg = self.engine_config or EngineConfig()
        for sup in self._live():
            if sup.engine is not None:
                cfg = sup.engine.config
                break
        if len(freq.prompt_token_ids) >= cfg.max_model_len:
            raise ValueError(
                f"prompt of {len(freq.prompt_token_ids)} tokens "
                f"leaves no room to generate under "
                f"max_model_len={cfg.max_model_len}"
            )
        self.metrics.requests_received += 1
        self._pending.append(freq)
        if self.qos is not None:
            # admission-time accounting stamps the fair-queuing
            # virtual tags; parked requests age against later arrivals
            self.qos.on_admit(freq.request)
        if self.journal is not None:
            # WAL the admission before dispatch: once flushed, a crash
            # replays this request instead of losing it
            self.journal.admit(freq.request)
            self.journal.flush()
        self._dispatch_pending()
        return freq

    def abort(self, request_id):
        """Abort a fleet request wherever it is; returns True if
        found. A dispatched request finishes with
        ``finish_reason="aborted"`` through its replica's next step."""
        for freq in list(self._pending):
            if freq.request_id == request_id:
                self._pending.remove(freq)
                if freq.done:
                    # completed while parked (hedge won after its
                    # primary died): nothing left to abort
                    return False
                # a failover-requeued request may still carry a live
                # hedge dispatch: cancel it so it doesn't keep
                # decoding for a dead client, and close the hedge
                # accounting (resolution is local, not via _collect)
                for disp in freq.dispatches:
                    if disp.cancelled or disp.finished:
                        continue
                    disp.cancelled = True
                    sup = self._sup_or_none(disp.replica)
                    if sup is not None and sup.engine is not None:
                        sup.engine.abort(disp.request.request_id)
                if freq.hedged:
                    self.metrics.hedges_lost += 1
                self._finish_local(freq, "aborted")
                return True
        for d in list(self._routes.values()):
            if (d.fleet_req.request_id != request_id
                    or d.kind != "primary" or d.cancelled):
                continue
            freq = d.fleet_req
            if freq.done:
                return False
            # abort EVERY live dispatch — a hedge left running could
            # win the race against the abort and deliver a normal
            # completion. The primary is NOT marked cancelled (its
            # aborted output surfaces through _collect as this
            # request's completion); hedges are, so theirs is
            # swallowed.
            found = False
            for disp in freq.dispatches:
                if disp.cancelled or disp.finished:
                    continue
                sup = self._sup_or_none(disp.replica)
                if (sup is not None and sup.engine is not None
                        and sup.engine.abort(disp.request.request_id)):
                    found = True
                if disp.kind == "hedge":
                    disp.cancelled = True
            return found
        return False

    def _finish_local(self, freq, reason, error=None):
        """Finish a fleet request that never reached (or never
        returned from) an engine — pending abort, unplaceable — with
        the full completion accounting a routed request gets."""
        req = freq.request
        req.error = error
        req.finish_reason = reason
        req.state = RequestState.FINISHED
        req.finish_time = time.perf_counter()
        # close the timeline too (a request that timed out parked
        # still deserves a phase breakdown on RequestOutput.metrics),
        # then the SAME finish accounting an engine would do — local
        # digests (e2e at least; queue/ttft belong to whatever engine
        # life it had, which already recorded them), SLO window,
        # access-log line, flight ring — via the shared helper
        req.timeline.mark_finish(reason, req.finish_time)
        record_finish(
            req, latency=self._local_latency, slo=self._local_slo,
            access_log=self._access_log, fleet=self.fleet_id,
        )
        freq.done = True
        freq.output = RequestOutput(req)
        self.metrics.requests_finished += 1
        if self.qos is not None:
            self.qos.on_finish(req)
        if self.journal is not None:
            self.journal.finish(req, reason)
            self.journal.flush()
        self._ready.append(freq.output)

    def step(self):
        """One fleet scheduler iteration; returns finished client
        RequestOutputs (buffered outputs from internal stepping — a
        drain, a rolling restart — are delivered here too)."""
        self._step_once()
        out, self._ready = self._ready, []
        return out

    def generate(self, prompts, sampling_params=None):
        """Submit everything, step until done, return outputs in
        submission order (the Engine.generate contract, fleet-wide)."""
        params = normalize_sampling_params(prompts, sampling_params)
        reqs = [
            self.add_request(p, sp) for p, sp in zip(prompts, params)
        ]
        done = {}
        idle = 0
        while not all(r.done for r in reqs):
            if not self._live():
                raise NoReplicaError(
                    f"fleet {self.fleet_id}: all replicas failed with "
                    f"{sum(not r.done for r in reqs)} request(s) "
                    "unfinished"
                )
            before = len(done)
            for out in self.step():
                done[out.request_id] = out
            stepped = any(
                s.engine is not None and s.engine.has_unfinished()
                for s in self.replicas
            )
            idle = 0 if (len(done) > before or stepped) else idle + 1
            if idle > 2:
                if (idle > 50 and self._pending and not self._routes
                        and self._pick_replica() is None
                        and not any(s.status == "quarantined"
                                    for s in self.replicas)):
                    # nothing in flight, nothing restarting, and the
                    # pending work has no routable target (e.g. the
                    # only replica was drained and never resumed):
                    # no fleet state change can ever unstick this —
                    # diagnose instead of blocking forever
                    raise RuntimeError(
                        f"fleet {self.fleet_id}: {len(self._pending)} "
                        "request(s) cannot be placed — no routable "
                        "replica and no restart in flight (replicas: "
                        + ", ".join(
                            f"{s.name}={s.status}"
                            for s in self.replicas
                        ) + ")"
                    )
                # nothing to step and nothing finishing: wait out a
                # background restart instead of spinning
                time.sleep(0.005)
        # flush hedge losers: their aborts finish on the next step of
        # their replicas, and leaving them in flight would make a
        # drained fleet report unfinished work
        guard = 0
        while (self._routes
               and all(d.cancelled for d in self._routes.values())
               and guard < 100):
            for out in self.step():
                done[out.request_id] = out
            guard += 1
        if self._ready:
            # late bookkeeping (e.g. every request finished locally
            # before a step ran): harvest AND clear, or the next
            # step() would deliver these completions a second time
            for out in self._ready:
                done[out.request_id] = out
            self._ready = []
        return [done[r.request_id] for r in reqs]

    # -- drain / rolling restart ---------------------------------------------
    def drain(self, replica, max_steps=10000):
        """Stop admission to ``replica`` and step the fleet until its
        in-flight work completes (other replicas keep serving; their
        finished outputs are buffered for the next ``step()``)."""
        sup = self.replica(replica) if isinstance(replica, str) else replica
        if sup.status == "failed":
            return sup
        if sup.status == "healthy":
            sup.status = "draining"
        for _ in range(max_steps):
            if sup.engine is None or not sup.engine.has_unfinished():
                return sup
            self._step_once()
        raise RuntimeError(
            f"drain of replica {sup.name!r} did not converge in "
            f"{max_steps} steps"
        )

    def resume_replica(self, replica):
        """Re-admit a drained replica."""
        sup = self.replica(replica) if isinstance(replica, str) else replica
        if sup.status == "draining":
            sup.status = "healthy"
        return sup

    def rolling_restart(self, min_available=1, model=None):
        """Cycle every live replica through drain → rebuild → rejoin —
        weight reload without dropping requests. ``model`` (optional)
        replaces the weights used for every subsequent build. At least
        ``min_available`` replicas stay admitting throughout; rolling
        rebuilds are operator-initiated and do NOT spend the crash
        restart budget."""
        live = self._live()
        if not 0 <= min_available <= len(live) - 1:
            raise ValueError(
                f"min_available={min_available} must leave a replica to "
                f"restart (fleet has {len(live)} live replica(s))"
            )
        if model is not None:
            self._model = model
        for sup in list(live):
            if sup.status not in ("healthy", "draining"):
                continue  # quarantined replicas are already rebuilding
            healthy_others = sum(
                s is not sup and s.status == "healthy"
                for s in self.replicas
            )
            if healthy_others < min_available:
                raise RuntimeError(
                    f"cannot restart replica {sup.name!r}: only "
                    f"{healthy_others} other healthy replica(s), "
                    f"min_available={min_available}"
                )
            # journal-backed migration instead of stepping out a full
            # drain: in-flight work moves to the pending-queue HEAD and
            # re-places through resume() (greedy byte-identical) while
            # this replica rebuilds — the restart no longer waits for
            # its longest request
            if sup.status == "healthy":
                sup.status = "draining"
            if self.journal is not None:
                self.journal.epoch("restart-begin", replica=sup.name)
                self.journal.flush()
            self._migrate_inflight(sup)
            with span("fleet.restart", replica=sup.name, rolling=True):
                self._absorb_latency(sup)  # folds digests, drops engine
                try:
                    sup.spawn()
                except Exception as e:
                    sup.last_error = f"{type(e).__name__}: {e}"
                    sup.status = "failed"
                    self.metrics.replicas_failed += 1
                    _flight.record(
                        "fleet", "rolling-restart-failed",
                        fleet=self.fleet_id, replica=sup.name,
                        error=sup.last_error,
                    )
                    continue
            self.metrics.restarts += 1
            if self.journal is not None:
                self.journal.epoch("restart-end", replica=sup.name)
                self.journal.flush()
            _flight.record(
                "fleet", "rolling-restart", fleet=self.fleet_id,
                replica=sup.name,
            )
            # migrated work re-places now (possibly straight back onto
            # the rebuilt replica) instead of waiting for the next step
            self._dispatch_pending()
        return self

    # -- elastic scaling -----------------------------------------------------
    def _free_slice_index(self):
        """Lowest placement slice no non-failed replica holds, or None
        (quarantined replicas keep their slice — the background
        restart rebuilds onto it; permanently failed and released
        replicas give theirs up)."""
        plan = self.config.placement
        if plan is None:
            return None
        held = {
            s.slice_index for s in self.replicas
            if s.slice_index is not None and s.status != "failed"
        }
        for i in range(plan.capacity()):
            if i not in held:
                return i
        return None

    def scale_up(self, reason="manual"):
        """Spawn one replica onto the lowest unused placement slice.
        Returns the new supervisor, or None when no slice is free or
        the op degraded (an injected ``fleet.scale``/``fleet.place``
        fault or a spawn failure is counted and flight-recorded, never
        raised — a failed scale-up must not take down serving
        traffic). The spawn is synchronous: on a warm shared compile
        cache it replays the manifest with zero fresh traces (the
        ~200ms restart path), so the new replica is routable on the
        very next dispatch sweep."""
        plan = self.config.placement
        if plan is None:
            raise RuntimeError(
                f"fleet {self.fleet_id} has no placement plan: "
                "scale_up needs FleetConfig(placement=) to know which "
                "devices a new replica may use"
            )
        idx = self._free_slice_index()
        if idx is None:
            return None
        name = f"r{next(self._replica_counter)}"
        devices = plan.slice_ids(idx)
        try:
            faults.fire(
                "fleet.scale", fleet=self.fleet_id, action="up",
                replica=name, reason=reason,
            )
            sup = self._make_supervisor(
                name, devices=devices, slice_index=idx
            )
            with span(
                "fleet.scale", action="up", replica=name,
                reason=reason,
            ):
                sup.spawn()
        except Exception as e:
            # analysis: allow(broad-except) the degradation contract
            # for scaling ops: a failed spawn (injected fault, OOM,
            # bad slice) is counted and the fleet keeps serving at its
            # current size
            self.metrics.scale_errors += 1
            _flight.record(
                "fleet", "scale-error", fleet=self.fleet_id,
                action="up", replica=name, devices=devices,
                error=f"{type(e).__name__}: {e}",
            )
            return None
        self.replicas.append(sup)
        self.metrics.scale_ups += 1
        if self.journal is not None:
            # epoch record: replay distinguishes a completed scale-up
            # from one the crash interrupted (idempotency itself rides
            # the ADMIT contract, not this marker)
            self.journal.epoch("scale-up", replica=name)
            self.journal.flush()
        _flight.record(
            "fleet", "scale-up", fleet=self.fleet_id, replica=name,
            devices=devices, reason=reason,
        )
        self._dispatch_pending()
        return sup

    def scale_down(self, replica=None, reason="manual"):
        """Release one replica (named, or the least-loaded healthy
        one): migrate its in-flight work to the pending-queue head,
        fold its telemetry, drop its engine — the slice is free for a
        later scale-up. Returns the released supervisor, or None when
        nothing can shrink (last serving replica, no healthy
        candidate) or the op degraded behind ``fleet.scale``. The
        journal brackets the migration in ``shrink-begin``/
        ``shrink-end`` epoch records, so a replay can report a
        mid-shrink crash (delivery stays exactly-once through the
        re-ADMITs' latest-ADMIT-wins keying either way)."""
        if replica is not None:
            sup = (
                self.replica(replica) if isinstance(replica, str)
                else replica
            )
            if sup.status not in ("healthy", "draining"):
                return None
        else:
            cands = [s for s in self.replicas if s.status == "healthy"]
            if not cands:
                return None
            sup = min(cands, key=lambda s: s.load())
        serving_after = sum(
            s is not sup and s.status in ("healthy", "draining")
            for s in self.replicas
        )
        if serving_after < 1:
            return None  # never shrink away the last serving replica
        try:
            faults.fire(
                "fleet.scale", fleet=self.fleet_id, action="down",
                replica=sup.name, reason=reason,
            )
        except Exception as e:
            # analysis: allow(broad-except) same degradation contract
            # as scale_up: a faulted shrink leaves the fleet as it was
            self.metrics.scale_errors += 1
            _flight.record(
                "fleet", "scale-error", fleet=self.fleet_id,
                action="down", replica=sup.name,
                error=f"{type(e).__name__}: {e}",
            )
            return None
        with span(
            "fleet.scale", action="down", replica=sup.name,
            reason=reason,
        ):
            sup.status = "draining"
            if self.journal is not None:
                self.journal.epoch("shrink-begin", replica=sup.name)
                self.journal.flush()
            migrated = self._migrate_inflight(sup)
            self._absorb_latency(sup)  # folds digests, drops engine
            sup.status = "released"
            self.replicas.remove(sup)
            self._retired.append(sup)
            del self._retired[:-8]
            if self.journal is not None:
                self.journal.epoch("shrink-end", replica=sup.name)
                self.journal.flush()
        self.metrics.scale_downs += 1
        _flight.record(
            "fleet", "scale-down", fleet=self.fleet_id,
            replica=sup.name, devices=sup.devices, reason=reason,
            migrated=migrated,
        )
        self._dispatch_pending()
        return sup

    def _migrate_inflight(self, sup):
        """Move every in-flight request off ``sup``'s LIVE engine:
        release (KV freed, no finish accounting), re-ADMIT to the
        journal with the emit cursor, and re-queue at the HEAD of the
        pending queue oldest-first — dispatch re-places them through
        the ``resume()`` re-prefill, so greedy continuations are
        byte-identical to an uninterrupted run. The migrated Request
        objects keep their arrival/deadline clocks and QoS fair-queue
        tags: ``_expire_pending`` sees the journaled arrival (TTL
        anchored at admission, not migration) and tenants are charged
        once. The live-engine sibling of ``_on_replica_death``'s
        route sweep; returns the number migrated."""
        eng = sup.engine
        if eng is None:
            return 0
        # finished-but-undelivered / cancelled / hedge routes first:
        # completions are delivered, hedge dispatches are dropped (the
        # primary keeps running elsewhere; resolution is counted at
        # its finish), cancelled losers just release their route
        for d in list(self._routes.values()):
            if d.replica != sup.name:
                continue
            req = d.request
            if req.state is RequestState.FINISHED:
                self._collect(RequestOutput(req))
            elif d.cancelled:
                self._routes.pop(req.request_id, None)
            elif d.kind == "hedge":
                d.finished = True
                self._routes.pop(req.request_id, None)
        moved = []
        slot_reqs = sorted(
            (r for r in eng.slots if r is not None),
            key=lambda r: r.admit_seq,
        )
        for req in slot_reqs + list(eng.waiting):
            d = self._routes.get(req.request_id)
            if (d is None or d.cancelled or d.kind != "primary"
                    or d.fleet_req.done):
                continue
            if eng.release(req.request_id) is None:
                continue
            self._routes.pop(req.request_id, None)
            freq = d.fleet_req
            freq.dispatches.remove(d)
            if self.journal is not None:
                # re-ADMIT with the emit cursor: replay never
                # re-counts tokens this request already produced, and
                # latest-ADMIT-wins makes a replayed migration
                # idempotent
                self.journal.admit(req)
            if self.qos is not None:
                self.qos.on_migrate(req)
            self.metrics.requests_migrated += 1
            _flight.record(
                "fleet", "migrate", fleet=self.fleet_id,
                replica=sup.name, request_id=freq.request_id,
                tokens_kept=len(req.output_token_ids),
            )
            moved.append(freq)
        # HEAD of the queue, oldest first: migrated work has been
        # waiting longest and must not queue behind fresh arrivals
        self._pending.extendleft(reversed(moved))
        if moved and self.journal is not None:
            self.journal.flush()
        return len(moved)

    def _autoscale(self, now):
        """One autoscaler tick (called once per scheduler step when
        ``FleetConfig(scaling=)`` is attached): feed the decision
        engine the pooled burn predicate, pending depth, and load;
        execute its verdict through the degradable scale ops. The
        cooldown clock is anchored on the DECISION, not its success —
        a failing spawn must not be re-attempted every step."""
        scaler = self._autoscaler
        if scaler is None:
            return None
        plan = self.config.placement
        decision = scaler.decide(
            now,
            burning=self.slo_burning(),
            pending=sum(not f.done for f in self._pending),
            live=self.size(),
            capacity=plan.capacity(),
            free_slice=self._free_slice_index() is not None,
            load=sum(
                s.load() for s in self.replicas
                if s.engine is not None
            ),
        )
        if decision == "up":
            scaler.note_action(now)
            self.scale_up(reason="autoscale")
        elif decision == "down":
            scaler.note_action(now)
            self.scale_down(reason="autoscale-idle")
        return decision

    # -- scheduler internals -------------------------------------------------
    def _sup_or_none(self, name):
        for sup in self.replicas:
            if sup.name == name:
                return sup
        return None

    def _pick_replica(self, exclude=()):
        candidates = [
            s for s in self.replicas
            if s.name not in exclude and s.routable()
        ]
        if not candidates:
            return None
        return min(candidates, key=lambda s: s.load())

    def _step_once(self):
        self._poll_restarts()
        # one error-watermark sweep per step: routable() stays
        # read-only, so health scrapes and repeated _pick_replica
        # calls can't consume the fresh-degraded admission gate
        for sup in self.replicas:
            sup.observe_errors()
        if self._autoscaler is not None:
            self._autoscale(time.perf_counter())
        self._expire_pending()
        self._dispatch_pending()
        if self.config.hedge_after_s is not None:
            self._maybe_hedge(time.perf_counter())
        for sup in list(self.replicas):
            if (sup.status not in ("healthy", "draining")
                    or sup.engine is None
                    or not sup.engine.has_unfinished()):
                continue
            try:
                outs = sup.step()
            except Exception as e:
                # analysis: allow(broad-except) a replica death is the
                # event this layer exists to contain: quarantine,
                # failover, restart — never crash the fleet
                self._on_replica_death(sup, e)
                continue
            for out in outs:
                self._collect(out)
        if self.journal is not None:
            # batched EMIT across every primary in flight (the fleet
            # owns the Request objects, which travel with their tokens
            # across replicas) + one group write for the whole fleet
            # step — a near-no-op until the write interval elapses or
            # a completion makes the buffer urgent
            self.journal.step_flush(
                d.request
                for d in self._routes.values()
                if d.kind == "primary" and not d.cancelled
            )
        if self._recovering:
            now = time.perf_counter()
            for req, n0 in list(self._recovering):
                if len(req.output_token_ids) > n0:
                    if self.metrics.last_recovered_token_s is None:
                        # FIRST recovered token since the failover
                        # (reset at death detection) — later requests
                        # must not inflate failover_recovery_s
                        self.metrics.last_recovered_token_s = now
                    self._recovering.remove((req, n0))
                elif req.state is RequestState.FINISHED:
                    # finished WITHOUT a new token (aborted/expired
                    # post-failover): not a recovery sample
                    self._recovering.remove((req, n0))

    def _expire_pending(self):
        """TTL enforcement for requests parked in the fleet pending
        queue: engine-side expiry (``Engine._expire``) only sees
        queued/running requests, so an UNROUTABLE request would
        otherwise outlive its ``ttl_s`` indefinitely. Expired parked
        requests finish with ``"timeout"`` — and any dispatch they
        still hold from a past life (a failover-requeued request's
        live hedge) is cancelled so it stops decoding for a client
        that already timed out."""
        if not self._pending:
            return
        now = time.perf_counter()
        for freq in [
            f for f in self._pending
            if not f.done and f.request.expired(now)
        ]:
            self._pending.remove(freq)
            self.metrics.requests_timeout += 1
            for disp in freq.dispatches:
                if disp.cancelled or disp.finished:
                    continue
                disp.cancelled = True
                sup = self._sup_or_none(disp.replica)
                if sup is not None and sup.engine is not None:
                    sup.engine.abort(disp.request.request_id)
            if freq.hedged:
                self.metrics.hedges_lost += 1
            _flight.record(
                "fleet", "timeout", fleet=self.fleet_id,
                request_id=freq.request_id, where="pending",
                tenant=getattr(freq.request, "tenant", None),
            )
            self._finish_local(freq, "timeout")

    def _poll_restarts(self):
        for sup in self.replicas:
            if sup.status != "quarantined":
                continue
            result = sup.poll()
            if result == "recovered":
                self.metrics.restarts += 1
                _flight.record(
                    "fleet", "replica-recovered", fleet=self.fleet_id,
                    replica=sup.name, restarts=sup.restarts,
                )
            elif result == "failed":
                self.metrics.replicas_failed += 1
                _flight.record(
                    "fleet", "replica-failed", fleet=self.fleet_id,
                    replica=sup.name, error=sup.last_error,
                )

    def _dispatch_pending(self):
        if not self._pending:
            return
        # routable set + loads computed ONCE per sweep (routable()
        # builds a health snapshot; re-deriving it per pending request
        # is O(pending x replicas) of waste), then tracked locally as
        # placements land so least-loaded stays balanced within the
        # sweep
        loads = {s: s.load() for s in self.replicas if s.routable()}
        # per-sweep snapshot of each candidate's cached chain digests
        # (hit-aware routing): chain_digests() walks the whole cache,
        # so it is taken at most once per replica per sweep, not per
        # pending request
        digests = {}
        while self._pending:
            # FIFO without QoS; with QoS attached the sweep dispatches
            # the weighted-fair-share pick (strict priority class,
            # then lowest virtual finish tag) instead of the head
            freq = (
                self._pending[0] if self.qos is None
                else self.qos.select(self._pending)
            )
            if freq is None:
                return
            if freq.done:
                # completed while parked (its hedge won after the
                # primary's replica died): already delivered, must
                # not be dispatched — and decoded — a second time
                self._pending.remove(freq)
                continue
            if not self._dispatch_one(freq, loads, digests):
                return
            self._pending.remove(freq)
            if self.qos is not None and not freq.done:
                # done here means _dispatch_one finished it locally
                # (unplaceable error) — that is not a dispatch, so the
                # global virtual clock must not advance for it
                self.qos.on_dispatch(freq.request)

    def _dispatch_one(self, freq, loads, digests=None):
        """Place one pending request; False leaves it queued (no
        routable replica, admission refused, or an injected
        ``fleet.route`` fault — routing failures degrade to a retry on
        the next step, never to a dropped request)."""
        if not loads:
            return False
        target, affinity = self._route_target(freq, loads, digests)
        try:
            faults.fire(
                "fleet.route", request_id=freq.request_id,
                replica=target.name,
            )
        except Exception as e:
            # analysis: allow(broad-except) an injected routing fault
            # exercises exactly this containment: count it, retry later
            self.metrics.route_errors += 1
            _flight.record(
                "fleet", "route-error", fleet=self.fleet_id,
                request_id=freq.request_id,
                error=f"{type(e).__name__}: {e}",
            )
            return False
        with span(
            "fleet.route", request_id=freq.request_id,
            replica=target.name,
        ):
            try:
                placed = self._place(freq, target)
                if not placed and affinity:
                    # the affinity pick refused admission (warm but
                    # full): retry least-loaded before parking — under
                    # plain least-loaded routing a refusal meant
                    # everyone else was fuller, so halting the sweep
                    # was right; an affinity refusal says nothing
                    # about the other candidates
                    fallback = min(
                        loads,
                        key=lambda s: self._route_weight(s, loads),
                    )
                    if fallback is not target:
                        placed = self._place(freq, fallback)
                        if placed:
                            target, affinity = fallback, False
                if not placed:
                    return False  # shed / queue full: stays pending
            except ValueError as e:
                # unplaceable (admission validation raced an engine
                # rebuild with a stricter config): fail THIS request
                # instead of wedging the pending queue behind it
                self._finish_local(
                    freq, "error", error=f"{type(e).__name__}: {e}",
                )
                return True
        if affinity:
            # counted only for PLACEMENTS won by prefix affinity —
            # refusals and faulted routes must not inflate it
            self.metrics.route_prefix_hits += 1
        d = _Dispatch(freq, freq.request, target.name, "primary")
        freq.dispatches.append(d)
        self._routes[freq.request.request_id] = d
        loads[target] += 1
        return True

    def _place(self, freq, sup):
        """Submit (or resume, after a failover) one request on one
        replica. True = placed; False = admission refused (shed /
        queue full — retry elsewhere or next step). ValueError
        propagates: the request itself is unplaceable."""
        try:
            if freq.request.output_token_ids:
                # failed-over mid-generation: KV must be rebuilt
                # over prompt + output[:-1] (recompute preemption)
                sup.engine.resume(freq.request)
            else:
                sup.engine.submit(freq.request)
        except (EngineOverloadedError, RuntimeError):
            return False
        return True

    def _route_weight(self, sup, loads):
        """Capacity-aware routing key, ascending-better, shared by
        every least-loaded pick (:meth:`_route_target`'s fallback and
        tie-breaks, :meth:`_dispatch_one`'s affinity-refusal retry):

        1. tp_degree-normalized load — a tp=4 slice runs each step
           across 4 chips' compute, so at equal raw backlog it is the
           LESS loaded candidate; dividing by width makes
           heterogeneous slices (tp=4 next to tp=2) absorb traffic
           proportionally instead of the narrow replica saturating
           first.
        2. per-chip KV headroom as the tie-break — free + reclaimable
           blocks scaled by the pool's shard degree (a sharded pool
           holds ~1/tp of each block per chip), negated so MORE
           absorbable capacity sorts first.
        """
        eng = sup.engine
        load = loads[sup]
        if eng is None:
            return (float(load), 0.0)
        tp = max(1, getattr(eng.config, "tp_degree", 1))
        shard = max(1, getattr(eng.pool, "shard_degree", 1))
        return (
            load / tp,
            -eng.metrics.kv_headroom_blocks / shard,
        )

    def _route_target(self, freq, loads, digests=None):
        """Hit-aware placement: among the routable candidates
        (``loads``), prefer the replica whose prefix cache already
        holds the longest chain match for this prompt — its shared
        blocks are forked instead of recomputed, which is exactly the
        prefill compute a least-loaded bounce would throw away. Ties
        on match length break on :meth:`_route_weight` (tp-normalized
        load, then per-chip KV headroom); zero matches anywhere falls
        back to the same weighted least-loaded pick. Affinity is
        load-bounded: a match of n blocks only overrides load while
        the warm replica carries fewer than n extra requests over the
        least-loaded candidate — saving n blocks of prefill is not
        worth queueing behind an arbitrarily deep backlog, so a
        saturated replica with a shallow match cannot capture all
        matching traffic. Resume placements (failover) benefit
        identically: the re-prefill over prompt + output[:-1] starts
        with the same prompt digests. ``digests`` carries the
        per-replica digest-set snapshots across one dispatch sweep;
        the prompt's own digests are cached on the FleetRequest
        (hashed once per lifetime, not per parked-retry sweep).
        Returns ``(supervisor, used_affinity)`` — the caller books the
        prefix-hit counter only once the placement actually lands."""
        best, best_len = None, 0
        if digests is None:
            digests = {}
        min_load = min(loads.values())
        for sup in loads:
            eng = sup.engine
            if eng is None or eng.prefix_cache is None:
                continue
            bs = eng.config.page_size
            want = freq.chain_digests(bs)
            if not want:
                continue
            have = digests.get(sup.name)
            if have is None:
                have = digests[sup.name] = set(
                    eng.prefix_cache.chain_digests()
                )
            n = 0
            for d in want:
                if d not in have:
                    break
                n += 1
            if loads[sup] - min_load >= n:
                continue  # too backlogged for what the match saves
            if n > best_len or (
                n == best_len and n > 0
                and self._route_weight(sup, loads)
                < self._route_weight(best, loads)
            ):
                best, best_len = sup, n
        if best is not None and best_len > 0:
            return best, True
        return (
            min(loads, key=lambda s: self._route_weight(s, loads)),
            False,
        )

    def _maybe_hedge(self, now):
        deadline = self.config.hedge_after_s
        for d in list(self._routes.values()):
            freq = d.fleet_req
            if (freq.done or freq.hedged or d.kind != "primary"
                    or d.cancelled or d.finished
                    or now - d.time <= deadline):
                continue
            target = self._pick_replica(exclude={d.replica})
            if target is None:
                continue
            hreq = Request(
                freq.prompt_token_ids, freq.sampling_params,
                request_id=f"{freq.request_id}::hedge",
            )
            # the hedge serves the SAME client request: anchor its
            # timeline (and TTL deadline) at the primary's arrival so
            # a hedge win reports the latency the client actually saw
            # — including the stall that triggered the hedge — instead
            # of restarting the clock at hedge dispatch (the aborted
            # primary is excluded from the digests, so the winner's
            # sample is the only record of this request's tail)
            hreq.arrival_time = freq.request.arrival_time
            hreq.timeline.arrival = hreq.arrival_time
            hreq.deadline = freq.request.deadline
            with span(
                "fleet.hedge", request_id=freq.request_id,
                replica=target.name,
            ):
                try:
                    target.engine.submit(hreq)
                except (EngineOverloadedError, RuntimeError):
                    continue  # no capacity for a hedge right now
            freq.hedged = True
            hd = _Dispatch(freq, hreq, target.name, "hedge")
            freq.dispatches.append(hd)
            self._routes[hreq.request_id] = hd
            self.metrics.hedges_started += 1
            _flight.record(
                "fleet", "hedge", fleet=self.fleet_id,
                request_id=freq.request_id, replica=target.name,
            )

    def _collect(self, out):
        d = self._routes.pop(out.request_id, None)
        if d is None:
            return  # not fleet-managed
        d.finished = True
        freq = d.fleet_req
        if freq.done or d.cancelled:
            return  # hedge loser / abort echo; resolution already done
        freq.done = True
        # hedge winners carry the engine-side "<id>::hedge" id; clients
        # see their own id regardless of which dispatch won
        out.request_id = freq.request_id
        freq.output = out
        if self.qos is not None:
            self.qos.on_finish(freq.request)
        if self.journal is not None:
            # the journal is keyed by the PRIMARY rid; a hedge winner
            # closes it with the winning reason (the primary's partial
            # tokens are irrelevant once the request is finished)
            self.journal.finish(freq.request, out.finish_reason)
        if freq.hedged:
            if d.kind == "hedge":
                self.metrics.hedges_won += 1
            else:
                self.metrics.hedges_lost += 1
        self.metrics.requests_finished += 1
        for other in freq.dispatches:
            if other is d or other.finished or other.cancelled:
                continue
            other.cancelled = True
            sup = self._sup_or_none(other.replica)
            if sup is not None and sup.engine is not None:
                sup.engine.abort(other.request.request_id)
        self._ready.append(out)

    # -- failover ------------------------------------------------------------
    def _on_replica_death(self, sup, exc):
        """Quarantine a dead replica, re-enqueue its in-flight work on
        healthy replicas (deterministic re-prefill), leave a
        postmortem, and start the background restart."""
        detect = time.perf_counter()
        m = self.metrics
        m.failovers += 1
        m.last_failover_detect_s = detect
        m.last_recovered_token_s = None
        engine = sup.engine
        error = f"{type(exc).__name__}: {exc}"
        _flight.record(
            "fleet", "replica-death", fleet=self.fleet_id,
            replica=sup.name, error=error,
        )
        try:
            probe = engine.health()
        except Exception as he:
            # analysis: allow(broad-except) the engine is torn by
            # definition here; the postmortem records that instead
            probe = {"error": f"health() failed: {he!r}"}
        self._absorb_latency(sup)  # folds digests, drops engine
        sup.quarantine(exc)
        with span("fleet.failover", replica=sup.name, error=error):
            # slot requests resume via appendleft on the survivor, so
            # process them YOUNGEST-first: the chain of appendlefts
            # leaves the oldest work at the head of its new queue.
            # The dead replica's local waiting queue follows in its
            # own (oldest-first) order — those re-place via tail
            # submit, which preserves processing order.
            inflight = sorted(
                (r for r in engine.slots if r is not None),
                key=lambda r: r.admit_seq, reverse=True,
            ) + list(engine.waiting)
            # requests the dying engine had already detached from its
            # scheduler — aborted between steps (``engine._aborted``)
            # or finished during the fatal step itself — still hold
            # live dispatch records; deliver their completions now so
            # no generate()/drain() waiter hangs on a dead route
            for d in list(self._routes.values()):
                if d.replica != sup.name:
                    continue
                req = d.request
                if req.state is RequestState.FINISHED:
                    self._collect(RequestOutput(req))
                elif req not in inflight:
                    inflight.append(req)  # limbo: fail it over too
            for req in inflight:
                d = self._routes.pop(req.request_id, None)
                if d is None or d.fleet_req.done:
                    continue
                freq = d.fleet_req
                if d.cancelled:
                    continue  # an already-aborted hedge loser died with it
                if d.kind == "hedge":
                    # the hedge died, the primary is still running:
                    # drop the hedge rather than failing it over
                    # (resolution is counted at the primary's win)
                    d.finished = True
                    continue
                self._recovering.append(
                    (req, len(req.output_token_ids))
                )
                m.failover_requests += 1
                _flight.record(
                    "fleet", "failover", fleet=self.fleet_id,
                    replica=sup.name, request_id=freq.request_id,
                    tokens_kept=len(req.output_token_ids),
                )
                self._pending.append(freq)
                # drop the dead dispatch record; _dispatch_pending
                # re-places the request (resume path: tokens kept)
                freq.dispatches.remove(d)
        _flight.dump(
            f"replica-death:{sup.name}",
            probes={
                f"serving.replica.{sup.name}": probe,
                f"serving.fleet.{self.fleet_id}": self.snapshot(),
            },
        )
        if sup.start_restart():
            _flight.record(
                "fleet", "restart-started", fleet=self.fleet_id,
                replica=sup.name, attempt=sup.restarts,
            )
        else:
            m.replicas_failed += 1
            _flight.record(
                "fleet", "replica-failed", fleet=self.fleet_id,
                replica=sup.name, error="restart budget exhausted",
            )
        self._dispatch_pending()
