"""Durable request journal: a per-fleet write-ahead log that makes
serving requests survive whole-process death.

The fleet already survives *replica* death (failover re-prefills
in-flight requests on a survivor, bit-identical greedy continuation) —
but an OOM kill, node preemption, or ``kill -9`` of the process lost
every queued and in-flight request. This module closes that gap with a
classic WAL, applied to serving state:

  * **Records** — crc32-framed, JSON-payload, append-only:

        ADMIT  (``"A"``)  request id, prompt token ids, the full
                          SamplingParams (incl. the per-request
                          ``seed``), wall-clock arrival, and the emit
                          cursor (tokens already produced — nonzero
                          only for re-admissions after a recovery)
        EMIT   (``"E"``)  tokens appended since the last flush,
                          batched per step across every live request
                          (one record per scheduler step in the
                          common case)
        FINISH (``"F"``)  terminal reason (length/stop/eos/timeout/
                          error)
        ABORT  (``"X"``)  client abort (a FINISH with
                          reason="aborted")

    Framing is ``<u32 length><u32 crc32(payload)><payload>``: a
    crc-damaged record with an intact length is *skipped* (warn +
    counter), a record whose frame cannot be parsed truncates the
    segment there (torn tail — the crash left a partial write).

  * **Segments** — ``wal-<n>.seg`` files under the journal directory.
    Appends are buffered and written with ONE ``write()`` per batch
    (SIGKILL-consistent: the kernel owns the bytes once the write
    returns). Batches carrying ADMIT/FINISH/ABORT — the records that
    decide delivery — write at the step they were buffered; pure-EMIT
    batches may group across steps for up to ``write_interval_s``
    (a lost EMIT is always re-derived by the replay recompute).
    ``fsync`` is grouped on its own interval (power-loss window =
    ``fsync_interval_s``) and always taken on rotation and close.
    Every process incarnation opens a FRESH segment (headered with
    the journal generation + engine seed), so a torn tail can only
    ever sit at the end of a dead incarnation's last segment.

  * **Compaction** — a segment whose every touched request has
    finished is deleted. Recovery re-ADMITs unfinished requests into
    the live segment (cursor carried), which is what lets the dead
    incarnation's segments retire as soon as the recovered work
    drains.

  * **Replay** — ``replay()`` walks every segment in order and folds
    records into per-request entries (latest ADMIT wins, EMITs extend
    its cursor, FINISH/ABORT closes). The engine/fleet re-admits the
    unfinished entries at the HEAD of its queue through the existing
    ``resume()`` re-prefill contract (``prompt + output[:-1]``), so
    greedy continuations are byte-identical to an uninterrupted run
    and no already-emitted token is ever produced twice. Requests
    whose ``ttl_s`` lapsed while the process was down are finished
    with ``"timeout"`` instead of re-admitted (deadline-aware
    recovery).

Failure policy: durability must never take down serving. Every append,
flush, rotation, and replay failure — including the injected
``journal.append`` / ``journal.replay`` faults — degrades to a warning
plus ``paddle_tpu_serving_journal_*`` counters; the engine keeps
stepping with a lossy (or absent) journal rather than going fatal.

Single-writer contract: one live process per journal directory. A
recovering process may open the directory only after the previous
incarnation is dead (the replay torn-tail truncation rewrites the dead
incarnation's last segment in place).
"""
from __future__ import annotations

import itertools
import json
import os
import re
import struct
import time
import warnings
import weakref
import zlib

from ..distributed.checkpoint import _fsync_dir as _ckpt_fsync_dir
from ..resilience import faults

__all__ = ["Journal", "ReplayEntry", "resolve_journal"]

_FRAME = struct.Struct("<II")      # payload length, crc32(payload)
_MAX_RECORD = 1 << 26              # frame-length sanity cap (64 MiB)
_SEG_RE = re.compile(r"^wal-(\d{8})\.seg$")

# monotonic journal ids for the collector-view label (same rationale
# as the engine counter: labels must never alias across lifetimes)
_journal_counter = itertools.count(1)

# counter attribute -> exported series (all under the namespace the
# acceptance contract names: paddle_tpu_serving_journal_*). The
# counters are plain attributes bumped inline — the flush path is per
# scheduler step, so it pays ZERO registry cost; the registry PULLS
# at scrape time through a weakref collector view (the EngineMetrics
# pattern).
_JOURNAL_COUNTERS = {
    "records_written": "paddle_tpu_serving_journal_records_total",
    "writes": "paddle_tpu_serving_journal_writes_total",
    "bytes_written": "paddle_tpu_serving_journal_bytes_total",
    "append_errors": "paddle_tpu_serving_journal_append_errors_total",
    "replays": "paddle_tpu_serving_journal_replays_total",
    "replayed_requests":
        "paddle_tpu_serving_journal_replayed_requests_total",
    "corrupt_records":
        "paddle_tpu_serving_journal_corrupt_records_total",
    "torn_tails": "paddle_tpu_serving_journal_torn_tails_total",
    "compacted_segments":
        "paddle_tpu_serving_journal_compacted_segments_total",
    "replay_errors": "paddle_tpu_serving_journal_replay_errors_total",
    "seed_mismatches":
        "paddle_tpu_serving_journal_seed_mismatches_total",
}


def _register_view(journal, journal_id):
    """Pull-time collector over one journal (weakref: a collected
    journal's view unregisters itself). Best-effort: a metrics
    failure must never become a journal failure."""
    try:
        from ..observability import MetricFamily, get_registry
    except Exception:
        # analysis: allow(broad-except) observability is optional here
        return
    ref = weakref.ref(journal)
    label = {"journal": journal_id}

    def collect():
        j = ref()
        if j is None:
            return None
        return [
            MetricFamily(series, "counter").add(getattr(j, attr), label)
            for attr, series in _JOURNAL_COUNTERS.items()
        ]

    try:
        get_registry().register_collector(
            f"serving.journal.{journal_id}", collect
        )
    except Exception:
        # analysis: allow(broad-except) telemetry is best-effort
        pass


def _flight_record(name, **data):
    try:
        from ..observability import flight

        flight.record("journal", name, **data)
    except Exception:
        # analysis: allow(broad-except) flight telemetry is best-effort
        pass


def _fsync_dir(path):
    """checkpoint v2's directory fsync, with the open() tolerated too:
    the journal treats an unfsyncable dir as best-effort (the append
    path degrades on its own terms)."""
    try:
        _ckpt_fsync_dir(path)
    except OSError:
        pass


class ReplayEntry:
    """One unfinished request recovered from the journal."""

    __slots__ = ("rid", "prompt", "params", "out", "ts", "tenant", "kv")

    def __init__(self, rid, prompt, params, out, ts, tenant=None,
                 kv=None):
        self.rid = rid          # request id (int or str, as journaled)
        self.prompt = prompt    # prompt token ids
        self.params = params    # SamplingParams dict (to_dict form)
        self.out = out          # tokens already emitted (the cursor)
        self.ts = ts            # wall-clock admission time (time.time)
        self.tenant = tenant    # QoS tenant id (None pre-QoS journals)
        self.kv = kv            # [spill_key, spill_tokens] handle into
        #                         the host spill tier, or None (pre-
        #                         spill journals / never-spilled)

    def __repr__(self):
        return (
            f"ReplayEntry(rid={self.rid!r}, prompt={len(self.prompt)} "
            f"tok, out={len(self.out)} tok)"
        )


def resolve_journal(journal, seed=None):
    """``EngineConfig(journal=)`` / ``FleetConfig(journal_dir=)``
    accept a directory path or a pre-built :class:`Journal`."""
    if isinstance(journal, Journal):
        return journal
    return Journal(str(journal), seed=seed)


def restore_entries(journal, entries, build):
    """The shared replay fold behind Engine/Fleet recovery: for each
    unfinished :class:`ReplayEntry`, reconstruct the request via
    ``build(entry, params)`` (returning a Request, or any object
    carrying one as ``.request``), restore its emitted tokens, and
    re-anchor its TTL deadline at the journaled wall-clock arrival.
    Entries whose TTL lapsed while the process was down are retired in
    the journal as ``"timeout"`` instead of rebuilt; entries that
    cannot be reconstructed (a field a crc-valid but semantically
    damaged record lost) are dropped with a warning and retired as
    ``"error"`` — recovery must never be fatal. Returns
    ``(live_objects, expired_count)``; the caller queues the live
    objects, re-journals their ADMITs, and flushes."""
    from .request import SamplingParams

    now = time.time()
    live, expired = [], 0
    for e in entries:
        try:
            params = SamplingParams.from_dict(e.params)
            remaining = None
            if params.ttl_s is not None and e.ts is not None:
                remaining = e.ts + params.ttl_s - now
                if remaining <= 0:
                    expired += 1
                    journal.finish_rid(e.rid, "timeout")
                    continue
            obj = build(e, params)
            req = getattr(obj, "request", obj)
            req.output_token_ids = list(e.out)
            if getattr(e, "tenant", None) is not None:
                req.tenant = e.tenant
            kv = getattr(e, "kv", None)
            if kv:
                # re-anchor the host-spill handle: if the tier (or its
                # disk third level) still holds the key, re-admission
                # restores the KV instead of re-prefilling it
                req.spill_key = kv[0]
                req.spill_tokens = int(kv[1])
            if e.ts is not None:
                # timeline coherence: anchor arrival at the journaled
                # wall-clock admission (the same field the TTL math
                # uses), mapped into this incarnation's perf_counter
                # domain. Without this a recovered request's TTFT/e2e
                # would be measured from the RESTART — the post-crash
                # latency digests would report impossibly fast
                # recoveries instead of the downtime the client saw.
                age = max(0.0, now - e.ts)
                req.arrival_time = time.perf_counter() - age
                req.timeline.arrival = req.arrival_time
                req.timeline.recovered = True
            if remaining is not None:
                # anchored at the ORIGINAL admission, not the restart
                # (perf_counter does not survive the process)
                req.deadline = time.perf_counter() + remaining
        except Exception as exc:
            # analysis: allow(broad-except) the degradation contract:
            # one unreconstructable entry must not keep the engine or
            # fleet from serving the rest
            warnings.warn(
                f"[journal] dropping unreplayable request {e.rid!r}: "
                f"{type(exc).__name__}: {exc}",
                stacklevel=2,
            )
            journal.finish_rid(e.rid, "error")
            continue
        live.append(obj)
    return live, expired


class Journal:
    """Append-only crc-framed request WAL over segment files.

    The writer API mirrors the request lifecycle — :meth:`admit`,
    :meth:`emit`, :meth:`finish` buffer records; :meth:`flush` writes
    the step's batch with one ``write()``. :meth:`replay` must run
    before the first append of a new incarnation (engine/fleet call it
    before accepting traffic)."""

    def __init__(self, path, segment_bytes=1 << 20, fsync_interval_s=0.25,
                 write_interval_s=0.02, seed=None):
        if segment_bytes < 1:
            raise ValueError(
                f"segment_bytes must be >= 1, got {segment_bytes}"
            )
        self.path = str(path)
        os.makedirs(self.path, exist_ok=True)
        self.segment_bytes = int(segment_bytes)
        # None: fsync only on rotate/close; 0: every write; >0: at most
        # once per interval (group commit — the power-loss window)
        self.fsync_interval_s = fsync_interval_s
        # pure-EMIT buffers may batch across steps for up to this long
        # before the write() syscall (0 writes every flush). Safe by
        # construction: a lost EMIT is re-derived by the replay
        # recompute (greedy byte-identical) — only ADMIT/FINISH/ABORT
        # decide delivery, and those always write immediately. This is
        # what keeps the per-step cost inside the <3% overhead bar.
        self.write_interval_s = float(write_interval_s)
        self.seed = seed
        self.generation = 1           # prior incarnations + 1 (replay)
        self._buffer: list = []       # record dicts pending write
        self._urgent = False          # buffer holds ADMIT/FINISH/ABORT
        self._open: set = set()      # admitted-not-finished rids
        self._touched: dict = {}      # segment name -> set of rids
        self._finished_since_compact = False
        self._seg_file = None
        self._seg_name = None
        self._seg_size = 0
        self._last_fsync = 0.0
        self._last_write = 0.0
        self._replayed = False
        self._append_warned = False
        self._epoch = 0               # replica-epoch counter (R records)
        self.replay_report = None
        # counters (plain attributes; exported by the collector view)
        self.records_written = 0
        self.writes = 0
        self.bytes_written = 0
        self.append_errors = 0
        self.replays = 0
        self.replayed_requests = 0
        self.corrupt_records = 0
        self.torn_tails = 0
        self.compacted_segments = 0
        self.replay_errors = 0
        self.seed_mismatches = 0
        _register_view(self, f"{next(_journal_counter)}")

    # -- introspection -------------------------------------------------------
    def segments(self):
        """Segment file names on disk, oldest first."""
        try:
            names = os.listdir(self.path)
        except OSError:
            return []
        return sorted(n for n in names if _SEG_RE.match(n))

    def open_requests(self):
        """Rids admitted but not finished (snapshot)."""
        return set(self._open)

    # -- framing -------------------------------------------------------------
    @staticmethod
    def _frame(record):
        payload = json.dumps(record, separators=(",", ":")).encode()
        return _FRAME.pack(
            len(payload), zlib.crc32(payload) & 0xFFFFFFFF
        ) + payload

    # -- writer API ----------------------------------------------------------
    def admit(self, req):
        """Buffer an ADMIT for ``req`` (a serving Request). Re-admits
        (failover / recovery) carry the emit cursor — the tokens
        already produced — so replay never double-counts them."""
        rid = req.request_id
        out = list(req.output_token_ids)
        rec = {
            "t": "A", "rid": rid, "p": list(req.prompt_token_ids),
            "sp": req.sampling_params.to_dict(), "out": out,
            "ts": time.time(),
        }
        # tenant attribution rides the ADMIT so a replay restores the
        # QoS accounting (quota/fair-share charges the right tenant)
        tenant = getattr(req, "tenant", None)
        if tenant is not None:
            rec["tn"] = tenant
        # host-spill handle rides the ADMIT too: a re-admit after
        # preempt/release journals [key, tokens] so a crash re-anchors
        # the restore-instead-of-recompute path (latest ADMIT wins, so
        # a consumed handle is naturally cleared by the next re-admit)
        kv_key = getattr(req, "spill_key", None)
        if kv_key is not None:
            rec["kv"] = [kv_key, int(getattr(req, "spill_tokens", 0))]
        self._buffer.append(rec)
        self._urgent = True   # admissions are durable before dispatch
        self._open.add(_key(rid))
        req.journal_cursor = len(out)

    def emit(self, req):
        """Buffer the tokens ``req`` gained since its last emit.
        Consecutive emits merge into ONE batched EMIT record — the
        per-step flush writes a single record for the whole batch.
        This is THE hot call (once per live slot per step): nothing
        here touches the registry, the clock, or the filesystem."""
        out = req.output_token_ids
        cursor = req.journal_cursor
        if len(out) <= cursor:
            return
        toks = out[cursor:]
        req.journal_cursor = len(out)
        buf = self._buffer
        if buf and buf[-1]["t"] == "E":
            buf[-1]["e"].append([req.request_id, toks])
        else:
            buf.append({"t": "E", "e": [[req.request_id, toks]]})

    def step_flush(self, reqs):
        """The per-step hook: called once per scheduler step with the
        live requests. When nothing urgent is buffered and the write
        interval has not elapsed, this is a two-comparison no-op —
        emit cursors are not even advanced; the new tokens simply stay
        on the Request objects until write time. Otherwise the live
        requests' new tokens are swept into one batched EMIT record
        and the whole buffer is written. This keeps the steady-state
        per-step journal cost at ~nothing, which is what holds the
        mixed-workload overhead under the 3% bar."""
        if (not self._urgent
                and time.monotonic() - self._last_write
                < self.write_interval_s):
            return 0
        for r in reqs:
            if r is not None:
                self.emit(r)
        return self.flush(force=True)

    def finish(self, req, reason=None):
        """Buffer the request's trailing emits plus its terminal
        record (ABORT for client aborts, FINISH otherwise)."""
        self.emit(req)
        reason = reason or req.finish_reason
        self.finish_rid(req.request_id, reason)

    def finish_rid(self, rid, reason):
        """Terminal record by rid alone — recovery uses this to retire
        a journaled request that expired while the process was down
        (there is no live Request to hand to :meth:`finish`).

        NOTE: the rid stays in ``_open`` until the write carrying this
        record SUCCEEDS (see flush) — compaction eligibility must
        follow durability, not buffering, or a crash between deleting
        the ADMIT-holding segment and writing the FINISH would lose
        the request entirely (neither delivered nor replayable)."""
        if reason == "aborted":
            self._buffer.append({"t": "X", "rid": rid})
        else:
            self._buffer.append({"t": "F", "rid": rid, "r": reason})
        self._urgent = True   # completions are durable before delivery

    def epoch(self, op, replica=None):
        """Buffer a replica-epoch record — the fleet brackets every
        scaling action with these (``"shrink-begin"``/``"shrink-end"``
        around a migration, one ``"scale-up"`` per spawn) so a replay
        can tell a COMPLETED scaling op from one a crash interrupted.
        Epochs are advisory markers, not the delivery contract: a
        replayed mid-shrink crash is already exactly-once through
        latest-ADMIT-wins (the migration re-ADMITs carry the emit
        cursor), and epoch records make the interruption *observable*
        (``replay_report["interrupted_ops"]``) so the fleet can
        flight-record it. ``*-begin`` ops unclosed by a later
        ``*-end`` for the same replica are reported as interrupted;
        epoch numbering resumes past the journal's max after replay.
        Urgent like every lifecycle record; returns the epoch number.
        """
        self._epoch += 1
        rec = {"t": "R", "ep": self._epoch, "op": str(op),
               "ts": time.time()}
        if replica is not None:
            rec["rep"] = str(replica)
        self._buffer.append(rec)
        self._urgent = True
        return self._epoch

    def flush(self, force=False):
        """Write the buffered records (one ``write()``), group-fsync by
        interval, rotate + compact when due. Returns bytes written.

        Pure-EMIT buffers (no admission, no completion) may wait up to
        ``write_interval_s`` before the syscall: a crash in that window
        loses only tokens the replay recompute re-derives
        byte-identically. Buffers carrying ADMIT/FINISH/ABORT — the
        records that decide delivery — always write immediately.

        NEVER raises: any failure — including an injected
        ``journal.append`` fault — degrades to a warning + counters,
        and the buffered records are dropped (a lossy journal, warned
        once and counted; serving keeps going)."""
        if not self._buffer:
            return 0
        now = time.monotonic()
        if (not force and not self._urgent
                and now - self._last_write < self.write_interval_s):
            return 0
        records, self._buffer = self._buffer, []
        self._urgent = False
        try:
            faults.fire(
                "journal.append", path=self.path, records=len(records),
            )
            if self._seg_file is None:
                self._open_segment()
            data = b"".join(self._frame(r) for r in records)
            if (self._seg_size and
                    self._seg_size + len(data) > self.segment_bytes):
                self._rotate()
            # touched is updated BEFORE the write: a superset only ever
            # makes compaction more conservative, never unsafe
            touched = self._touched[self._seg_name]
            for r in records:
                touched.update(_record_rids(r))
            self._seg_file.write(data)   # unbuffered: ONE syscall
            self._seg_size += len(data)
            self._last_write = now
            # terminal records are ON DISK now: only at this point may
            # their requests stop protecting the segments that hold
            # their history (a dropped batch — the except below — must
            # leave them open, so compaction stays conservative)
            for r in records:
                if r["t"] in ("F", "X"):
                    self._open.discard(_key(r["rid"]))
                    self._finished_since_compact = True
            if self.fsync_interval_s is not None and (
                self.fsync_interval_s <= 0
                or now - self._last_fsync >= self.fsync_interval_s
            ):
                os.fsync(self._seg_file.fileno())
                self._last_fsync = now
            self.records_written += len(records)
            self.bytes_written += len(data)
            self.writes += 1
            if self._finished_since_compact and len(self._touched) > 1:
                # only when retired segments can actually exist — the
                # steady single-segment state pays nothing here
                self._finished_since_compact = False
                self._compact()
            return len(data)
        except Exception as e:
            # analysis: allow(broad-except) the degradation contract:
            # serving never goes fatal because durability did
            self.append_errors += 1
            _flight_record(
                "append-error", path=self.path,
                error=f"{type(e).__name__}: {e}",
                records=len(records),
            )
            if not self._append_warned:
                self._append_warned = True
                warnings.warn(
                    f"[journal] append to {self.path} failed "
                    f"({type(e).__name__}: {e}); {len(records)} "
                    "record(s) dropped — serving continues with a "
                    "lossy journal (further append failures are "
                    "counted, not warned)",
                    stacklevel=2,
                )
            return 0

    def close(self):
        """Flush, fsync, and close the live segment (clean shutdown;
        deliberately NOT called from any destructor — a crash must
        look like a crash)."""
        self.flush(force=True)
        if self._seg_file is not None:
            try:
                os.fsync(self._seg_file.fileno())
                self._seg_file.close()
            except OSError as e:
                self.append_errors += 1
                warnings.warn(
                    f"[journal] close of {self._seg_name} failed: {e}",
                    stacklevel=2,
                )
            self._seg_file = None

    # -- segments ------------------------------------------------------------
    def _open_segment(self):
        segs = self.segments()
        nxt = 1 + (
            int(_SEG_RE.match(segs[-1]).group(1)) if segs else 0
        )
        name = f"wal-{nxt:08d}.seg"
        path = os.path.join(self.path, name)
        # unbuffered: flush() writes ONE pre-joined byte string per
        # step batch, so the BufferedWriter layer is pure overhead
        self._seg_file = open(path, "ab", buffering=0)
        self._seg_name = name
        self._seg_size = 0
        self._touched.setdefault(name, set())
        header = self._frame({
            "t": "H", "v": 1, "gen": self.generation,
            "seed": self.seed,
        })
        self._seg_file.write(header)
        os.fsync(self._seg_file.fileno())
        self._seg_size += len(header)
        self._last_fsync = time.monotonic()
        _fsync_dir(self.path)

    def _rotate(self):
        """Close the live segment and start the next (fsync'd on both
        sides so the boundary is never torn), then try compaction."""
        os.fsync(self._seg_file.fileno())
        self._seg_file.close()
        self._open_segment()
        self._compact()

    def _compact(self):
        """Delete every non-live segment none of whose touched
        requests is still open. A segment replay never saw (no touched
        entry) is kept — unknown means not provably retired."""
        removed = 0
        for name in self.segments():
            if name == self._seg_name:
                continue
            touched = self._touched.get(name)
            if touched is None or touched & self._open:
                continue
            try:
                os.remove(os.path.join(self.path, name))
            except OSError:
                continue  # unremovable segments retry next compaction
            self._touched.pop(name, None)
            removed += 1
        if removed:
            self.compacted_segments += removed
            _fsync_dir(self.path)
        return removed

    # -- replay --------------------------------------------------------------
    def replay(self):
        """Fold every on-disk segment into per-request entries and
        return the UNFINISHED ones in admission order (the caller
        re-admits them at its queue head). Idempotent per instance:
        a second call returns ``[]`` — and across instances, the
        re-ADMIT records the caller writes (latest-ADMIT-wins keying)
        make a replay-of-a-replay admit nothing twice.

        Never raises: corrupt records are skipped, torn tails
        truncated, and a replay-level failure (injected
        ``journal.replay`` fault, unreadable directory) degrades to a
        warning + counter and an empty recovery."""
        if self._replayed:
            return []
        self._replayed = True
        try:
            return self._replay()
        except Exception as e:
            # analysis: allow(broad-except) the degradation contract:
            # a broken journal must not stop the engine from serving
            self.replay_errors += 1
            _flight_record(
                "replay-error", path=self.path,
                error=f"{type(e).__name__}: {e}",
            )
            warnings.warn(
                f"[journal] replay of {self.path} failed "
                f"({type(e).__name__}: {e}); recovering nothing",
                stacklevel=2,
            )
            self.replay_report = {"error": f"{type(e).__name__}: {e}"}
            return []

    def _replay(self):
        faults.fire("journal.replay", path=self.path)
        self.replays += 1
        entries: dict = {}
        order: dict = {}
        seq = 0
        generations = 0
        corrupt = torn = nrecords = 0
        seeds = []
        epoch_max = 0
        open_ops: dict = {}   # (op base, replica) -> epoch of begin
        for name in self.segments():
            spath = os.path.join(self.path, name)
            touched = self._touched.setdefault(name, set())
            with open(spath, "rb") as f:
                data = f.read()
            off = 0
            while off < len(data):
                if off + _FRAME.size > len(data):
                    torn += 1
                    self._truncate(spath, name, off, len(data))
                    break
                ln, crc = _FRAME.unpack_from(data, off)
                end = off + _FRAME.size + ln
                if ln > _MAX_RECORD or end > len(data):
                    # unparseable frame: everything from here is a
                    # partial write — the torn tail
                    torn += 1
                    self._truncate(spath, name, off, len(data))
                    break
                payload = data[off + _FRAME.size: end]
                off = end
                if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                    corrupt += 1
                    continue  # framed but damaged: skip this record
                try:
                    rec = json.loads(payload)
                except ValueError:
                    corrupt += 1
                    continue
                nrecords += 1
                t = rec.get("t")
                if t == "H":
                    generations += 1
                    seeds.append(rec.get("seed"))
                    continue
                rids = _record_rids(rec)
                touched.update(rids)
                if t == "A":
                    k = _key(rec["rid"])
                    entries[k] = {
                        "rid": rec["rid"], "p": rec.get("p", []),
                        "sp": rec.get("sp", {}),
                        "out": list(rec.get("out", [])),
                        "ts": rec.get("ts"), "tn": rec.get("tn"),
                        "kv": rec.get("kv"), "fin": False,
                    }
                    order.setdefault(k, seq)
                    seq += 1
                elif t == "E":
                    for rid, toks in rec.get("e", []):
                        ent = entries.get(_key(rid))
                        if ent is not None and not ent["fin"]:
                            ent["out"].extend(toks)
                elif t in ("F", "X"):
                    ent = entries.get(_key(rec["rid"]))
                    if ent is not None:
                        ent["fin"] = True
                elif t == "R":
                    epoch_max = max(epoch_max, rec.get("ep", 0))
                    op = rec.get("op", "")
                    rep = rec.get("rep")
                    if op.endswith("-begin"):
                        open_ops[(op[:-6], rep)] = rec.get("ep", 0)
                    elif op.endswith("-end"):
                        open_ops.pop((op[:-4], rep), None)
        self.generation = generations + 1
        if self.seed is not None and any(
            s is not None and s != self.seed for s in seeds
        ):
            self.seed_mismatches += 1
            warnings.warn(
                f"[journal] {self.path} was written under a different "
                f"engine seed ({[s for s in seeds if s is not None]} "
                f"vs {self.seed}): greedy replay is unaffected, but "
                "sampled continuations will draw a different key "
                "stream",
                stacklevel=2,
            )
        self._open = {
            k for k, ent in entries.items() if not ent["fin"]
        }
        unfinished = sorted(self._open, key=order.get)
        result = [
            ReplayEntry(
                entries[k]["rid"], entries[k]["p"], entries[k]["sp"],
                entries[k]["out"], entries[k]["ts"], entries[k]["tn"],
                entries[k]["kv"],
            )
            for k in unfinished
        ]
        _advance_request_counter(
            ent["rid"] for ent in entries.values()
        )
        if corrupt:
            self.corrupt_records += corrupt
            warnings.warn(
                f"[journal] {self.path}: skipped {corrupt} corrupt "
                "record(s) during replay",
                stacklevel=2,
            )
        if torn:
            self.torn_tails += torn
        self.replayed_requests += len(result)
        # epoch numbering resumes past the dead incarnation's max, and
        # any *-begin its crash left unclosed is surfaced so the fleet
        # flight-records the interrupted scaling op (delivery itself
        # is already exactly-once via latest-ADMIT-wins)
        self._epoch = max(self._epoch, epoch_max)
        interrupted = sorted(
            f"{op}@{rep}" if rep is not None else op
            for op, rep in open_ops
        )
        self.replay_report = {
            "segments": len(self.segments()), "records": nrecords,
            "corrupt": corrupt, "torn": torn,
            "finished": sum(e["fin"] for e in entries.values()),
            "unfinished": len(result), "generation": self.generation,
            "epochs": epoch_max, "interrupted_ops": interrupted,
        }
        _flight_record("replay", path=self.path, **self.replay_report)
        # recovery appends go to a fresh headered segment: the dead
        # incarnation's files are never appended to again, so a torn
        # tail can only ever be the one replay just truncated. A
        # WRITER failure here (read-only dir, disk full) must not
        # throw away the recovery that just succeeded — the entries
        # are returned regardless and the append path degrades on its
        # own terms (flush retries _open_segment and warns + counts).
        try:
            self._open_segment()
            self._compact()
        except Exception as e:
            # analysis: allow(broad-except) the degradation contract:
            # a parse-clean recovery must survive an unwritable dir
            self.append_errors += 1
            warnings.warn(
                f"[journal] could not open a recovery segment in "
                f"{self.path} ({type(e).__name__}: {e}); recovered "
                f"{len(result)} request(s) anyway — the journal is "
                "lossy until the directory becomes writable",
                stacklevel=3,
            )
        return result

    def _truncate(self, spath, name, good, total):
        """Cut a segment back to its last whole record (the crash's
        partial write is unrecoverable by construction)."""
        warnings.warn(
            f"[journal] {name}: torn tail truncated at byte {good} "
            f"(dropping {total - good} partial byte(s))",
            stacklevel=3,
        )
        try:
            with open(spath, "r+b") as f:
                f.truncate(good)
                f.flush()
                os.fsync(f.fileno())
        except OSError as e:
            # unwritable journal dir: replay still proceeds off the
            # in-memory parse; the tail will be re-truncated next boot
            warnings.warn(
                f"[journal] could not truncate {name}: {e}",
                stacklevel=3,
            )


def _key(rid):
    """Journal-side request key: rids may be ints (engine default) or
    strings (fleet); JSON round-trips both faithfully, and keys must
    compare the same way on both sides of a crash."""
    return rid if isinstance(rid, str) else int(rid)


def _record_rids(rec):
    t = rec.get("t")
    if t == "E":
        return {_key(rid) for rid, _ in rec.get("e", [])}
    if t in ("A", "F", "X"):
        return {_key(rec["rid"])}
    return set()


def _advance_request_counter(rids):
    """A fresh process restarts the module-level Request id counter at
    zero; replayed numeric rids would collide with new admissions
    (same id on two live requests breaks every rid-keyed map). Advance
    the shared counter past everything the journal has seen."""
    numeric = [r for r in rids if isinstance(r, int)]
    if not numeric:
        return
    from . import request as _request_mod

    current = next(_request_mod._request_counter)
    _request_mod._request_counter = itertools.count(
        max(current, max(numeric) + 1)
    )
