"""Streaming HTTP front door for the serving stack.

An OpenAI-style ``POST /v1/completions`` endpoint over a ``Fleet`` or
single ``Engine`` — the wire boundary above every capability the
serving stack has accumulated (continuous batching, prefix caching,
journaled recovery, SLO burn), built on the stdlib
``ThreadingHTTPServer`` the observability scrape endpoint already
uses (``ThreadedHTTPHost``; no framework dependency):

  * **Streaming** — ``"stream": true`` responds as Server-Sent
    Events: one ``data: {...}`` chunk per token batch, riding the
    per-token emit path (the handler watches
    ``Request.output_token_ids`` grow past its cursor — the journal
    EMIT-cursor idiom at the wire), a final chunk carrying
    ``finish_reason`` + usage, then ``data: [DONE]``. Greedy streams
    are byte-identical to in-process ``generate()`` output.
  * **Non-streaming** — one JSON completion body at finish.
  * **Validation** — malformed requests answer structured 4xx JSON
    (``{"error": {"type", "message", "param"}}``), never a stack
    trace; the offending field is named when known.
  * **Multi-tenant QoS** (``serving/qos.py``) — tenant identity from
    ``Authorization: Bearer``/``X-Tenant``, quota / token-rate /
    sustained-burn shedding as 429 + ``Retry-After``, weighted
    fair-share dispatch over the fleet pending queue, per-tenant
    latency/SLO series on the co-hosted ``/metrics``.
  * **Co-hosting** — ``GET /metrics`` + ``GET /healthz`` answer on
    the same port (the scrape thread stays available standalone).
  * **Degradation** — the fault sites ``http.accept`` (request
    accept) and ``http.stream`` (per-chunk stream write) plus client
    disconnects degrade to a counted, warn-once abort of THAT request;
    nothing at the HTTP layer is ever fatal to the engine.
  * **Drain** — SIGTERM (or :meth:`Server.drain`) stops admitting
    (503 ``server_draining``), lets in-flight streams finish, then
    closes the listener.

One stepping thread drives the backend; handler threads only submit,
watch token growth, and write bytes — the engine never runs on a
client's thread.
"""
from __future__ import annotations

import itertools
import json
import math
import threading
import time
import warnings
import weakref

from ..observability import metrics as _obs_metrics
from ..observability.scrape import (
    ObservabilityHandler, ThreadedHTTPHost, register_health_provider,
    unregister_health_provider,
)
from ..observability.spans import remote_span, span
from ..resilience import faults
from .engine import EngineOverloadedError
from .qos import QoS, QoSConfig, QoSRejection, UnknownTenantError
from .request import Request, SamplingParams

__all__ = ["Server", "serve"]

_server_counter = itertools.count(1)

# SamplingParams fields accepted on the wire (plus the OpenAI-style
# "max_tokens" alias); anything else in the body is ignored for
# forward compatibility — EXCEPT unknown sampling of known fields,
# which SamplingParams validates by name
_SAMPLING_FIELDS = (
    "max_new_tokens", "do_sample", "temperature", "top_k", "top_p",
    "eos_token_id", "stop_token_ids", "ttl_s", "seed",
)

_RESPONSE_CLASSES = ("2xx", "3xx", "4xx", "5xx")


def _parse_traceparent(header):
    """W3C ``traceparent`` (``00-<32hex trace>-<16hex span>-<flags>``)
    -> this repo's ``"<trace_id>-<span_id>"`` propagation string; None
    for a missing/malformed header (the caller then mints a fresh
    trace root, so every admitted request carries SOME trace id into
    the access log)."""
    if not header or not isinstance(header, str):
        return None
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace, parent, _flags = parts
    if len(trace) != 32 or len(parent) != 16 or len(version) != 2:
        return None
    try:
        int(version, 16), int(trace, 16), int(parent, 16)
    except ValueError:
        return None
    if trace == "0" * 32 or parent == "0" * 16:
        return None
    return f"{trace}-{parent}"


class _ServerMetrics:
    """Plain-attribute counters for the HTTP layer, exported at pull
    time by a weakref collector view (the EngineMetrics pattern)."""

    def __init__(self, server_id):
        self.requests = 0          # POST /v1/completions accepted
        self.streams = 0           # of which streaming
        self.responses = {c: 0 for c in _RESPONSE_CLASSES}
        self.shed_429 = 0          # QoS/overload rejections
        self.disconnects = 0       # mid-stream client hangups
        self.accept_faults = 0     # http.accept degradations
        self.stream_faults = 0     # http.stream degradations
        self.step_errors = 0       # backend stepping degradations
        self.active_streams = 0    # gauge
        self.draining = False
        _register_server_view(self, server_id)

    def count_response(self, code):
        cls = f"{code // 100}xx"
        if cls in self.responses:
            self.responses[cls] += 1


def _register_server_view(m, server_id):
    try:
        from ..observability import MetricFamily, get_registry
    except Exception:
        # analysis: allow(broad-except) observability is optional here
        return
    ref = weakref.ref(m)
    label = {"server": server_id}

    def collect():
        sm = ref()
        if sm is None:
            return None
        fams = [
            MetricFamily(
                "paddle_tpu_serving_http_requests_total", "counter",
            ).add(sm.requests, label),
            MetricFamily(
                "paddle_tpu_serving_http_streams_total", "counter",
            ).add(sm.streams, label),
            MetricFamily(
                "paddle_tpu_serving_http_shed_total", "counter",
            ).add(sm.shed_429, label),
            MetricFamily(
                "paddle_tpu_serving_http_disconnects_total", "counter",
            ).add(sm.disconnects, label),
            MetricFamily(
                "paddle_tpu_serving_http_accept_faults_total", "counter",
            ).add(sm.accept_faults, label),
            MetricFamily(
                "paddle_tpu_serving_http_stream_faults_total", "counter",
            ).add(sm.stream_faults, label),
            MetricFamily(
                "paddle_tpu_serving_http_step_errors_total", "counter",
            ).add(sm.step_errors, label),
            MetricFamily(
                "paddle_tpu_serving_http_active_streams", "gauge",
            ).add(sm.active_streams, label),
            MetricFamily(
                "paddle_tpu_serving_http_draining", "gauge",
            ).add(1.0 if sm.draining else 0.0, label),
        ]
        resp = MetricFamily(
            "paddle_tpu_serving_http_responses_total", "counter",
        )
        for cls, n in sm.responses.items():
            resp.add(n, {**label, "class": cls})
        fams.append(resp)
        return fams

    try:
        get_registry().register_collector(
            f"serving.server.{server_id}", collect
        )
    except Exception:
        # analysis: allow(broad-except) telemetry is best-effort
        pass


class _ApiError(Exception):
    """Internal signal mapped to one structured HTTP error body."""

    def __init__(self, code, err_type, message, param=None,
                 retry_after=None):
        self.code = code
        self.err_type = err_type
        self.message = message
        self.param = param
        self.retry_after = retry_after
        super().__init__(message)

    def body(self):
        err = {"type": self.err_type, "message": self.message}
        if self.param is not None:
            err["param"] = self.param
        return {"error": err}


def _param_from_message(msg):
    """Best-effort offending-field extraction: SamplingParams (and the
    prompt checks) open their ValueError messages with the field
    name."""
    head = str(msg).split(" ", 1)[0]
    if head in _SAMPLING_FIELDS or head in ("prompt", "prompt_token_ids"):
        return "prompt" if head == "prompt_token_ids" else head
    return None


class _Stream:
    """One in-flight HTTP request: the engine-side Request plus the
    completion event the waiting handler blocks on."""

    __slots__ = ("req", "tenant", "done", "output", "streaming")

    def __init__(self, req, tenant, streaming):
        self.req = req
        self.tenant = tenant
        self.streaming = streaming
        self.done = threading.Event()
        self.output = None


class _ApiHandler(ObservabilityHandler):
    """Routes: POST /v1/completions (the API), GET /metrics +
    /healthz (inherited). Handler threads never step the engine."""

    def do_POST(self):
        api = self.server.api
        path = self.path.split("?", 1)[0]
        if path != "/v1/completions":
            self._send_json(404, {"error": {
                "type": "invalid_request_error",
                "message": f"no such endpoint: {path}",
            }})
            return
        try:
            api.handle_completion(self)
        except Exception as e:
            # analysis: allow(broad-except) the HTTP degradation
            # contract: a handler failure answers 500 (when the
            # response line is still writable) and is counted —
            # never propagated into the serving process
            api.metrics.accept_faults += 1
            api.warn_once(
                "accept",
                f"[server] request handling failed (degraded): {e!r}",
            )
            try:
                self._send_json(500, {"error": {
                    "type": "internal_error",
                    "message": f"{type(e).__name__}: {e}",
                }})
            except OSError:
                pass  # peer already gone; nothing left to degrade to

    def _send_json(self, code, obj, headers=None):
        self.server.api.metrics.count_response(code)
        self._send(
            code, json.dumps(obj), "application/json", headers=headers
        )


class Server(ThreadedHTTPHost):
    """The HTTP front door. ``backend`` is a ``Fleet`` or a single
    ``Engine``; ``qos`` a :class:`~.qos.QoS`, :class:`~.qos.QoSConfig`
    or None (default policy: one shared tenant, no limits).
    ``port=0`` binds an ephemeral port (read ``.port``/``.url``)."""

    thread_name = "paddle_tpu-http-api"
    handler_cls = _ApiHandler

    def __init__(self, backend, host="127.0.0.1", port=0, qos=None,
                 registry=None, drain_timeout_s=30.0,
                 poll_interval_s=0.002):
        from .fleet import Fleet

        self.backend = backend
        self._is_fleet = isinstance(backend, Fleet)
        if isinstance(qos, QoS):
            self.qos = qos
        else:
            self.qos = QoS(qos if isinstance(qos, QoSConfig) else None)
        if self._is_fleet:
            self.qos.attach(backend)
        self.server_id = f"{next(_server_counter)}"
        self.metrics = _ServerMetrics(self.server_id)
        self.drain_timeout_s = float(drain_timeout_s)
        self.poll_interval_s = float(poll_interval_s)
        self._draining = False
        self._closed = False
        self._warned: set = set()
        self._streams: dict = {}          # rid -> _Stream
        # ONE lock serializes every backend call (submit/step/abort):
        # engines are stepped from the driver thread, handlers only
        # enqueue/abort under the same lock
        self._backend_lock = threading.Lock()
        # signaled after every step so streaming handlers wake to new
        # tokens, and whenever work arrives so the driver wakes
        self._progress = threading.Condition()
        super().__init__(
            host=host, port=port,
            registry=registry or _obs_metrics.get_registry(),
            api=self,
        )
        self._driver = threading.Thread(
            target=self._step_loop, daemon=True,
            name=f"paddle_tpu-http-driver-{self.server_id}",
        )
        self._driver.start()

        def _probe(ref=weakref.ref(self)):
            srv = ref()
            if srv is None:
                return None
            return {
                "status": "draining" if srv._draining else "ok",
                "active_streams": len(srv._streams),
                "port": srv.port,
            }

        register_health_provider(
            f"serving.server.{self.server_id}", _probe
        )

    # -- lifecycle -----------------------------------------------------------
    def warn_once(self, key, message):
        if key in self._warned:
            return
        self._warned.add(key)
        warnings.warn(message, stacklevel=2)

    def drain(self, timeout=None):
        """Stop admitting (new completions answer 503
        ``server_draining``), wait for in-flight requests to finish.
        Returns True when everything drained inside ``timeout``
        (default ``drain_timeout_s``)."""
        # GIL-atomic one-way bool flip; racing writers all write True
        # analysis: allow(unlocked-shared-mutation) benign idempotent flag
        self._draining = True
        self.metrics.draining = True
        deadline = time.monotonic() + (
            self.drain_timeout_s if timeout is None else float(timeout)
        )
        while self._streams and time.monotonic() < deadline:
            time.sleep(0.01)
        return not self._streams

    def install_signal_handlers(self):
        """SIGTERM -> graceful drain then close (main thread only;
        the CLI entry point calls this)."""
        import signal

        def _on_term(signum, frame):
            t = threading.Thread(
                target=self._drain_and_close, daemon=True,
                name="paddle_tpu-http-drain",
            )
            t.start()

        signal.signal(signal.SIGTERM, _on_term)

    def _drain_and_close(self):
        self.drain()
        self.close()

    def close(self):
        if self._closed:
            return
        # one-way bool flip; a racing duplicate close is idempotent
        # analysis: allow(unlocked-shared-mutation) benign idempotent flag
        self._closed = True
        with self._progress:
            self._progress.notify_all()
        unregister_health_provider(f"serving.server.{self.server_id}")
        super().close()
        self._driver.join(timeout=5.0)

    # -- backend driving -----------------------------------------------------
    def _step_loop(self):
        """The single thread that steps the backend while any HTTP
        request is in flight. Stepping failures degrade (warn-once +
        counter + pause) — the driver must outlive any injected or
        transient backend error."""
        while not self._closed:
            if not self._streams:
                with self._progress:
                    if not self._streams and not self._closed:
                        self._progress.wait(0.05)
                continue
            try:
                with self._backend_lock:
                    outs = self.backend.step()
            except Exception as e:
                # analysis: allow(broad-except) the degradation
                # contract: the HTTP layer must never be fatal to —
                # or killed by — the engine it fronts
                self.metrics.step_errors += 1
                self.warn_once(
                    "step",
                    f"[server] backend step failed (degraded): {e!r}",
                )
                time.sleep(0.05)
                outs = []
            for out in outs:
                self._finish_stream(out)
            with self._progress:
                self._progress.notify_all()
            if not outs:
                # requests in flight but nothing finished this step;
                # yield briefly so handler threads can drain tokens
                time.sleep(self.poll_interval_s)

    def _finish_stream(self, out):
        stream = self._streams.pop(out.request_id, None)
        if stream is None:
            return  # in-process caller's request (shared backend)
        self.metrics.active_streams = len(self._streams)
        self.qos.on_finish(stream.req)
        stream.output = out
        stream.done.set()

    def _submit(self, prompt, params, tenant):
        """Admission under the backend lock; returns the new _Stream.
        Raises _ApiError for every refusal. The QoS check and the
        backend admission share the lock so they are ATOMIC —
        otherwise N concurrent handlers could all pass the quota
        check before any of them is accounted inflight."""
        with self._backend_lock:
            try:
                backlog, capacity = self._backlog()
                self.qos.try_admit(
                    tenant, params.max_new_tokens,
                    backlog=backlog, capacity=capacity,
                )
            except QoSRejection as e:
                raise _ApiError(
                    429, "rate_limit_error", str(e),
                    retry_after=e.retry_after,
                )
            try:
                if self._is_fleet:
                    freq = self.backend.add_request(
                        prompt, params, tenant=tenant
                    )
                    req = freq.request
                else:
                    req = Request(prompt, params)
                    req.tenant = tenant
                    self.backend.submit(req)
            except EngineOverloadedError as e:
                self.qos.count_queue_shed(tenant)
                raise _ApiError(
                    429, "overloaded_error", str(e), retry_after=1.0
                )
            except RuntimeError as e:
                # engine bounded admission queue (max_waiting)
                self.qos.count_queue_shed(tenant)
                raise _ApiError(
                    429, "overloaded_error", str(e), retry_after=1.0
                )
            except ValueError as e:
                raise _ApiError(
                    400, "invalid_request_error", str(e),
                    param=_param_from_message(e),
                )
            stream = _Stream(req, tenant, streaming=False)
            self._streams[req.request_id] = stream
            self.metrics.active_streams = len(self._streams)
            if not self._is_fleet:
                # the fleet's add_request already accounted the
                # admission; the bare-engine path has no QoS hook of
                # its own. Still under the lock: the accounting must
                # land before the next handler's quota check runs.
                self.qos.on_admit(req)
        with self._progress:
            self._progress.notify_all()
        return stream

    def _abort(self, rid):
        with self._backend_lock:
            self.backend.abort(rid)

    def _backlog(self):
        """(live backlog, capacity-or-None) for burn-first shedding."""
        b = self.backend
        if self._is_fleet:
            return (
                sum(not f.done for f in b._pending),
                b.config.max_pending,
            )
        return len(b.waiting), getattr(b.config, "max_waiting", None)

    # -- request handling ----------------------------------------------------
    def handle_completion(self, handler):
        self.metrics.requests += 1
        try:
            faults.fire(
                "http.accept", path="/v1/completions",
                client=handler.client_address[0],
            )
        except Exception as e:
            # analysis: allow(broad-except) injected accept fault:
            # count + structured 500, never fatal to the engine
            self.metrics.accept_faults += 1
            self.warn_once(
                "http.accept",
                f"[server] http.accept fault (degraded): {e!r}",
            )
            handler._send_json(500, {"error": {
                "type": "internal_error",
                "message": f"accept failed: {type(e).__name__}: {e}",
            }})
            return
        try:
            stream, body = self._admit(handler)
        except _ApiError as e:
            headers = {}
            if e.retry_after is not None:
                headers["Retry-After"] = str(
                    max(1, int(math.ceil(e.retry_after)))
                )
                self.metrics.shed_429 += 1
            handler._send_json(e.code, e.body(), headers=headers)
            return
        if stream.streaming:
            self.metrics.streams += 1
            self._stream_response(handler, stream)
        else:
            self._blocking_response(handler, stream)

    def _admit(self, handler):
        """Parse + validate + QoS-admit one POST body; returns the
        registered _Stream. Every refusal raises _ApiError."""
        if self._draining:
            raise _ApiError(
                503, "server_draining",
                "server is draining; retry against another replica",
                retry_after=1.0,
            )
        try:
            length = int(handler.headers.get("Content-Length", 0))
        except (TypeError, ValueError):
            length = 0
        raw = handler.rfile.read(length) if length > 0 else b""
        try:
            body = json.loads(raw.decode() or "null")
        except (ValueError, UnicodeDecodeError):
            raise _ApiError(
                400, "invalid_request_error",
                "request body is not valid JSON",
            )
        if not isinstance(body, dict):
            raise _ApiError(
                400, "invalid_request_error",
                "request body must be a JSON object",
            )
        try:
            tenant = self.qos.resolve(handler.headers)
        except UnknownTenantError as e:
            raise _ApiError(401, "authentication_error", str(e))
        prompt = body.get("prompt", body.get("prompt_token_ids"))
        if (not isinstance(prompt, list) or not prompt or any(
                isinstance(t, bool) or not isinstance(t, int)
                for t in prompt)):
            raise _ApiError(
                400, "invalid_request_error",
                "prompt must be a non-empty list of token ids "
                "(this API is tokenizer-less)",
                param="prompt",
            )
        streaming = body.get("stream", False)
        if not isinstance(streaming, bool):
            raise _ApiError(
                400, "invalid_request_error",
                f"stream must be a boolean, got {streaming!r}",
                param="stream",
            )
        kw = {}
        if "max_tokens" in body:      # OpenAI-style alias
            kw["max_new_tokens"] = body["max_tokens"]
        for f in _SAMPLING_FIELDS:
            if f in body:
                kw[f] = body[f]
        try:
            params = SamplingParams(**kw)
        except (ValueError, TypeError) as e:
            raise _ApiError(
                400, "invalid_request_error", str(e),
                param=_param_from_message(e),
            )
        # trace propagation: an inbound W3C traceparent continues the
        # caller's trace; without one a fresh root is minted. Either
        # way the span is open across Request creation, so
        # Request.trace_id (and thus the access-log "trace" field)
        # carries the distributed trace id
        tp = _parse_traceparent(handler.headers.get("traceparent"))
        ctx = (
            remote_span("http.completion", tp, tenant=tenant)
            if tp is not None
            else span("http.completion", tenant=tenant)
        )
        with ctx:
            stream = self._submit(prompt, params, tenant)
        stream.streaming = streaming
        return stream, body

    # -- responses -----------------------------------------------------------
    def _completion_body(self, stream, out):
        n_prompt = len(stream.req.prompt_token_ids)
        n_out = len(out.token_ids)
        return {
            "id": str(out.request_id),
            "object": "text_completion",
            "tenant": stream.tenant,
            "choices": [{
                "index": 0,
                "token_ids": list(out.token_ids),
                "finish_reason": out.finish_reason,
            }],
            "usage": {
                "prompt_tokens": n_prompt,
                "completion_tokens": n_out,
                "total_tokens": n_prompt + n_out,
            },
        }

    def _blocking_response(self, handler, stream):
        while not stream.done.wait(0.05):
            if self._closed:
                handler._send_json(503, {"error": {
                    "type": "server_draining",
                    "message": "server closed mid-request",
                }})
                return
        out = stream.output
        rid_headers = {"x-request-id": str(stream.req.request_id)}
        if out.finish_reason == "error":
            handler._send_json(500, {"error": {
                "type": "internal_error",
                "message": out.error or "request errored",
            }}, headers=rid_headers)
            return
        handler._send_json(
            200, self._completion_body(stream, out), headers=rid_headers
        )

    def _stream_response(self, handler, stream):
        """SSE: chunks of new token ids as they land (the handler's
        cursor over ``output_token_ids`` — the EMIT-cursor idiom at
        the wire), a final chunk with finish_reason + usage, then
        ``[DONE]``. A write failure (client gone, injected
        ``http.stream`` fault) aborts THIS request and nothing
        else."""
        rid = stream.req.request_id
        self.metrics.count_response(200)
        try:
            handler.send_response(200)
            handler.send_header(
                "Content-Type", "text/event-stream; charset=utf-8"
            )
            handler.send_header("Cache-Control", "no-cache")
            handler.send_header("Connection", "close")
            handler.send_header("x-request-id", str(rid))
            handler.end_headers()
        except OSError:
            self._client_gone(stream)
            return
        cursor = 0
        seq = 0
        try:
            while True:
                done = stream.done.is_set()
                toks = stream.req.output_token_ids
                if len(toks) > cursor:
                    chunk = list(toks[cursor:])
                    cursor += len(chunk)
                    seq += 1
                    faults.fire(
                        "http.stream", rid=str(rid), seq=seq,
                    )
                    self._write_event(handler, {
                        "id": str(rid),
                        "object": "text_completion.chunk",
                        "choices": [{
                            "index": 0,
                            "token_ids": chunk,
                            "finish_reason": None,
                        }],
                    })
                if done:
                    break
                if self._closed:
                    return
                with self._progress:
                    self._progress.wait(0.05)
            out = stream.output
            final = self._completion_body(stream, out)
            final["object"] = "text_completion.chunk"
            self._write_event(handler, final)
            handler.wfile.write(b"data: [DONE]\n\n")
            handler.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            self._client_gone(stream)
        except Exception as e:
            # analysis: allow(broad-except) injected http.stream
            # fault or serializer failure: degrade to aborting THIS
            # stream; the engine and every other stream keep going
            self.metrics.stream_faults += 1
            self.warn_once(
                "http.stream",
                f"[server] stream write failed (degraded): {e!r}",
            )
            if not stream.done.is_set():
                self._abort(rid)

    def _write_event(self, handler, obj):
        handler.wfile.write(
            b"data: " + json.dumps(obj).encode() + b"\n\n"
        )
        handler.wfile.flush()

    def _client_gone(self, stream):
        """Mid-stream disconnect: abort the request so its slot frees
        on the next step (no slot leak for a dead client)."""
        self.metrics.disconnects += 1
        if not stream.done.is_set():
            self._abort(stream.req.request_id)


def serve(backend, host="127.0.0.1", port=8000, qos=None,
          registry=None):
    """Convenience wrapper: build a :class:`Server`, install the
    SIGTERM drain handler, return the server (non-blocking — callers
    own the foreground wait)."""
    srv = Server(
        backend, host=host, port=port, qos=qos, registry=registry
    )
    try:
        srv.install_signal_handlers()
    except ValueError:
        # not the main thread (tests): signals stay uninstalled
        pass
    return srv
