"""Batched masked sampling for the continuous batch.

Reuses ``generation.warp_logits`` — the exact warp math behind
``GenerationMixin.generate`` — with per-slot parameter VECTORS instead of
scalars, so one [slots, vocab] program samples every occupant of the batch
at once (heterogeneous temperature/top-k/top-p across slots, no per-request
dispatch). Greedy rows bypass the warp via a final ``where`` on the
``do_sample`` mask, which keeps greedy serving bit-identical to
``generate``'s ``F.argmax`` path.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..generation import warp_logits

__all__ = ["sample_tokens", "pack_sampling_params"]


def sample_tokens(logits, temperature, top_k, top_p, do_sample, u=None):
    """Next token per slot on [slots, vocab] logits.

    ``temperature/top_k/top_p/do_sample``: [slots] arrays. ``u``: uniform
    (0, 1] noise of logits' shape — passed in (rather than drawn here) so
    the caller owns the RNG stream; the Gumbel trick then matches
    ``generation._sample``. ``u=None`` declares the whole batch greedy
    (a STATIC fact the engine knows host-side): the vocab-wide
    sort/softmax warp is skipped entirely instead of computed and
    discarded by the ``where``.
    """
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if u is None:
        return greedy
    warped = warp_logits(logits, temperature, top_k, top_p)
    gumbel = -jnp.log(-jnp.log(u))
    sampled = jnp.argmax(warped + gumbel, axis=-1).astype(jnp.int32)
    return jnp.where(jnp.asarray(do_sample), sampled, greedy)


def pack_sampling_params(requests):
    """Pack per-slot SamplingParams into fixed-shape host arrays (empty
    slots get inert defaults). ``requests``: list of Request-or-None, one
    per batch slot."""
    n = len(requests)
    temperature = np.ones(n, np.float32)
    top_k = np.zeros(n, np.int32)
    top_p = np.ones(n, np.float32)
    do_sample = np.zeros(n, bool)
    for i, r in enumerate(requests):
        if r is None:
            continue
        p = r.sampling_params
        temperature[i] = p.temperature
        top_k[i] = p.top_k
        top_p[i] = p.top_p
        do_sample[i] = p.do_sample
    return {
        "temperature": temperature,
        "top_k": top_k,
        "top_p": top_p,
        "do_sample": do_sample,
    }
