"""paddle.static — the deployment-facing subset.

ref: python/paddle/static (Program/Executor graph mode, static/io
save/load_inference_model). Design decision (SURVEY §7 step 3): the
define-and-run Program/Executor frontend is subsumed by program capture —
`paddle.jit.to_static` stages define-by-run code into one XLA program,
which is what Program construction + PirInterpreter execution achieve in
the reference. This namespace keeps the *artifact* APIs reference users
script against (InputSpec, save/load_inference_model, normalize_program)
over the StableHLO export path; the graph-construction API
(program_guard et al.) intentionally has no equivalent and raises with
guidance.
"""
from __future__ import annotations

from ..jit.serialization import InputSpec, TranslatedLayer
from ..jit.serialization import load as _jit_load
from ..jit.serialization import save as _jit_save

__all__ = [
    "InputSpec", "save_inference_model", "load_inference_model",
    "normalize_program", "Program", "program_guard", "default_main_program",
]


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         **kwargs):
    """ref: static/io.py save_inference_model. `fetch_vars` carries the
    layer (program) to export; `feed_vars` the InputSpecs."""
    layer = kwargs.get("program") or fetch_vars
    specs = [
        v if isinstance(v, InputSpec) else InputSpec(v.shape, v.dtype.name)
        for v in (feed_vars if isinstance(feed_vars, (list, tuple))
                  else [feed_vars])
    ]
    _jit_save(layer, path_prefix, input_spec=specs)


def load_inference_model(path_prefix, executor=None, **kwargs):
    """ref: static/io.py load_inference_model -> (program, feed_names,
    fetch_names) triple; here the program IS the callable artifact."""
    tl = _jit_load(path_prefix)
    feed_names = [s.name or f"x{i}" for i, s in enumerate(tl.input_spec)]
    return tl, feed_names, None


def normalize_program(program, feed_vars, fetch_vars):
    return program


def _no_graph_mode(*a, **k):
    raise NotImplementedError(
        "the define-and-run Program/Executor frontend has no TPU-native "
        "equivalent; stage define-by-run code with paddle.jit.to_static "
        "(training: paddle.jit.TrainStep, deployment: paddle.jit.save)"
    )


Program = _no_graph_mode
program_guard = _no_graph_mode
default_main_program = _no_graph_mode
