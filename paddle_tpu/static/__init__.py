"""paddle.static — the deployment-facing subset.

ref: python/paddle/static (Program/Executor graph mode, static/io
save/load_inference_model). Design decision (SURVEY §7 step 3): the
define-and-run Program/Executor frontend is subsumed by program capture —
`paddle.jit.to_static` stages define-by-run code into one XLA program,
which is what Program construction + PirInterpreter execution achieve in
the reference. This namespace keeps the *artifact* APIs reference users
script against (InputSpec, save/load_inference_model, normalize_program)
over the StableHLO export path; the graph-construction API
(program_guard et al.) intentionally has no equivalent and raises with
guidance.
"""
from __future__ import annotations

from ..jit.serialization import InputSpec, TranslatedLayer
from ..jit.serialization import load as _jit_load
from ..jit.serialization import save as _jit_save

__all__ = [
    "InputSpec", "save_inference_model", "load_inference_model",
    "normalize_program", "Program", "program_guard", "default_main_program",
]


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         **kwargs):
    """ref: static/io.py save_inference_model. `fetch_vars` carries the
    layer (program) to export; `feed_vars` the InputSpecs."""
    layer = kwargs.get("program") or fetch_vars
    specs = [
        v if isinstance(v, InputSpec) else InputSpec(v.shape, v.dtype.name)
        for v in (feed_vars if isinstance(feed_vars, (list, tuple))
                  else [feed_vars])
    ]
    _jit_save(layer, path_prefix, input_spec=specs)


def load_inference_model(path_prefix, executor=None, **kwargs):
    """ref: static/io.py load_inference_model -> (program, feed_names,
    fetch_names) triple; here the program IS the callable artifact."""
    tl = _jit_load(path_prefix)
    feed_names = [s.name or f"x{i}" for i, s in enumerate(tl.input_spec)]
    return tl, feed_names, None


def normalize_program(program, feed_vars, fetch_vars):
    return program


# Per-API migration recipes (VERDICT r4: reference users' static-graph
# scripts need an explicit path per API, not a generic refusal).
_MIGRATIONS = {
    "Program": (
        "build the model as paddle.nn.Layer code; the compiled program "
        "is created by paddle.jit.to_static(layer) (inference) or "
        "paddle.jit.TrainStep(model, loss_fn, opt) (training)"
    ),
    "program_guard": (
        "delete the guard; define-by-run code IS the program. Wrap the "
        "function you were building inside the guard with "
        "paddle.jit.to_static"
    ),
    "default_main_program": (
        "no global program exists; the staged function returned by "
        "paddle.jit.to_static plays this role — hold a reference to it"
    ),
    "default_startup_program": (
        "parameter initialization runs eagerly at Layer construction; "
        "delete the startup program and rely on layer initializers "
        "(paddle.nn.initializer)"
    ),
    "Executor": (
        "no executor object: call the staged function directly — "
        "outputs = paddle.jit.to_static(layer)(inputs). For feed/fetch "
        "dicts, pass/collect tensors as arguments/returns"
    ),
    "scope_guard": (
        "variable scopes do not exist; parameters live on their Layer. "
        "For multiple model instances, construct multiple Layers"
    ),
    "global_scope": (
        "inspect parameters via layer.state_dict() instead of scope "
        "variables"
    ),
    "data": (
        "replace static.data(name, shape, dtype) with "
        "paddle.static.InputSpec(shape, dtype, name) passed to "
        "paddle.jit.to_static(input_spec=[...]) or jit.save"
    ),
}


class _MigrationStub:
    """Callable stub that raises an API-specific migration recipe."""

    def __init__(self, api):
        self._api = api

    def _raise(self, *a, **k):
        raise NotImplementedError(
            f"paddle.static.{self._api} belongs to the define-and-run "
            "Program frontend, which has no TPU-native equivalent. "
            f"Migration: {_MIGRATIONS[self._api]}"
        )

    __call__ = _raise

    def __enter__(self):
        self._raise()

    def __exit__(self, *exc):
        return False


Program = _MigrationStub("Program")
program_guard = _MigrationStub("program_guard")
default_main_program = _MigrationStub("default_main_program")
default_startup_program = _MigrationStub("default_startup_program")
Executor = _MigrationStub("Executor")
scope_guard = _MigrationStub("scope_guard")
global_scope = _MigrationStub("global_scope")
data = _MigrationStub("data")

__all__ += [
    "default_startup_program", "Executor", "scope_guard", "global_scope",
    "data",
]
