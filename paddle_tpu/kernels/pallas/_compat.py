"""jax version compatibility for the Pallas TPU kernels.

jax renamed ``pltpu.TPUCompilerParams`` to ``pltpu.CompilerParams``;
resolve whichever this jax exposes once, and fail loudly at import time
(not at first kernel call) if neither exists.
"""
from __future__ import annotations

import jax
from jax.experimental.pallas import tpu as pltpu

CompilerParams = getattr(
    pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
)
if CompilerParams is None:  # pragma: no cover - future-jax guard
    raise ImportError(
        f"jax {jax.__version__}: neither pallas.tpu.CompilerParams nor "
        "TPUCompilerParams exists; update kernels/pallas/_compat.py for "
        "this jax version"
    )
