"""Shared runtime for the Pallas TPU kernels: jax compat + dispatch.

Three concerns every kernel in this package routes through, instead of
per-file version sniffing and ad-hoc interpret checks:

  * ``CompilerParams`` — jax renamed ``pltpu.TPUCompilerParams`` to
    ``pltpu.CompilerParams``; resolve whichever this jax exposes once,
    and fail loudly at import time (not at first kernel call) if
    neither exists. Audited against the current pin (jax 0.4.37 ships
    ``TPUCompilerParams``; newer jax ships ``CompilerParams``).
  * ``pl_call()`` — the one ``pl.pallas_call`` wrapper: interpret-mode
    autoselect off-TPU (so CPU tier-1 exercises the same kernel code
    path the TPU compiles) and ``dimension_semantics`` threading
    through the resolved CompilerParams class.
  * ``record_fallback()`` — kernel-path observability: every time a
    Pallas hot path degrades to its XLA fallback (unsupported backend,
    shape, or dtype) the degradation is counted in
    ``paddle_tpu_kernels_fallbacks_total{kernel,reason}`` and warned
    once per (kernel, reason). Degradation never raises; the counter is
    best-effort (a broken metrics registry must not take down a
    launch).
"""
from __future__ import annotations

import warnings

import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

CompilerParams = getattr(
    pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
)
if CompilerParams is None:  # pragma: no cover - future-jax guard
    raise ImportError(
        f"jax {jax.__version__}: neither pallas.tpu.CompilerParams nor "
        "TPUCompilerParams exists; update kernels/pallas/_compat.py for "
        "this jax version"
    )


def interpret_mode():
    """True off-TPU: kernels run under the Pallas interpreter so the
    same kernel body is testable on the CPU mesh."""
    return jax.default_backend() != "tpu"


def pl_call(kernel, *, dimension_semantics=None, interpret=None,
            compiler_params=None, **kwargs):
    """``pl.pallas_call`` with the package-wide defaults applied:
    interpret-mode autoselect (``interpret=None``) and
    ``dimension_semantics`` routed through the version-resolved
    CompilerParams class. Any explicit ``compiler_params`` wins."""
    if compiler_params is None and dimension_semantics is not None:
        compiler_params = CompilerParams(
            dimension_semantics=tuple(dimension_semantics)
        )
    if interpret is None:
        interpret = interpret_mode()
    return pl.pallas_call(
        kernel, compiler_params=compiler_params, interpret=interpret,
        **kwargs,
    )


# (kernel, reason) pairs already warned about — the counter moves on
# every degradation, the warning fires once per pair per process
_warned_fallbacks = set()


def record_fallback(kernel, reason, hint=None):
    """A Pallas path degraded to its XLA fallback. Count it (always)
    and warn (once per (kernel, reason)); NEVER raise — degradation is
    the contract, the fallback produces the same math. ``hint`` lets
    the caller append remediation that actually applies to ITS
    degradation (e.g. the interpret flag for an off-backend serving
    request)."""
    try:
        from ...observability import counter

        counter(
            "paddle_tpu_kernels_fallbacks_total",
            "Pallas kernel launches degraded to the XLA fallback",
            labelnames=("kernel", "reason"),
        ).inc(kernel=kernel, reason=reason)
    except Exception:
        # analysis: allow(broad-except) fallback telemetry is
        # best-effort: a broken metrics registry must not take down the
        # launch that is already degrading gracefully
        pass
    if (kernel, reason) not in _warned_fallbacks:
        _warned_fallbacks.add((kernel, reason))
        msg = (
            f"pallas kernel {kernel!r} degraded to the XLA fallback "
            f"({reason})"
        )
        if hint:
            msg += f"; {hint}"
        warnings.warn(msg, stacklevel=3)


def fallbacks_total():
    """Current total of the degradation counter (test/diagnostic
    accessor); 0 when the registry is unavailable."""
    try:
        from ...observability import counter

        c = counter(
            "paddle_tpu_kernels_fallbacks_total",
            "Pallas kernel launches degraded to the XLA fallback",
            labelnames=("kernel", "reason"),
        )
        return sum(child.value for _, child in c._series())
    except Exception:
        # analysis: allow(broad-except) same best-effort contract as
        # record_fallback above
        return 0
