"""Pallas TPU grouped (ragged) matrix multiply for MoE expert FFNs.

The megablocks-style dropless-MoE contraction (ref: the reference's
fused_moe_kernel.cu grouped cutlass GEMMs; MegaBlocks, MLSys '23): rows
of ``lhs`` are sorted so each expert's tokens form one contiguous
segment, and every expert multiplies ONLY its own segment against its
own weight matrix —

    out[i] = lhs[i] @ rhs[g(i)]      g(i) = the group row i belongs to

with ``group_sizes [num_groups]`` giving the segment lengths in order.
No capacity padding, no one-hot dispatch tensors: the arithmetic is
exactly ``sum(group_sizes) * k * m`` MACs.

Kernel shape: the row dimension is cut into TM-row tiles and the work
list is the (group, tile) overlap staircase — at most
``num_row_tiles + num_groups`` items, computed as scalar-prefetch
metadata INSIDE the traced program (group sizes are data, the grid is
static). Each item multiplies one row tile against one expert's weight
block and accumulates the rows that belong to that expert; consecutive
items share either the tile (an expert boundary inside a tile) or the
expert (a segment spanning tiles), so the f32 scratch accumulator
carries across a tile's items and is stored once per out block.

Quantized experts: ``rhs`` may be int8 with per-expert-per-output-channel
float32 ``rhs_scales [e, m]`` (weight-only absmax quantization); the
kernel dequantizes in-kernel by scaling each expert's contribution —
``(x @ q) * scale`` is algebraically ``x @ (q * scale)`` for per-column
scales, so no dense float copy of the weights ever exists.

Fallback: ``grouped_matmul_xla`` — the same contraction as a pure-XLA
sort/segment program (tile-aligned segment padding + one batched
matmul; measured at parity with the capacity-padded dense einsum on
CPU, where ``jax.lax.ragged_dot`` lowers 3-6x slower). CPU tier-1 runs
this path, and it is the counted degradation target for unsupported
shapes/dtypes on TPU. Both paths are differentiable: the custom VJP
computes the kernel's grads through the fallback's contraction.

Contract: ``sum(group_sizes) == lhs.shape[0]`` — every row belongs to a
group (the MoE dispatch guarantees this); rows beyond the sum are
unspecified. Empty groups are fine (zero-length segments are skipped by
the staircase metadata).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._compat import interpret_mode, pl_call, record_fallback

__all__ = ["grouped_matmul", "grouped_matmul_xla"]

DEFAULT_TM = 128
DEFAULT_TN = 128


def _group_metadata(group_sizes, num_row_tiles, tm):
    """The (group, tile) staircase as four [T] int32 arrays, T =
    num_row_tiles + num_groups (static): per work item its row tile,
    its group, and the [lo, hi) global-row span of that group (lo == hi
    marks an inactive padding item). Computed with XLA ops over
    [e]-sized arrays — cheap, and legal inside a jit (the group sizes
    are traced data)."""
    e = group_sizes.shape[0]
    sizes = group_sizes.astype(jnp.int32)
    offs = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(sizes)]
    )
    start, end = offs[:-1], offs[1:]
    first = start // tm
    last = jnp.where(sizes > 0, (end - 1) // tm, first)
    count = jnp.where(sizes > 0, last - first + 1, 0)
    istart = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(count)]
    )  # [e+1]; istart[g] = first work item of group g
    total = istart[-1]
    t = jnp.arange(num_row_tiles + e, dtype=jnp.int32)
    # largest g with istart[g] <= t: zero-count groups share their
    # successor's start, so side="right" skips them
    g = (
        jnp.searchsorted(istart[:-1], t, side="right").astype(jnp.int32)
        - 1
    )
    valid = t < total
    tile_id = first[g] + (t - istart[:-1][g])
    # padding items extend the LAST real tile's run with empty spans:
    # they add nothing and keep the final out block's store at the
    # final grid step
    tile_id = jnp.where(valid, tile_id, num_row_tiles - 1)
    gid = jnp.where(valid, g, e - 1)
    lo = jnp.where(valid, start[g], 0)
    hi = jnp.where(valid, end[g], 0)
    return tile_id, gid, lo, hi


def _gmm_kernel(tile_ref, gid_ref, lo_ref, hi_ref, x_ref, w_ref, o_ref,
                acc_scr, *, tm, n_items, quant):
    t = pl.program_id(1)
    tile = tile_ref[t]
    prev = tile_ref[jnp.maximum(t - 1, 0)]
    nxt = tile_ref[jnp.minimum(t + 1, n_items - 1)]

    @pl.when((t == 0) | (prev != tile))
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    x = x_ref[...].astype(jnp.float32)          # [tm, k]
    w = w_ref[0].astype(jnp.float32)            # [k, tn]
    contrib = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                           # [tm, tn]
    row = tile * tm + jax.lax.broadcasted_iota(
        jnp.int32, contrib.shape, 0
    )
    mask = (row >= lo_ref[t]) & (row < hi_ref[t])
    acc_scr[:] += jnp.where(mask, contrib, 0.0)

    @pl.when((t == n_items - 1) | (nxt != tile))
    def _store():
        o_ref[...] = acc_scr[:].astype(o_ref.dtype)


def _gmm_kernel_quant(tile_ref, gid_ref, lo_ref, hi_ref, x_ref, w_ref,
                      s_ref, o_ref, acc_scr, *, tm, n_items, quant):
    """Int8-rhs variant: per-output-channel dequant applied to this
    expert's contribution after the integer-weight matmul."""
    t = pl.program_id(1)
    tile = tile_ref[t]
    prev = tile_ref[jnp.maximum(t - 1, 0)]
    nxt = tile_ref[jnp.minimum(t + 1, n_items - 1)]

    @pl.when((t == 0) | (prev != tile))
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    x = x_ref[...].astype(jnp.float32)
    w = w_ref[0].astype(jnp.float32)
    contrib = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * s_ref[0][None, :]                       # dequant-in-kernel
    row = tile * tm + jax.lax.broadcasted_iota(
        jnp.int32, contrib.shape, 0
    )
    mask = (row >= lo_ref[t]) & (row < hi_ref[t])
    acc_scr[:] += jnp.where(mask, contrib, 0.0)

    @pl.when((t == n_items - 1) | (nxt != tile))
    def _store():
        o_ref[...] = acc_scr[:].astype(o_ref.dtype)


def _gmm_pallas_raw(lhs, rhs, group_sizes, rhs_scales, tm, tn):
    n, k = lhs.shape
    e, _, m = rhs.shape
    tm = max(8, min(tm, -(-n // 8) * 8))
    n_pad = -(-n // tm) * tm
    if n_pad != n:
        lhs = jnp.pad(lhs, ((0, n_pad - n), (0, 0)))
    num_row_tiles = n_pad // tm
    tn = min(tn, m)
    if m % tn:
        tn = m  # odd widths: one block over m (interpret/CPU path)
    num_col_tiles = m // tn
    n_items = num_row_tiles + e
    tile_id, gid, lo, hi = _group_metadata(
        group_sizes, num_row_tiles, tm
    )

    quant = rhs_scales is not None
    kernel = _gmm_kernel_quant if quant else _gmm_kernel
    in_specs = [
        pl.BlockSpec((tm, k), lambda j, t, tile, gid, lo, hi: (tile[t], 0)),
        pl.BlockSpec(
            (1, k, tn), lambda j, t, tile, gid, lo, hi: (gid[t], 0, j)
        ),
    ]
    operands = [lhs, rhs]
    if quant:
        in_specs.append(pl.BlockSpec(
            (1, tn), lambda j, t, tile, gid, lo, hi: (gid[t], j)
        ))
        operands.append(rhs_scales.astype(jnp.float32))

    out = pl_call(
        functools.partial(
            kernel, tm=tm, n_items=n_items, quant=quant,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(num_col_tiles, n_items),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (tm, tn), lambda j, t, tile, gid, lo, hi: (tile[t], j)
            ),
            scratch_shapes=[pltpu.VMEM((tm, tn), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((n_pad, m), lhs.dtype),
        dimension_semantics=("parallel", "arbitrary"),
    )(tile_id, gid, lo, hi, *operands)
    return out[:n]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _gmm_pallas(lhs, rhs, group_sizes, tm, tn):
    return _gmm_pallas_raw(lhs, rhs, group_sizes, None, tm, tn)


def _gmm_pallas_fwd(lhs, rhs, group_sizes, tm, tn):
    return _gmm_pallas(lhs, rhs, group_sizes, tm, tn), (
        lhs, rhs, group_sizes,
    )


def _gmm_pallas_bwd(tm, tn, res, g):
    # grads via the XLA fallback's contraction (dlhs = g @ rhs[gid]^T
    # per segment, drhs = the segment-wise outer products); a dedicated
    # Pallas backward kernel is a follow-up — training through the
    # ragged path stays correct either way
    lhs, rhs, group_sizes = res
    import numpy as np

    _, vjp = jax.vjp(
        lambda a, b: grouped_matmul_xla(a, b, group_sizes),
        lhs, rhs,
    )
    dlhs, drhs = vjp(g)
    # integer primal -> symbolic-zero (float0) tangent
    zero_gs = np.zeros(group_sizes.shape, jax.dtypes.float0)
    return dlhs, drhs, zero_gs


_gmm_pallas.defvjp(_gmm_pallas_fwd, _gmm_pallas_bwd)


def grouped_matmul_xla(lhs, rhs, group_sizes, rhs_scales=None, *,
                       tm=128):
    """The pure-XLA sort/segment fallback: pad every group's segment up
    to a tile boundary (the aligned form of the kernel's staircase —
    at most ``e`` extra tiles), run ONE batched matmul of row tiles
    against per-tile gathered expert weights, and gather the live rows
    back. No masking pass, no output scatter-add, so XLA executes it at
    plain batched-einsum speed — measured at parity with the
    capacity-padded dense einsum on CPU, unlike ``jax.lax.ragged_dot``
    (~3-6x slower there). Differentiable by construction (scatter /
    batched matmul / gather).

    Int8 expert weights dequantize as a per-tile column scale on the
    matmul output — algebraically identical to the kernel's in-kernel
    dequant, still never materializing dense float weights."""
    n, k = lhs.shape
    e, _, m = rhs.shape
    gs = group_sizes.astype(jnp.int32)
    tm = max(8, min(tm, -(-max(n, 1) // 8) * 8))
    num_tiles = -(-n // tm) + e            # static tile bound
    offs = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(gs)]
    )
    # per-group padded tile start (tile units), aligned so no tile
    # spans two groups
    gtiles = -(-gs // tm)                  # cdiv
    tstart = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(gtiles)]
    )  # [e+1]
    # group of each sorted row, and its padded destination row
    i = jnp.arange(n, dtype=jnp.int32)
    gi = (
        jnp.searchsorted(offs, i, side="right").astype(jnp.int32) - 1
    )
    ppos = tstart[gi] * tm + (i - offs[gi])
    x_pad = jnp.zeros((num_tiles * tm, k), lhs.dtype).at[ppos].set(lhs)
    # expert of each tile (empty groups share their successor's start;
    # side="right" skips them); tiles past the padded total are dead —
    # their rows are zero and nothing gathers them back
    t = jnp.arange(num_tiles, dtype=jnp.int32)
    gid = jnp.clip(
        jnp.searchsorted(tstart[:-1], t, side="right").astype(
            jnp.int32
        ) - 1,
        0, e - 1,
    )
    y = jnp.einsum(
        "tik,tkm->tim",
        x_pad.reshape(num_tiles, tm, k),
        rhs[gid],
        preferred_element_type=jnp.float32,
    )
    if rhs_scales is not None:
        y = y * rhs_scales.astype(jnp.float32)[gid][:, None, :]
    return y.reshape(num_tiles * tm, m)[ppos].astype(lhs.dtype)


def _pallas_supported(lhs, rhs):
    """(ok, reason) for the real-TPU kernel; interpret mode (off-TPU)
    has no tiling constraints."""
    if lhs.dtype not in (jnp.float32, jnp.bfloat16):
        return False, "dtype"
    if rhs.dtype not in (jnp.float32, jnp.bfloat16, jnp.int8):
        return False, "dtype"
    if interpret_mode():
        return True, None
    k, m = rhs.shape[1], rhs.shape[2]
    if k % 8 or m % 128:
        return False, "shape"
    return True, None


def grouped_matmul(lhs, rhs, group_sizes, *, rhs_scales=None,
                   impl="auto", tm=DEFAULT_TM, tn=DEFAULT_TN):
    """Ragged grouped GEMM: ``out[i] = lhs[i] @ rhs[g(i)]``.

    lhs: [n, k] rows sorted by group; rhs: [e, k, m] stacked expert
    weights (optionally int8 with ``rhs_scales [e, m]``); group_sizes:
    [e] int32 summing to n. Returns [n, m] in ``lhs.dtype`` (f32
    accumulation on every path).

    impl:
      * ``"auto"`` — the Pallas kernel on TPU (FLAGS_use_pallas_kernels),
        the XLA ``ragged_dot`` fallback elsewhere; an unsupported
        shape/dtype on TPU degrades to the fallback (warned + counted in
        ``paddle_tpu_kernels_fallbacks_total``), never raises.
      * ``"pallas"`` — always the kernel (interpreter off-TPU): the
        parity-testing path.
      * ``"xla"`` — always the fallback.

    The float path is differentiable (custom VJP, grads via
    ``ragged_dot``); the int8 path is inference-only.
    """
    if impl not in ("auto", "pallas", "xla"):
        raise ValueError(
            f'grouped_matmul impl must be "auto", "pallas" or "xla", '
            f"got {impl!r}"
        )
    if impl == "auto":
        from ...core import flags

        if (jax.default_backend() == "tpu"
                and flags.get_flag("FLAGS_use_pallas_kernels")):
            ok, reason = _pallas_supported(lhs, rhs)
            if ok:
                impl = "pallas"
            else:
                record_fallback("grouped_matmul", reason)
                impl = "xla"
        else:
            impl = "xla"
    if impl == "xla":
        return grouped_matmul_xla(lhs, rhs, group_sizes, rhs_scales)
    if rhs_scales is not None:
        # int8 weights: inference-only, no VJP wrapper
        return _gmm_pallas_raw(
            lhs, rhs, group_sizes.astype(jnp.int32), rhs_scales, tm, tn
        )
    return _gmm_pallas(
        lhs, rhs, group_sizes.astype(jnp.int32), tm, tn
    )
