"""Pallas TPU flash attention.

Replaces the reference's dynloaded flash-attn CUDA kernels
(ref: python/paddle/nn/functional/flash_attention.py:242,
phi/backends/dynload/flashattn.cc) with a TPU-native Pallas kernel pair:
online-softmax forward saving per-row logsumexp, blocked backward
recomputing probabilities (no s×s materialization in HBM either way).

Layout contract matches the public API: q/k/v are [batch, seq, heads,
head_dim]; the kernel operates in [batch*heads, seq, head_dim].

Grid: (bh, q_blocks, k_blocks) with the k dimension innermost/"arbitrary"
so the scratch carry (running max / sum / accumulator) is valid across the
sequential k sweep. Causal blocks above the diagonal are skipped via
pl.when.

On non-TPU backends the kernels run in interpreter mode so the numerics
are testable on the 8-device CPU mesh (conftest).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._compat import pl_call

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30


# ---------------------------------------------------------------- forward
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale, causal, block_q, block_k, seq_k):
    kb = pl.program_id(2)
    qb = pl.program_id(1)
    nk = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _visit():
        q = q_ref[0].astype(jnp.float32)  # [bq, d]
        k = k_ref[0].astype(jnp.float32)  # [bk, d]
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [bq, bk]

        if causal:
            qi = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            kj = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(qi >= kj, s, NEG_INF)

        # m/l scratches are lane-replicated [bq, 128] (TPU tile shape);
        # column 0 is authoritative
        m_prev = m_scr[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_scr[:, :1] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        # skip blocks strictly above the diagonal
        @pl.when(kb * block_k <= qb * block_q + (block_q - 1))
        def _():
            _visit()
    else:
        _visit()

    @pl.when(kb == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        # lse stored sublane-replicated [8, block_q] (TPU block rule:
        # trailing block dims divisible by (8, 128))
        lse = (m_scr[:, :1] + jnp.log(l_safe)).reshape(1, -1)
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _flash_fwd(q, k, v, scale, causal, block_q, block_k):
    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    grid = (bh, pl.cdiv(sq, block_q), pl.cdiv(sk, block_k))

    out, lse = pl_call(
        functools.partial(
            _fwd_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, seq_k=sk,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 8, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((bh, 8, sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        dimension_semantics=("parallel", "parallel", "arbitrary"),
    )(q, k, v)
    return out, lse


# --------------------------------------------------------------- backward
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   acc_scr, *, scale, causal, block_q, block_k):
    kb = pl.program_id(2)
    qb = pl.program_id(1)
    nk = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _visit():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0].reshape(block_q, 1)
        delta = delta_ref[0, 0].reshape(block_q, 1)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            qi = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            kj = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(qi >= kj, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * scale
        acc_scr[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        @pl.when(kb * block_k <= qb * block_q + (block_q - 1))
        def _():
            _visit()
    else:
        _visit()

    @pl.when(kb == nk - 1)
    def _fin():
        dq_ref[0] = acc_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, scale, causal,
                    block_q, block_k):
    qb = pl.program_id(2)
    kb = pl.program_id(1)
    nq = pl.num_programs(2)

    @pl.when(qb == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _visit():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0].reshape(block_q, 1)
        delta = delta_ref[0, 0].reshape(block_q, 1)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            qi = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            kj = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(qi >= kj, s, NEG_INF)
        p = jnp.exp(s - lse)  # [bq, bk]
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        # this k block only sees q blocks at or below the diagonal
        @pl.when(qb * block_q + (block_q - 1) >= kb * block_k)
        def _():
            _visit()
    else:
        _visit()

    @pl.when(qb == nq - 1)
    def _fin():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, out, lse, do, scale, causal, block_q, block_k):
    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    delta_row = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )  # [bh, sq]
    # sublane-replicated like lse (TPU block tiling rule)
    delta = jnp.broadcast_to(delta_row[:, None, :], (bh, 8, sq))

    dq = pl_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
        ),
        grid=(bh, pl.cdiv(sq, block_q), pl.cdiv(sk, block_k)),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 8, block_q), lambda b, i, j: (b, 0, i)),
            pl.BlockSpec((1, 8, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        dimension_semantics=("parallel", "parallel", "arbitrary"),
    )(q, k, v, do, lse, delta)

    dk, dv = pl_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
        ),
        grid=(bh, pl.cdiv(sk, block_k), pl.cdiv(sq, block_q)),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, 8, block_q), lambda b, j, i: (b, 0, i)),
            pl.BlockSpec((1, 8, block_q), lambda b, j, i: (b, 0, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        dimension_semantics=("parallel", "parallel", "arbitrary"),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ------------------------------------------------------------- public op
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_core(q, k, v, scale, causal, block_q, block_k):
    out, _ = _flash_fwd(q, k, v, scale, causal, block_q, block_k)
    return out


def _flash_core_fwd(q, k, v, scale, causal, block_q, block_k):
    out, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k)
    return out, (q, k, v, out, lse)


def _flash_core_bwd(scale, causal, block_q, block_k, res, do):
    q, k, v, out, lse = res
    dq, dk, dv = _flash_bwd(
        q, k, v, out, lse, do, scale, causal, block_q, block_k
    )
    return dq, dk, dv


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention(q, k, v, *, causal=True, scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    """q/k/v: [batch, seq, heads, head_dim] -> same-shape output.

    Requirements: no attention mask (causal flag instead), no dropout —
    callers fall back to the math sdpa otherwise (nn_ops dispatch)."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    # The kernel has no padding mask for partial tail blocks; out-of-range
    # rows/cols would silently attend to block padding.
    if sq % min(int(block_q), sq) or sk % min(int(block_k), sk):
        raise ValueError(
            f"flash_attention requires seq lengths divisible by the block "
            f"sizes: got sq={sq}, sk={sk} with block_q={block_q}, "
            f"block_k={block_k}; pad the sequence or use the math sdpa"
        )
    if scale is None:
        scale = 1.0 / (d ** 0.5)

    def _merge(x):
        return (
            jnp.swapaxes(x, 1, 2).reshape(b * h, x.shape[1], d)
        )

    qm, km, vm = _merge(q), _merge(k), _merge(v)
    out = _flash_core(qm, km, vm, float(scale), bool(causal),
                      int(block_q), int(block_k))
    return jnp.swapaxes(out.reshape(b, h, sq, d), 1, 2)
