"""Pallas TPU paged (block-table) KV-cache attention for incremental decode.

The reference serves long-context decode through a paged KV cache: physical
cache pages indexed per-sequence by a block table
(ref: paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu,
python/paddle/incubate/nn/functional/block_multihead_attention.py — the CUDA
kernel walks `block_tables [bsz, block_num_per_seq]` into
`key_cache [max_block_num, num_head, block_size, head_size]`).

TPU-native form: one query token per sequence ([batch, heads, head_dim]),
pages gathered through a scalar-prefetched block table so the page index
feeds the BlockSpec index_map before the grid step runs (Pallas TPU's
analogue of the CUDA kernel's pointer chase), online softmax across the
page sweep. GQA folds query heads into per-kv-head groups so the MXU sees
a [group, page_size] matmul per page instead of a scalar loop.

Layout:
  q            [batch, num_q_heads, head_dim]
  k_pages      [num_kv_heads, num_pages, page_size, head_dim]
  v_pages      [num_kv_heads, num_pages, page_size, head_dim]
  block_tables [batch, pages_per_seq] int32  (logical page i of seq b ->
               physical page block_tables[b, i])
  lengths      [batch] int32  (tokens currently in the cache per sequence)

Quantized (int8) pages: ``k_pages``/``v_pages`` may instead be a
``(pages int8, scales float32 [num_kv_heads, num_pages, page_size])``
pair — one scale per cached token per kv head (quantize-on-write, see
``update_pages``); both the Pallas kernel and the XLA reference
dequantize in-attention (``k = int8 * scale``), so the int8 cache never
materializes a dense float copy.

A sequence with ``lengths[b] == 0`` returns exact zeros (nothing to
attend over) on BOTH paths — serving's inactive-slot convention.

On non-TPU backends the kernel runs under the Pallas interpreter
(``_compat.pl_call``) so numerics are testable on the CPU mesh.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._compat import pl_call

NEG_INF = -1e30


def _split_quant(pages):
    """(pages, scales) for a quantized pair, (pages, None) otherwise."""
    if isinstance(pages, (tuple, list)):
        return pages[0], pages[1]
    return pages, None


def quantize_tokens(kv):
    """Per-token-per-head absmax int8 quantization of new cache entries.

    kv: [..., d] float -> (q int8 [..., d], scale float32 [...]) with
    ``kv ≈ q * scale[..., None]``. The scale floor keeps all-zero tokens
    exact (q == 0, scale == 1e-8)."""
    absmax = jnp.max(jnp.abs(kv.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(
        jnp.round(kv.astype(jnp.float32) / scale[..., None]), -127, 127
    ).astype(jnp.int8)
    return q, scale


def _decode_kernel(lengths_ref, tables_ref, q_ref, k_ref, v_ref, o_ref,
                   m_scr, l_scr, acc_scr, *, scale, page_size):
    b = pl.program_id(0)
    page = pl.program_id(2)
    n_pages = pl.num_programs(2)
    length = lengths_ref[b]

    @pl.when(page == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(page * page_size < length)
    def _visit():
        q = q_ref[0, 0].astype(jnp.float32)   # [group_pad, d]
        k = k_ref[0, 0].astype(jnp.float32)   # [page_size, d]
        v = v_ref[0, 0].astype(jnp.float32)
        _online_softmax_step(
            q, k, v, m_scr, l_scr, acc_scr,
            scale=scale, page_size=page_size, page=page, length=length,
        )

    @pl.when(page == n_pages - 1)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)


def _decode_kernel_quant(lengths_ref, tables_ref, q_ref, k_ref, v_ref,
                         ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr, *,
                         scale, page_size):
    """Int8 variant: dequantize the page in-kernel from its per-token
    scales before the online-softmax step."""
    b = pl.program_id(0)
    page = pl.program_id(2)
    n_pages = pl.num_programs(2)
    length = lengths_ref[b]

    @pl.when(page == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(page * page_size < length)
    def _visit():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32) * ks_ref[0, 0][:, None]
        v = v_ref[0, 0].astype(jnp.float32) * vs_ref[0, 0][:, None]
        _online_softmax_step(
            q, k, v, m_scr, l_scr, acc_scr,
            scale=scale, page_size=page_size, page=page, length=length,
        )

    @pl.when(page == n_pages - 1)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)


def _online_softmax_step(q, k, v, m_scr, l_scr, acc_scr, *, scale,
                         page_size, page, length):
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale  # [group_pad, page_size]

    # mask cache slots at/after the current length (unwritten tail of
    # the last partially-filled page)
    pos = page * page_size + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, 1
    )
    s = jnp.where(pos < length, s, NEG_INF)

    m_prev = m_scr[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)
    l_scr[:] = jnp.broadcast_to(
        l_scr[:, :1] * alpha + jnp.sum(p, axis=1, keepdims=True),
        l_scr.shape,
    )
    acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)


def paged_attention(q, k_pages, v_pages, block_tables, lengths, *,
                    scale=None):
    """Decode-mode paged attention. Returns [batch, num_q_heads, head_dim].

    GQA: num_q_heads must be a multiple of num_kv_heads; query heads are
    grouped per kv head inside the kernel. ``k_pages``/``v_pages`` may be
    int8 ``(pages, scales)`` pairs (module docstring)."""
    k_pages, k_scales = _split_quant(k_pages)
    v_pages, v_scales = _split_quant(v_pages)
    quant = k_scales is not None
    batch, n_q_heads, d = q.shape
    n_kv_heads, n_pages_total, page_size, _ = k_pages.shape
    pages_per_seq = block_tables.shape[1]
    if n_q_heads % n_kv_heads:
        raise ValueError(
            f"num_q_heads ({n_q_heads}) must be divisible by num_kv_heads "
            f"({n_kv_heads})"
        )
    group = n_q_heads // n_kv_heads
    if scale is None:
        scale = 1.0 / (d ** 0.5)

    # pad the per-kv-head query group up to the fp32 sublane tile (8) so
    # scratch/block shapes stay tileable; padded rows are sliced off after
    group_pad = max(8, group)
    qg = q.reshape(batch, n_kv_heads, group, d)
    if group_pad != group:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, group_pad - group), (0, 0)))

    grid = (batch, n_kv_heads, pages_per_seq)

    def q_map(b, h, i, lens, tabs):
        return (b, h, 0, 0)

    def kv_map(b, h, i, lens, tabs):
        return (h, tabs[b, i], 0, 0)

    def sc_map(b, h, i, lens, tabs):
        return (h, tabs[b, i], 0)

    in_specs = [
        pl.BlockSpec((1, 1, group_pad, d), q_map),
        pl.BlockSpec((1, 1, page_size, d), kv_map),
        pl.BlockSpec((1, 1, page_size, d), kv_map),
    ]
    operands = [qg, k_pages, v_pages]
    if quant:
        kernel = _decode_kernel_quant
        in_specs += [
            pl.BlockSpec((1, 1, page_size), sc_map),
            pl.BlockSpec((1, 1, page_size), sc_map),
        ]
        operands += [k_scales, v_scales]
    else:
        kernel = _decode_kernel

    out = pl_call(
        functools.partial(
            kernel, scale=float(scale), page_size=page_size,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, 1, group_pad, d), q_map),
            scratch_shapes=[
                pltpu.VMEM((group_pad, 128), jnp.float32),
                pltpu.VMEM((group_pad, 128), jnp.float32),
                pltpu.VMEM((group_pad, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct(
            (batch, n_kv_heads, group_pad, d), q.dtype
        ),
        dimension_semantics=("parallel", "parallel", "arbitrary"),
    )(lengths.astype(jnp.int32), block_tables.astype(jnp.int32),
      *operands)

    return out[:, :, :group, :].reshape(batch, n_q_heads, d)


def paged_attention_xla(q, k_pages, v_pages, block_tables, lengths, *,
                        scale=None):
    """Pure-XLA reference of the same contract (gather + masked softmax).
    Used by tests as the numeric oracle and as the fallback when the
    Pallas path is disabled. Accepts the same int8 ``(pages, scales)``
    pairs (dequantized after the gather, before the softmax)."""
    k_pages, k_scales = _split_quant(k_pages)
    v_pages, v_scales = _split_quant(v_pages)
    batch, n_q_heads, d = q.shape
    n_kv_heads, _, page_size, _ = k_pages.shape
    pages_per_seq = block_tables.shape[1]
    group = n_q_heads // n_kv_heads
    if scale is None:
        scale = 1.0 / (d ** 0.5)

    # gather logical caches: [batch, n_kv_heads, pages_per_seq*page_size, d]
    k = jnp.swapaxes(k_pages[:, block_tables], 0, 1)
    v = jnp.swapaxes(v_pages[:, block_tables], 0, 1)
    k = k.reshape(batch, n_kv_heads, pages_per_seq * page_size, d)
    v = v.reshape(batch, n_kv_heads, pages_per_seq * page_size, d)
    if k_scales is not None:
        ks = jnp.swapaxes(k_scales[:, block_tables], 0, 1)
        vs = jnp.swapaxes(v_scales[:, block_tables], 0, 1)
        k = k.astype(jnp.float32) * ks.reshape(
            batch, n_kv_heads, -1
        )[..., None]
        v = v.astype(jnp.float32) * vs.reshape(
            batch, n_kv_heads, -1
        )[..., None]

    qg = q.reshape(batch, n_kv_heads, group, d).astype(jnp.float32)
    s = jnp.einsum("bhgd,bhkd->bhgk", qg, k.astype(jnp.float32)) * scale
    pos = jnp.arange(pages_per_seq * page_size)
    s = jnp.where(
        pos[None, None, None, :] < lengths[:, None, None, None], s, NEG_INF
    )
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhgk,bhkd->bhgd", p, v.astype(jnp.float32))
    # a length-0 sequence has nothing to attend over: the all-masked
    # softmax is uniform garbage, so pin the row to the Pallas kernel's
    # exact-zero contract (serving never reads inactive slots, but the
    # two paths must agree everywhere)
    out = jnp.where(lengths[:, None, None, None] > 0, out, 0.0)
    return out.reshape(batch, n_q_heads, d).astype(q.dtype)


def update_pages(k_pages, v_pages, k_new, v_new, block_tables, lengths):
    """Write one new token per sequence into its current page slot.

    k_new/v_new: [batch, num_kv_heads, head_dim] — the token at position
    ``lengths[b]`` of sequence b. Returns updated (k_pages, v_pages).
    Scatter form (one dynamic_update_slice per batch via vmap-free scatter)
    so it stages inside a jitted decode step. Sequences already at capacity
    (lengths[b] == pages_per_seq * page_size) are NOT written — their
    scatter row is pushed out of bounds so jax drops it — because the
    gather on block_tables would otherwise clamp to the last page and
    silently overwrite live cache slots; the caller owns capacity policy
    (grow the block table or evict), as in the reference's serving loop.

    With int8 ``(pages, scales)`` pairs the token is quantized on write
    (``quantize_tokens``) and its scale lands in the same slot of the
    scale plane; the page write and the scale write share one routing."""
    kq, k_scales = _split_quant(k_pages)
    vq, v_scales = _split_quant(v_pages)
    page_size = kq.shape[2]
    capacity = block_tables.shape[1] * page_size
    logical_page = jnp.minimum(
        lengths // page_size, block_tables.shape[1] - 1
    )
    slot = lengths % page_size
    phys = jnp.take_along_axis(
        block_tables, logical_page[:, None], axis=1
    )[:, 0]  # [batch]
    # at-capacity rows: point at a nonexistent page so the scatter drops
    phys = jnp.where(lengths < capacity, phys, kq.shape[1])

    # scatter indices: for each (batch, kv_head) write [phys, head, slot]
    n_kv = kq.shape[0]
    heads = jnp.arange(n_kv)
    idx = jnp.stack(
        [
            jnp.broadcast_to(heads[None, :], (phys.shape[0], n_kv)),
            jnp.broadcast_to(phys[:, None], (phys.shape[0], n_kv)),
            jnp.broadcast_to(slot[:, None], (phys.shape[0], n_kv)),
        ],
        axis=-1,
    ).reshape(-1, 3)  # [batch*n_kv, 3]
    k_upd = k_new.reshape(-1, k_new.shape[-1])  # batch-major over kv heads
    v_upd = v_new.reshape(-1, v_new.shape[-1])
    if k_scales is None:
        kq = kq.at[idx[:, 0], idx[:, 1], idx[:, 2]].set(
            k_upd.astype(kq.dtype)
        )
        vq = vq.at[idx[:, 0], idx[:, 1], idx[:, 2]].set(
            v_upd.astype(vq.dtype)
        )
        return kq, vq
    k_q8, k_s = quantize_tokens(k_upd)
    v_q8, v_s = quantize_tokens(v_upd)
    kq = kq.at[idx[:, 0], idx[:, 1], idx[:, 2]].set(k_q8)
    vq = vq.at[idx[:, 0], idx[:, 1], idx[:, 2]].set(v_q8)
    k_scales = k_scales.at[idx[:, 0], idx[:, 1], idx[:, 2]].set(k_s)
    v_scales = v_scales.at[idx[:, 0], idx[:, 1], idx[:, 2]].set(v_s)
    return (kq, k_scales), (vq, v_scales)
