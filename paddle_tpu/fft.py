"""paddle.fft namespace (ref: python/paddle/fft.py re-exporting
python/paddle/tensor/fft.py). All ops lower to the XLA FFT HLO
(ops/impl/fft_ops.py)."""
from .ops import (  # noqa: F401
    fft,
    fft2,
    fftfreq,
    fftn,
    fftshift,
    hfft,
    ifft,
    ifft2,
    ifftn,
    ifftshift,
    ihfft,
    irfft,
    irfft2,
    irfftn,
    rfft,
    rfft2,
    rfftfreq,
    rfftn,
)

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2",
    "fftn", "ifftn", "rfftn", "irfftn",
    "fftshift", "ifftshift", "fftfreq", "rfftfreq",
]
