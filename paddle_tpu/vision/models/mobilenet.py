"""MobileNet v1/v2/v3 (ref: python/paddle/vision/models/mobilenetv1.py,
mobilenetv2.py, mobilenetv3.py — depthwise-separable conv stacks,
inverted residuals, and SE + hardswish variants). pretrained weights are
not downloadable offline — load a state dict via paddle.load.
"""
from __future__ import annotations

from ... import nn

__all__ = [
    "MobileNetV1", "MobileNetV2", "MobileNetV3Small", "MobileNetV3Large",
    "mobilenet_v1", "mobilenet_v2", "mobilenet_v3_small",
    "mobilenet_v3_large",
]


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class ConvBNAct(nn.Sequential):
    def __init__(self, cin, cout, kernel=3, stride=1, groups=1,
                 act=nn.ReLU6, dilation=1):
        padding = (kernel - 1) // 2 * dilation
        layers = [
            nn.Conv2D(cin, cout, kernel, stride=stride, padding=padding,
                      groups=groups, dilation=dilation, bias_attr=False),
            nn.BatchNorm2D(cout),
        ]
        if act is not None:
            layers.append(act())
        super().__init__(*layers)


# ---- v1: plain depthwise-separable stacks (mobilenetv1.py) ---------------
class _DepthwiseSep(nn.Sequential):
    def __init__(self, cin, cout, stride):
        super().__init__(
            ConvBNAct(cin, cin, 3, stride, groups=cin, act=nn.ReLU),
            ConvBNAct(cin, cout, 1, 1, act=nn.ReLU),
        )


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return max(8, int(ch * scale))

        cfg = [  # (out, stride)
            (64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
            (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2),
            (1024, 1),
        ]
        feats = [ConvBNAct(3, c(32), 3, 2, act=nn.ReLU)]
        cin = c(32)
        for cout, s in cfg:
            feats.append(_DepthwiseSep(cin, c(cout), s))
            cin = c(cout)
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(cin, num_classes)
        self._out_ch = cin

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ... import ops as F

            x = self.fc(F.flatten(x, 1))
        return x


# ---- v2: inverted residual with linear bottleneck (mobilenetv2.py) -------
class InvertedResidual(nn.Layer):
    def __init__(self, cin, cout, stride, expand_ratio):
        super().__init__()
        hidden = int(round(cin * expand_ratio))
        self.use_res = stride == 1 and cin == cout
        layers = []
        if expand_ratio != 1:
            layers.append(ConvBNAct(cin, hidden, 1))
        layers += [
            ConvBNAct(hidden, hidden, 3, stride, groups=hidden),
            # linear bottleneck: no activation after projection
            nn.Conv2D(hidden, cout, 1, bias_attr=False),
            nn.BatchNorm2D(cout),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [  # t, c, n, s
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
        ]
        cin = _make_divisible(32 * scale)
        feats = [ConvBNAct(3, cin, 3, 2)]
        for t, c, n, s in cfg:
            cout = _make_divisible(c * scale)
            for i in range(n):
                feats.append(
                    InvertedResidual(cin, cout, s if i == 0 else 1, t)
                )
                cin = cout
        last = _make_divisible(1280 * max(1.0, scale))
        feats.append(ConvBNAct(cin, last, 1))
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.2), nn.Linear(last, num_classes)
            )
        self._out_ch = last

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ... import ops as F

            x = self.classifier(F.flatten(x, 1))
        return x


# ---- v3: SE + hardswish search cells (mobilenetv3.py) --------------------
class SqueezeExcite(nn.Layer):
    def __init__(self, ch, squeeze=4):
        super().__init__()
        mid = _make_divisible(ch // squeeze)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(ch, mid, 1)
        self.relu = nn.ReLU()
        self.fc2 = nn.Conv2D(mid, ch, 1)
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _V3Block(nn.Layer):
    def __init__(self, cin, mid, cout, kernel, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and cin == cout
        layers = []
        if mid != cin:
            layers.append(ConvBNAct(cin, mid, 1, act=act))
        layers.append(ConvBNAct(mid, mid, kernel, stride, groups=mid,
                                act=act))
        if use_se:
            layers.append(SqueezeExcite(mid))
        layers += [
            nn.Conv2D(mid, cout, 1, bias_attr=False),
            nn.BatchNorm2D(cout),
        ]
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


_V3_LARGE = [  # kernel, mid, out, se, act, stride
    (3, 16, 16, False, nn.ReLU, 1),
    (3, 64, 24, False, nn.ReLU, 2),
    (3, 72, 24, False, nn.ReLU, 1),
    (5, 72, 40, True, nn.ReLU, 2),
    (5, 120, 40, True, nn.ReLU, 1),
    (5, 120, 40, True, nn.ReLU, 1),
    (3, 240, 80, False, nn.Hardswish, 2),
    (3, 200, 80, False, nn.Hardswish, 1),
    (3, 184, 80, False, nn.Hardswish, 1),
    (3, 184, 80, False, nn.Hardswish, 1),
    (3, 480, 112, True, nn.Hardswish, 1),
    (3, 672, 112, True, nn.Hardswish, 1),
    (5, 672, 160, True, nn.Hardswish, 2),
    (5, 960, 160, True, nn.Hardswish, 1),
    (5, 960, 160, True, nn.Hardswish, 1),
]
_V3_SMALL = [
    (3, 16, 16, True, nn.ReLU, 2),
    (3, 72, 24, False, nn.ReLU, 2),
    (3, 88, 24, False, nn.ReLU, 1),
    (5, 96, 40, True, nn.Hardswish, 2),
    (5, 240, 40, True, nn.Hardswish, 1),
    (5, 240, 40, True, nn.Hardswish, 1),
    (5, 120, 48, True, nn.Hardswish, 1),
    (5, 144, 48, True, nn.Hardswish, 1),
    (5, 288, 96, True, nn.Hardswish, 2),
    (5, 576, 96, True, nn.Hardswish, 1),
    (5, 576, 96, True, nn.Hardswish, 1),
]


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_conv, last_fc, scale=1.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return _make_divisible(ch * scale)

        cin = c(16)
        feats = [ConvBNAct(3, cin, 3, 2, act=nn.Hardswish)]
        for k, mid, cout, se, act, s in cfg:
            feats.append(_V3Block(cin, c(mid), c(cout), k, s, se, act))
            cin = c(cout)
        feats.append(ConvBNAct(cin, c(last_conv), 1, act=nn.Hardswish))
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(c(last_conv), last_fc),
                nn.Hardswish(),
                nn.Dropout(0.2),
                nn.Linear(last_fc, num_classes),
            )
        self._out_ch = c(last_conv)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ... import ops as F

            x = self.classifier(F.flatten(x, 1))
        return x


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_SMALL, 576, 1024, scale, num_classes,
                         with_pool)


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_LARGE, 960, 1280, scale, num_classes,
                         with_pool)


def _no_pretrained(pretrained):
    if pretrained:
        raise ValueError(
            "pretrained weights are unavailable offline; load a state "
            "dict with model.set_state_dict(paddle.load(path))"
        )


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    _no_pretrained(pretrained)
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    _no_pretrained(pretrained)
    return MobileNetV2(scale=scale, **kwargs)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    _no_pretrained(pretrained)
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    _no_pretrained(pretrained)
    return MobileNetV3Large(scale=scale, **kwargs)
