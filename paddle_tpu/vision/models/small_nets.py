"""LeNet / AlexNet / VGG / SqueezeNet (ref:
python/paddle/vision/models/{lenet,alexnet,vgg,squeezenet}.py).
pretrained weights are not downloadable offline — load state dicts via
paddle.load.
"""
from __future__ import annotations

from ... import nn

__all__ = [
    "LeNet", "AlexNet", "VGG", "SqueezeNet",
    "alexnet", "vgg11", "vgg13", "vgg16", "vgg19",
    "squeezenet1_0", "squeezenet1_1",
]


class LeNet(nn.Layer):
    """ref: vision/models/lenet.py — 1x28x28 MNIST topology."""

    def __init__(self, num_classes=10):
        super().__init__()
        self.num_classes = num_classes
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 3, stride=1, padding=1), nn.ReLU(),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(6, 16, 5, stride=1, padding=0), nn.ReLU(),
            nn.MaxPool2D(2, 2),
        )
        if num_classes > 0:
            self.fc = nn.Sequential(
                nn.Linear(400, 120), nn.Linear(120, 84),
                nn.Linear(84, num_classes),
            )

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            from ... import ops as F

            x = self.fc(F.flatten(x, 1))
        return x


class AlexNet(nn.Layer):
    """ref: vision/models/alexnet.py."""

    def __init__(self, num_classes=1000, dropout=0.5):
        super().__init__()
        self.num_classes = num_classes
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, 2),
        )
        self.pool = nn.AdaptiveAvgPool2D((6, 6))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(dropout), nn.Linear(256 * 36, 4096), nn.ReLU(),
                nn.Dropout(dropout), nn.Linear(4096, 4096), nn.ReLU(),
                nn.Linear(4096, num_classes),
            )

    def forward(self, x):
        x = self.pool(self.features(x))
        if self.num_classes > 0:
            from ... import ops as F

            x = self.classifier(F.flatten(x, 1))
        return x


_VGG_CFGS = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
          512, 512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
          "M", 512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512,
          512, 512, "M", 512, 512, 512, 512, "M"],
}


class VGG(nn.Layer):
    """ref: vision/models/vgg.py — VGG(features, num_classes)."""

    def __init__(self, features, num_classes=1000, batch_norm=False,
                 dropout=0.5):
        super().__init__()
        if isinstance(features, str):
            features = make_vgg_features(_VGG_CFGS[features], batch_norm)
        self.features = features
        self.num_classes = num_classes
        self.pool = nn.AdaptiveAvgPool2D((7, 7))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(512 * 49, 4096), nn.ReLU(), nn.Dropout(dropout),
                nn.Linear(4096, 4096), nn.ReLU(), nn.Dropout(dropout),
                nn.Linear(4096, num_classes),
            )

    def forward(self, x):
        x = self.pool(self.features(x))
        if self.num_classes > 0:
            from ... import ops as F

            x = self.classifier(F.flatten(x, 1))
        return x


def make_vgg_features(cfg, batch_norm=False):
    from ...nn import initializer as I
    from ...nn.parameter import ParamAttr

    layers, cin = [], 3
    for v in cfg:
        if v == "M":
            layers.append(nn.MaxPool2D(2, 2))
            continue
        # Kaiming fan-out: 13 stacked ReLU convs vanish under the
        # default Xavier scaling (activations decay ~15x by the last
        # block; measured r5) — the reference/torchvision VGG recipe
        layers.append(nn.Conv2D(
            cin, v, 3, padding=1,
            weight_attr=ParamAttr(initializer=I.KaimingNormal(
                nonlinearity="relu")),
        ))
        if batch_norm:
            layers.append(nn.BatchNorm2D(v))
        layers.append(nn.ReLU())
        cin = v
    return nn.Sequential(*layers)


class _Fire(nn.Layer):
    def __init__(self, cin, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Sequential(nn.Conv2D(cin, squeeze, 1), nn.ReLU())
        self.expand1 = nn.Sequential(nn.Conv2D(squeeze, e1, 1), nn.ReLU())
        self.expand3 = nn.Sequential(
            nn.Conv2D(squeeze, e3, 3, padding=1), nn.ReLU()
        )

    def forward(self, x):
        from ... import ops as F

        s = self.squeeze(x)
        return F.concat([self.expand1(s), self.expand3(s)], axis=1)


class SqueezeNet(nn.Layer):
    """ref: vision/models/squeezenet.py — version '1.0'/'1.1'."""

    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, 2),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128), nn.MaxPool2D(3, 2),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, 2), _Fire(512, 64, 256, 256),
            )
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, 2),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, 2),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, 2),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256),
            )
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.5), nn.Conv2D(512, num_classes, 1), nn.ReLU(),
            )
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)

    def forward(self, x):
        from ... import ops as F

        x = self.features(x)
        if self.num_classes > 0:
            x = self.classifier(x)
        if self.with_pool:
            x = self.pool(x)
        return F.flatten(x, 1)


def _no_pretrained(pretrained):
    if pretrained:
        raise ValueError(
            "pretrained weights are unavailable offline; load a state "
            "dict with model.set_state_dict(paddle.load(path))"
        )


def alexnet(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return AlexNet(**kwargs)


def vgg11(pretrained=False, batch_norm=False, **kwargs):
    _no_pretrained(pretrained)
    return VGG("A", batch_norm=batch_norm, **kwargs)


def vgg13(pretrained=False, batch_norm=False, **kwargs):
    _no_pretrained(pretrained)
    return VGG("B", batch_norm=batch_norm, **kwargs)


def vgg16(pretrained=False, batch_norm=False, **kwargs):
    _no_pretrained(pretrained)
    return VGG("D", batch_norm=batch_norm, **kwargs)


def vgg19(pretrained=False, batch_norm=False, **kwargs):
    _no_pretrained(pretrained)
    return VGG("E", batch_norm=batch_norm, **kwargs)


def squeezenet1_0(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return SqueezeNet("1.1", **kwargs)
