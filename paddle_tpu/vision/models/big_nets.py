"""DenseNet / GoogLeNet / ShuffleNetV2 / InceptionV3 (ref:
python/paddle/vision/models/{densenet,googlenet,shufflenetv2,
inceptionv3}.py). pretrained weights are not downloadable offline —
load state dicts via paddle.load.
"""
from __future__ import annotations

from ... import nn

__all__ = [
    "DenseNet", "GoogLeNet", "ShuffleNetV2", "InceptionV3",
    "densenet121", "densenet161", "densenet169", "densenet201",
    "densenet264", "googlenet", "shufflenet_v2_x0_25",
    "shufflenet_v2_x0_5", "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
    "shufflenet_v2_x2_0", "inception_v3",
]


def _flatten(x):
    from ... import ops as F

    return F.flatten(x, 1)


# ---- DenseNet -------------------------------------------------------------
class _DenseLayer(nn.Layer):
    def __init__(self, cin, growth, bn_size, dropout):
        super().__init__()
        self.norm1 = nn.BatchNorm2D(cin)
        self.relu = nn.ReLU()
        self.conv1 = nn.Conv2D(cin, bn_size * growth, 1, bias_attr=False)
        self.norm2 = nn.BatchNorm2D(bn_size * growth)
        self.conv2 = nn.Conv2D(bn_size * growth, growth, 3, padding=1,
                               bias_attr=False)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        from ... import ops as F

        out = self.conv1(self.relu(self.norm1(x)))
        out = self.conv2(self.relu(self.norm2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        return F.concat([x, out], axis=1)


class _Transition(nn.Sequential):
    def __init__(self, cin, cout):
        super().__init__(
            nn.BatchNorm2D(cin), nn.ReLU(),
            nn.Conv2D(cin, cout, 1, bias_attr=False),
            nn.AvgPool2D(2, 2),
        )


_DENSE_CFG = {
    121: (64, 32, [6, 12, 24, 16]),
    161: (96, 48, [6, 12, 36, 24]),
    169: (64, 32, [6, 12, 32, 32]),
    201: (64, 32, [6, 12, 48, 32]),
    264: (64, 32, [6, 12, 64, 48]),
}


class DenseNet(nn.Layer):
    """ref: vision/models/densenet.py DenseNet(layers=121, ...)."""

    def __init__(self, layers=121, bn_size=4, dropout=0.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        num_init, growth, block_cfg = _DENSE_CFG[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool
        feats = [
            nn.Conv2D(3, num_init, 7, stride=2, padding=3,
                      bias_attr=False),
            nn.BatchNorm2D(num_init), nn.ReLU(),
            nn.MaxPool2D(3, 2, padding=1),
        ]
        ch = num_init
        for bi, n in enumerate(block_cfg):
            for _ in range(n):
                feats.append(_DenseLayer(ch, growth, bn_size, dropout))
                ch += growth
            if bi != len(block_cfg) - 1:
                feats.append(_Transition(ch, ch // 2))
                ch //= 2
        feats += [nn.BatchNorm2D(ch), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(_flatten(x))
        return x


# ---- GoogLeNet ------------------------------------------------------------
class _Inception(nn.Layer):
    def __init__(self, cin, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = nn.Sequential(nn.Conv2D(cin, c1, 1), nn.ReLU())
        self.b2 = nn.Sequential(
            nn.Conv2D(cin, c3r, 1), nn.ReLU(),
            nn.Conv2D(c3r, c3, 3, padding=1), nn.ReLU(),
        )
        self.b3 = nn.Sequential(
            nn.Conv2D(cin, c5r, 1), nn.ReLU(),
            nn.Conv2D(c5r, c5, 5, padding=2), nn.ReLU(),
        )
        self.b4 = nn.Sequential(
            nn.MaxPool2D(3, 1, padding=1),
            nn.Conv2D(cin, proj, 1), nn.ReLU(),
        )

    def forward(self, x):
        from ... import ops as F

        return F.concat(
            [self.b1(x), self.b2(x), self.b3(x), self.b4(x)], axis=1
        )


class GoogLeNet(nn.Layer):
    """ref: vision/models/googlenet.py — returns (out, aux1, aux2) in
    train mode like the reference's GoogLeNet.forward."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            nn.Conv2D(3, 64, 7, stride=2, padding=3), nn.ReLU(),
            nn.MaxPool2D(3, 2, padding=1),
            nn.Conv2D(64, 64, 1), nn.ReLU(),
            nn.Conv2D(64, 192, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, 2, padding=1),
        )
        self.i3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.i3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, 2, padding=1)
        self.i4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.i4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.i4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.i4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.i4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, 2, padding=1)
        self.i5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.i5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.pool5 = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.4)
            self.fc = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.i3b(self.i3a(x)))
        x = self.i4e(self.i4d(self.i4c(self.i4b(self.i4a(x)))))
        x = self.pool4(x)
        x = self.i5b(self.i5a(x))
        if self.with_pool:
            x = self.pool5(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(_flatten(x)))
        return x


# ---- ShuffleNetV2 ---------------------------------------------------------
def _channel_shuffle(x, groups):
    from ... import ops as F

    n, c, h, w = x.shape
    x = F.reshape(x, [n, groups, c // groups, h, w])
    x = F.transpose(x, perm=[0, 2, 1, 3, 4])
    return F.reshape(x, [n, c, h, w])


class _ShuffleUnit(nn.Layer):
    def __init__(self, cin, cout, stride):
        super().__init__()
        self.stride = stride
        branch = cout // 2
        if stride == 2:
            self.b1 = nn.Sequential(
                nn.Conv2D(cin, cin, 3, stride=2, padding=1, groups=cin,
                          bias_attr=False),
                nn.BatchNorm2D(cin),
                nn.Conv2D(cin, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), nn.ReLU(),
            )
            c2in = cin
        else:
            self.b1 = None
            c2in = cin // 2
        self.b2 = nn.Sequential(
            nn.Conv2D(c2in, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch), nn.ReLU(),
            nn.Conv2D(branch, branch, 3, stride=stride, padding=1,
                      groups=branch, bias_attr=False),
            nn.BatchNorm2D(branch),
            nn.Conv2D(branch, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch), nn.ReLU(),
        )

    def forward(self, x):
        from ... import ops as F

        if self.stride == 2:
            out = F.concat([self.b1(x), self.b2(x)], axis=1)
        else:
            half = x.shape[1] // 2
            x1, x2 = x[:, :half], x[:, half:]
            out = F.concat([x1, self.b2(x2)], axis=1)
        return _channel_shuffle(out, 2)


_SHUFFLE_CH = {
    0.25: [24, 24, 48, 96, 512],
    0.5: [24, 48, 96, 192, 1024],
    1.0: [24, 116, 232, 464, 1024],
    1.5: [24, 176, 352, 704, 1024],
    2.0: [24, 244, 488, 976, 2048],
}


class ShuffleNetV2(nn.Layer):
    """ref: vision/models/shufflenetv2.py ShuffleNetV2(scale, ...)."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        ch = _SHUFFLE_CH[scale]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            nn.Conv2D(3, ch[0], 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(ch[0]), nn.ReLU(),
            nn.MaxPool2D(3, 2, padding=1),
        )
        stages = []
        cin = ch[0]
        for si, repeat in enumerate([4, 8, 4]):
            cout = ch[si + 1]
            stages.append(_ShuffleUnit(cin, cout, 2))
            for _ in range(repeat - 1):
                stages.append(_ShuffleUnit(cout, cout, 1))
            cin = cout
        self.stages = nn.Sequential(*stages)
        self.final = nn.Sequential(
            nn.Conv2D(cin, ch[4], 1, bias_attr=False),
            nn.BatchNorm2D(ch[4]), nn.ReLU(),
        )
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(ch[4], num_classes)

    def forward(self, x):
        x = self.final(self.stages(self.stem(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(_flatten(x))
        return x


# ---- InceptionV3 ----------------------------------------------------------
class _BasicConv(nn.Sequential):
    def __init__(self, cin, cout, kernel, **kw):
        super().__init__(
            nn.Conv2D(cin, cout, kernel, bias_attr=False, **kw),
            nn.BatchNorm2D(cout), nn.ReLU(),
        )


class _InceptionA(nn.Layer):
    def __init__(self, cin, pool_ch):
        super().__init__()
        self.b1 = _BasicConv(cin, 64, 1)
        self.b2 = nn.Sequential(_BasicConv(cin, 48, 1),
                                _BasicConv(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(_BasicConv(cin, 64, 1),
                                _BasicConv(64, 96, 3, padding=1),
                                _BasicConv(96, 96, 3, padding=1))
        self.b4 = nn.Sequential(nn.AvgPool2D(3, 1, padding=1),
                                _BasicConv(cin, pool_ch, 1))

    def forward(self, x):
        from ... import ops as F

        return F.concat(
            [self.b1(x), self.b2(x), self.b3(x), self.b4(x)], axis=1
        )


class _InceptionB(nn.Layer):
    def __init__(self, cin):
        super().__init__()
        self.b1 = _BasicConv(cin, 384, 3, stride=2)
        self.b2 = nn.Sequential(_BasicConv(cin, 64, 1),
                                _BasicConv(64, 96, 3, padding=1),
                                _BasicConv(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, 2)

    def forward(self, x):
        from ... import ops as F

        return F.concat([self.b1(x), self.b2(x), self.pool(x)], axis=1)


class _InceptionC(nn.Layer):
    def __init__(self, cin, c7):
        super().__init__()
        self.b1 = _BasicConv(cin, 192, 1)
        self.b2 = nn.Sequential(
            _BasicConv(cin, c7, 1),
            _BasicConv(c7, c7, (1, 7), padding=(0, 3)),
            _BasicConv(c7, 192, (7, 1), padding=(3, 0)),
        )
        self.b3 = nn.Sequential(
            _BasicConv(cin, c7, 1),
            _BasicConv(c7, c7, (7, 1), padding=(3, 0)),
            _BasicConv(c7, c7, (1, 7), padding=(0, 3)),
            _BasicConv(c7, c7, (7, 1), padding=(3, 0)),
            _BasicConv(c7, 192, (1, 7), padding=(0, 3)),
        )
        self.b4 = nn.Sequential(nn.AvgPool2D(3, 1, padding=1),
                                _BasicConv(cin, 192, 1))

    def forward(self, x):
        from ... import ops as F

        return F.concat(
            [self.b1(x), self.b2(x), self.b3(x), self.b4(x)], axis=1
        )


class _InceptionD(nn.Layer):
    def __init__(self, cin):
        super().__init__()
        self.b1 = nn.Sequential(_BasicConv(cin, 192, 1),
                                _BasicConv(192, 320, 3, stride=2))
        self.b2 = nn.Sequential(
            _BasicConv(cin, 192, 1),
            _BasicConv(192, 192, (1, 7), padding=(0, 3)),
            _BasicConv(192, 192, (7, 1), padding=(3, 0)),
            _BasicConv(192, 192, 3, stride=2),
        )
        self.pool = nn.MaxPool2D(3, 2)

    def forward(self, x):
        from ... import ops as F

        return F.concat([self.b1(x), self.b2(x), self.pool(x)], axis=1)


class _InceptionE(nn.Layer):
    def __init__(self, cin):
        super().__init__()
        self.b1 = _BasicConv(cin, 320, 1)
        self.b2_stem = _BasicConv(cin, 384, 1)
        self.b2a = _BasicConv(384, 384, (1, 3), padding=(0, 1))
        self.b2b = _BasicConv(384, 384, (3, 1), padding=(1, 0))
        self.b3_stem = nn.Sequential(_BasicConv(cin, 448, 1),
                                     _BasicConv(448, 384, 3, padding=1))
        self.b3a = _BasicConv(384, 384, (1, 3), padding=(0, 1))
        self.b3b = _BasicConv(384, 384, (3, 1), padding=(1, 0))
        self.b4 = nn.Sequential(nn.AvgPool2D(3, 1, padding=1),
                                _BasicConv(cin, 192, 1))

    def forward(self, x):
        from ... import ops as F

        b2 = self.b2_stem(x)
        b3 = self.b3_stem(x)
        return F.concat(
            [self.b1(x),
             F.concat([self.b2a(b2), self.b2b(b2)], axis=1),
             F.concat([self.b3a(b3), self.b3b(b3)], axis=1),
             self.b4(x)],
            axis=1,
        )


class InceptionV3(nn.Layer):
    """ref: vision/models/inceptionv3.py InceptionV3(num_classes, ...)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _BasicConv(3, 32, 3, stride=2),
            _BasicConv(32, 32, 3),
            _BasicConv(32, 64, 3, padding=1),
            nn.MaxPool2D(3, 2),
            _BasicConv(64, 80, 1),
            _BasicConv(80, 192, 3),
            nn.MaxPool2D(3, 2),
        )
        self.blocks = nn.Sequential(
            _InceptionA(192, 32), _InceptionA(256, 64),
            _InceptionA(288, 64),
            _InceptionB(288),
            _InceptionC(768, 128), _InceptionC(768, 160),
            _InceptionC(768, 160), _InceptionC(768, 192),
            _InceptionD(768),
            _InceptionE(1280), _InceptionE(2048),
        )
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.5)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(_flatten(x)))
        return x


def _no_pretrained(pretrained):
    if pretrained:
        raise ValueError(
            "pretrained weights are unavailable offline; load a state "
            "dict with model.set_state_dict(paddle.load(path))"
        )


def _densenet(layers):
    def build(pretrained=False, **kwargs):
        _no_pretrained(pretrained)
        return DenseNet(layers=layers, **kwargs)

    build.__name__ = f"densenet{layers}"
    return build


densenet121 = _densenet(121)
densenet161 = _densenet(161)
densenet169 = _densenet(169)
densenet201 = _densenet(201)
densenet264 = _densenet(264)


def googlenet(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return GoogLeNet(**kwargs)


def _shufflenet(scale):
    def build(pretrained=False, **kwargs):
        _no_pretrained(pretrained)
        return ShuffleNetV2(scale=scale, **kwargs)

    build.__name__ = f"shufflenet_v2_x{str(scale).replace('.', '_')}"
    return build


shufflenet_v2_x0_25 = _shufflenet(0.25)
shufflenet_v2_x0_5 = _shufflenet(0.5)
shufflenet_v2_x1_0 = _shufflenet(1.0)
shufflenet_v2_x1_5 = _shufflenet(1.5)
shufflenet_v2_x2_0 = _shufflenet(2.0)


def inception_v3(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return InceptionV3(**kwargs)
