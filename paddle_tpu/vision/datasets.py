"""Vision datasets (ref: python/paddle/vision/datasets/cifar.py, mnist.py).

Zero-egress environment: datasets load from a local archive when present
(same file formats the reference downloads) and otherwise generate a
deterministic synthetic split (`backend="synthetic"` or automatically when
no file is found and allow_synthetic=True) so training pipelines stay
runnable end to end.
"""
from __future__ import annotations

import os
import pickle
import tarfile

import numpy as np

from ..io.dataset import Dataset

__all__ = ["Cifar10", "Cifar100", "MNIST"]


class _SyntheticImages(Dataset):
    def __init__(self, n, shape, num_classes, transform=None, seed=0):
        rng = np.random.RandomState(seed)
        self.labels = (rng.rand(n) * num_classes).astype(np.int64)
        # class-dependent means so models can actually learn
        base = rng.rand(num_classes, *shape).astype(np.float32)
        noise = rng.rand(n, *shape).astype(np.float32) * 0.4
        self.images = (
            (base[self.labels] * 0.6 + noise) * 255.0
        ).astype(np.uint8)
        self.transform = transform

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, int(self.labels[idx])


class Cifar10(Dataset):
    """ref: vision/datasets/cifar.py Cifar10 (python-version archive)."""

    num_classes = 10
    _archive = "cifar-10-python.tar.gz"
    _train_files = [f"data_batch_{i}" for i in range(1, 6)]
    _test_files = ["test_batch"]

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None, allow_synthetic=True,
                 synthetic_size=None):
        assert mode in ("train", "test")
        self.mode = mode
        self.transform = transform
        data_file = data_file or os.path.join(
            os.path.expanduser("~"), ".cache", "paddle", "dataset",
            "cifar", self._archive,
        )
        if backend == "synthetic" or (
            not os.path.exists(data_file) and allow_synthetic
        ):
            n = (synthetic_size if synthetic_size is not None
                 else (1024 if mode == "train" else 256))
            self._syn = _SyntheticImages(
                n, (32, 32, 3), self.num_classes, transform,
                seed=0 if mode == "train" else 1,
            )
            self.images, self.labels = self._syn.images, self._syn.labels
            return
        self._syn = None
        names = self._train_files if mode == "train" else self._test_files
        images, labels = [], []
        with tarfile.open(data_file, "r:gz") as tf:
            for member in tf.getmembers():
                base = os.path.basename(member.name)
                if base in names:
                    d = pickle.load(tf.extractfile(member), encoding="bytes")
                    images.append(d[b"data"])
                    labels.extend(d.get(b"labels", d.get(b"fine_labels")))
        self.images = (
            np.concatenate(images).reshape(-1, 3, 32, 32)
            .transpose(0, 2, 3, 1)
        )
        self.labels = np.asarray(labels, np.int64)

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, int(self.labels[idx])


class Cifar100(Cifar10):
    num_classes = 100
    _archive = "cifar-100-python.tar.gz"
    _train_files = ["train"]
    _test_files = ["test"]


class MNIST(Dataset):
    """ref: vision/datasets/mnist.py (idx-ubyte files or synthetic)."""

    num_classes = 10

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None,
                 allow_synthetic=True, synthetic_size=None):
        assert mode in ("train", "test")
        self.transform = transform
        if (
            backend == "synthetic"
            or image_path is None
            or not os.path.exists(image_path)
        ) and allow_synthetic:
            n = (synthetic_size if synthetic_size is not None
                 else (1024 if mode == "train" else 256))
            self._syn = _SyntheticImages(
                n, (28, 28), self.num_classes, transform,
                seed=2 if mode == "train" else 3,
            )
            self.images, self.labels = self._syn.images, self._syn.labels
            return
        import gzip
        import struct

        opener = gzip.open if image_path.endswith(".gz") else open
        with opener(image_path, "rb") as f:
            _, n, rows, cols = struct.unpack(">IIII", f.read(16))
            self.images = np.frombuffer(
                f.read(), np.uint8
            ).reshape(n, rows, cols)
        with opener(label_path, "rb") as f:
            struct.unpack(">II", f.read(8))
            self.labels = np.frombuffer(f.read(), np.uint8).astype(np.int64)

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, int(self.labels[idx])
