"""Vision transforms (ref: python/paddle/vision/transforms/transforms.py —
the numpy/CHW subset that matters for training pipelines)."""
from __future__ import annotations

import numpy as np

__all__ = [
    "Compose", "ToTensor", "Normalize", "Resize", "CenterCrop",
    "RandomCrop", "RandomHorizontalFlip", "Transpose", "Pad",
]


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor:
    """HWC uint8 [0,255] -> CHW float32 [0,1] (ref transforms ToTensor)."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img)
        if arr.dtype == np.uint8:
            arr = arr.astype(np.float32) / 255.0
        else:
            arr = arr.astype(np.float32)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return arr


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def __call__(self, img):
        img = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            shape = (-1, 1, 1)
        else:
            shape = (1, 1, -1)
        return (img - self.mean.reshape(shape)) / self.std.reshape(shape)


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.asarray(img).transpose(self.order)


class Resize:
    """Nearest/bilinear resize on HWC numpy arrays."""

    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.interpolation = interpolation

    def __call__(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        if (h, w) == (th, tw):
            return arr
        ys = np.linspace(0, h - 1, th)
        xs = np.linspace(0, w - 1, tw)
        if self.interpolation == "nearest":
            return arr[np.round(ys).astype(int)][:, np.round(xs).astype(int)]
        y0 = np.floor(ys).astype(int)
        x0 = np.floor(xs).astype(int)
        y1 = np.minimum(y0 + 1, h - 1)
        x1 = np.minimum(x0 + 1, w - 1)
        wy = (ys - y0)[:, None, None]
        wx = (xs - x0)[None, :, None]
        a = arr.astype(np.float32)
        if a.ndim == 2:
            a = a[:, :, None]
            squeeze = True
        else:
            squeeze = False
        out = (
            a[y0][:, x0] * (1 - wy) * (1 - wx)
            + a[y0][:, x1] * (1 - wy) * wx
            + a[y1][:, x0] * wy * (1 - wx)
            + a[y1][:, x1] * wy * wx
        )
        if arr.dtype == np.uint8:
            out = np.clip(out, 0, 255).astype(np.uint8)
        return out[:, :, 0] if squeeze else out


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = max(0, (h - th) // 2)
        j = max(0, (w - tw) // 2)
        return arr[i : i + th, j : j + tw]


class RandomCrop:
    def __init__(self, size, padding=None, pad_if_needed=False):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img)
        if self.padding:
            p = self.padding
            pads = [(p, p), (p, p)] + [(0, 0)] * (arr.ndim - 2)
            arr = np.pad(arr, pads, mode="constant")
        h, w = arr.shape[:2]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return arr[i : i + th, j : j + tw]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return np.asarray(img)


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def __call__(self, img):
        arr = np.asarray(img)
        p = self.padding
        if isinstance(p, int):
            p = (p, p, p, p)  # l, t, r, b
        pads = [(p[1], p[3]), (p[0], p[2])] + [(0, 0)] * (arr.ndim - 2)
        return np.pad(
            arr, pads, mode=self.padding_mode,
            constant_values=self.fill if self.padding_mode == "constant" else None,
        ) if self.padding_mode == "constant" else np.pad(
            arr, pads, mode=self.padding_mode
        )
