"""paddle.vision.ops — detection/vision operators.

ref: python/paddle/vision/ops.py (nms:1934, roi_align:1705,
roi_pool:1572, box_coder:584, deform_conv2d:766, ConvNormActivation).

TPU-native notes: nms returns dynamically-many indices — inherently a
host-side op (the reference's CUDA kernel also ends in a host copy of
the kept count), so it runs eagerly on concrete tensors. roi_align /
roi_pool are batched bilinear gathers — static shapes, fully jittable.
read_file/decode_jpeg are declared but raise (zero-egress image, no
codec); datasets feed arrays directly.
"""
from __future__ import annotations

import numpy as np

from .. import nn
from ..core.tensor import Tensor

__all__ = [
    "nms", "roi_align", "roi_pool", "box_coder", "deform_conv2d",
    "DeformConv2D", "RoIAlign", "RoIPool", "ConvNormActivation",
    "read_file", "decode_jpeg", "psroi_pool", "PSRoIPool",
]


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Hard NMS over [N, 4] x1y1x2y2 boxes (ref ops.py:1934). Returns
    kept indices sorted by descending score. Dynamic output size makes
    this a host op by nature; inputs must be concrete (eager)."""
    b = np.asarray(boxes.numpy() if isinstance(boxes, Tensor) else boxes)
    n = b.shape[0]
    s = (np.asarray(scores.numpy() if isinstance(scores, Tensor)
                    else scores) if scores is not None
         else np.arange(n, 0, -1, dtype=np.float32))
    cats = (np.asarray(category_idxs.numpy()
                       if isinstance(category_idxs, Tensor)
                       else category_idxs)
            if category_idxs is not None else np.zeros(n, np.int64))

    areas = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    keep = []
    for c in np.unique(cats):
        idx = np.where(cats == c)[0]
        order = idx[np.argsort(-s[idx])]
        while order.size:
            i = order[0]
            keep.append(i)
            if order.size == 1:
                break
            rest = order[1:]
            xx1 = np.maximum(b[i, 0], b[rest, 0])
            yy1 = np.maximum(b[i, 1], b[rest, 1])
            xx2 = np.minimum(b[i, 2], b[rest, 2])
            yy2 = np.minimum(b[i, 3], b[rest, 3])
            inter = np.clip(xx2 - xx1, 0, None) * np.clip(
                yy2 - yy1, 0, None)
            iou = inter / (areas[i] + areas[rest] - inter + 1e-9)
            order = rest[iou <= iou_threshold]
    keep = np.array(sorted(keep, key=lambda i: -s[i]), np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(keep, stop_gradient=True)


def _bilinear_gather(feat, ys, xs):
    """feat [C,H,W]; ys/xs arbitrary same-shape float grids -> [C,*]."""
    import jax.numpy as jnp

    h, w = feat.shape[-2], feat.shape[-1]
    y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, h - 1)
    x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, w - 1)
    y1 = jnp.clip(y0 + 1, 0, h - 1)
    x1 = jnp.clip(x0 + 1, 0, w - 1)
    wy = jnp.clip(ys - y0, 0.0, 1.0)
    wx = jnp.clip(xs - x0, 0.0, 1.0)
    v00 = feat[:, y0, x0]
    v01 = feat[:, y0, x1]
    v10 = feat[:, y1, x0]
    v11 = feat[:, y1, x1]
    return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
            + v10 * wy * (1 - wx) + v11 * wy * wx)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """RoIAlign (ref ops.py:1705): average of bilinear samples per bin.
    x [N,C,H,W]; boxes [R,4]; boxes_num [N] rois per image. Gradients
    flow to x and boxes (the op records on the tape via dispatch).

    TPU-native shape discipline: ONE vmapped gather over all ROIs (no
    per-ROI program growth). The adaptive sampling grid
    (sampling_ratio=-1 -> ceil(roi_size/out_size) per axis, per the
    reference) must be static under jit, so the grid is the per-axis
    MAX over the call's ROIs — small ROIs get at-least-as-dense
    sampling, identical bin averages in the constant-feature limit."""
    import jax

    from ..core import dispatch

    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    bn = np.asarray(boxes_num.numpy() if isinstance(boxes_num, Tensor)
                    else boxes_num)
    img_idx = np.repeat(np.arange(len(bn)), bn)
    off = 0.5 if aligned else 0.0
    bx_host = np.asarray(
        jax.device_get(boxes._data if isinstance(boxes, Tensor)
                       else boxes))
    # cap the shared adaptive grid: one near-image-size ROI would
    # otherwise force its dense grid onto EVERY ROI in the vmapped
    # gather (512 ROIs x 7x7x115x115 samples = OOM); >=8 samples/bin
    # per axis is within float32 noise of the exact bin integral
    _SR_CAP = 8
    if sampling_ratio > 0:
        sr_y = sr_x = int(sampling_ratio)
    elif bx_host.shape[0]:
        sr_y = min(_SR_CAP, max(1, int(np.ceil(
            (bx_host[:, 3] - bx_host[:, 1]).max()
            * spatial_scale / ph))))
        sr_x = min(_SR_CAP, max(1, int(np.ceil(
            (bx_host[:, 2] - bx_host[:, 0]).max()
            * spatial_scale / pw))))
    else:
        sr_y = sr_x = 1

    def impl(xd, bxd):
        import jax.numpy as jnp

        if bxd.shape[0] == 0:
            return jnp.zeros((0, xd.shape[1], ph, pw), xd.dtype)
        feats = xd[jnp.asarray(img_idx)]          # [R, C, H, W]

        def one(feat, box):
            x1, y1, x2, y2 = [box[k] * spatial_scale - off
                              for k in range(4)]
            bh = jnp.maximum(y2 - y1, 1e-4) / ph
            bw = jnp.maximum(x2 - x1, 1e-4) / pw
            iy = (jnp.arange(ph)[:, None, None, None] * bh + y1
                  + (jnp.arange(sr_y)[None, None, :, None] + 0.5)
                  * bh / sr_y)
            ix = (jnp.arange(pw)[None, :, None, None] * bw + x1
                  + (jnp.arange(sr_x)[None, None, None, :] + 0.5)
                  * bw / sr_x)
            iy = jnp.broadcast_to(iy, (ph, pw, sr_y, sr_x))
            ix = jnp.broadcast_to(ix, (ph, pw, sr_y, sr_x))
            vals = _bilinear_gather(feat, iy.reshape(-1),
                                    ix.reshape(-1))
            return vals.reshape(feat.shape[0], ph, pw,
                                sr_y * sr_x).mean(-1)

        return jax.vmap(one)(feats, bxd)

    xt = x if isinstance(x, Tensor) else Tensor(x, stop_gradient=True)
    bt = boxes if isinstance(boxes, Tensor) else Tensor(
        boxes, stop_gradient=True)
    return dispatch.call("roi_align", impl, (xt, bt), {})


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """Max-pool RoI variant (ref ops.py:1572): adaptive max over each
    bin's integer sub-window. Bin boundaries come from host box values
    (static slices); the max itself records on the tape via dispatch so
    gradients reach x."""
    from ..core import dispatch

    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    bx = np.asarray(boxes.numpy() if isinstance(boxes, Tensor)
                    else boxes)
    bn = np.asarray(boxes_num.numpy() if isinstance(boxes_num, Tensor)
                    else boxes_num)
    img_idx = np.repeat(np.arange(len(bn)), bn)

    def impl(xd):
        import jax.numpy as jnp

        h, w = xd.shape[-2], xd.shape[-1]
        outs = []
        for r in range(bx.shape[0]):
            feat = xd[int(img_idx[r])]
            x1 = int(round(bx[r, 0] * spatial_scale))
            y1 = int(round(bx[r, 1] * spatial_scale))
            x2 = max(int(round(bx[r, 2] * spatial_scale)), x1 + 1)
            y2 = max(int(round(bx[r, 3] * spatial_scale)), y1 + 1)
            x1, y1 = min(x1, w - 1), min(y1, h - 1)
            x2, y2 = min(x2, w), min(y2, h)
            bins = []
            for i in range(ph):
                ys = y1 + (y2 - y1) * i // ph
                ye = max(y1 + (y2 - y1) * (i + 1) // ph, ys + 1)
                for j in range(pw):
                    xs = x1 + (x2 - x1) * j // pw
                    xe = max(x1 + (x2 - x1) * (j + 1) // pw, xs + 1)
                    bins.append(
                        feat[:, ys:ye, xs:xe].max(axis=(-2, -1)))
            outs.append(jnp.stack(bins, -1).reshape(
                feat.shape[0], ph, pw))
        return jnp.stack(outs) if outs else jnp.zeros(
            (0, xd.shape[1], ph, pw), xd.dtype)

    xt = x if isinstance(x, Tensor) else Tensor(x, stop_gradient=True)
    return dispatch.call("roi_pool", impl, (xt,), {})


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """Position-sensitive RoI pool (ref ops.py:1441): channel group
    (i,j) feeds bin (i,j); average within the bin. Built on the
    differentiable roi_align, with the position-sensitive selection as
    a taped op so gradients reach x."""
    from ..core import dispatch

    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    cin = (x._data if isinstance(x, Tensor) else x).shape[1]
    c_out = cin // (ph * pw)
    pooled = roi_align(x, boxes, boxes_num, output_size, spatial_scale,
                       sampling_ratio=2, aligned=False)

    def impl(pd_in):
        import jax.numpy as jnp

        # out[r, c, i, j] = pd[r, (i*pw + j)*c_out + c, i, j] — keep
        # the advanced indices ADJACENT (split placement would move the
        # broadcast dims to the front)
        pd = pd_in.reshape(-1, ph * pw, c_out, ph, pw)
        pdm = jnp.moveaxis(pd, 2, -1)         # [R, ph*pw, ph, pw, c]
        ii = jnp.arange(ph)[:, None]
        jj = jnp.arange(pw)[None, :]
        out = pdm[:, ii * pw + jj, ii, jj]    # [R, ph, pw, c]
        return jnp.transpose(out, (0, 3, 1, 2))

    return dispatch.call("psroi_pool_select", impl, (pooled,), {})


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0):
    """Encode/decode boxes against priors (ref ops.py:584).

    ``axis`` selects which axis of a 3-D decode target the 2-D prior
    broadcasts along (the reference's contract; it is ignored for
    encode): axis=0 pairs prior k with ``target_box[:, k]``, axis=1 with
    ``target_box[k, :]``. Pre-r6 the argument was accepted but silently
    ignored, producing wrong boxes for axis=1 inputs."""
    import jax.numpy as jnp

    if axis not in (0, 1):
        raise ValueError(f"box_coder axis must be 0 or 1, got {axis}")
    pb = prior_box._data if isinstance(prior_box, Tensor) \
        else jnp.asarray(prior_box)
    tb = target_box._data if isinstance(target_box, Tensor) \
        else jnp.asarray(target_box)
    var = (prior_box_var._data if isinstance(prior_box_var, Tensor)
           else jnp.asarray(prior_box_var)) \
        if prior_box_var is not None else jnp.ones_like(pb)
    norm = 0.0 if box_normalized else 1.0
    pw = pb[..., 2] - pb[..., 0] + norm
    ph_ = pb[..., 3] - pb[..., 1] + norm
    pcx = pb[..., 0] + pw * 0.5
    pcy = pb[..., 1] + ph_ * 0.5
    if (code_type == "decode_center_size" and axis == 1
            and tb.ndim == pb.ndim + 1):
        # prior k decodes row k: insert the broadcast dim AFTER the prior
        # axis instead of relying on trailing-dim alignment (which
        # implements axis=0)
        pw, ph_, pcx, pcy = (
            a[..., :, None] for a in (pw, ph_, pcx, pcy)
        )
        if var.ndim == pb.ndim:
            # per-prior variances follow the prior's broadcast dim; a
            # 1-D [4] variance broadcasts over every box as-is
            var = var[..., :, None, :]
    if code_type == "encode_center_size":
        tw = tb[..., 2] - tb[..., 0] + norm
        th = tb[..., 3] - tb[..., 1] + norm
        tcx = tb[..., 0] + tw * 0.5
        tcy = tb[..., 1] + th * 0.5
        out = jnp.stack([
            (tcx - pcx) / pw / var[..., 0],
            (tcy - pcy) / ph_ / var[..., 1],
            jnp.log(tw / pw) / var[..., 2],
            jnp.log(th / ph_) / var[..., 3],
        ], -1)
    else:  # decode_center_size
        ocx = var[..., 0] * tb[..., 0] * pw + pcx
        ocy = var[..., 1] * tb[..., 1] * ph_ + pcy
        ow = jnp.exp(var[..., 2] * tb[..., 2]) * pw
        oh = jnp.exp(var[..., 3] * tb[..., 3]) * ph_
        out = jnp.stack([
            ocx - ow * 0.5, ocy - oh * 0.5,
            ocx + ow * 0.5 - norm, ocy + oh * 0.5 - norm,
        ], -1)
    return Tensor(out, stop_gradient=True)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None):
    """Deformable conv v1/v2 (ref ops.py:766): bilinear-sample the
    input at offset positions per kernel tap, then a 1x1 contraction."""
    import jax.numpy as jnp

    xd = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    wd = weight._data if isinstance(weight, Tensor) \
        else jnp.asarray(weight)

    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    sh, sw = _pair(stride)
    ph_, pw_ = _pair(padding)
    dh, dw = _pair(dilation)
    n, cin, h, w = xd.shape
    cout, _, kh, kw = wd.shape
    ho = (h + 2 * ph_ - dh * (kh - 1) - 1) // sh + 1
    wo = (w + 2 * pw_ - dw * (kw - 1) - 1) // sw + 1
    from ..core import dispatch

    md_t = mask if mask is None or isinstance(mask, Tensor) \
        else Tensor(mask, stop_gradient=True)

    if groups != 1:
        raise NotImplementedError(
            "deform_conv2d groups>1 (channel-grouped weights) is not "
            "supported; deformable_groups IS supported"
        )
    dg = int(deformable_groups)
    if cin % dg != 0:
        raise ValueError(
            f"deformable_groups={dg} must divide in_channels={cin}"
        )
    cg = cin // dg  # input channels per deformable group

    def impl(xd2, od2, wd2, bd2=None, md2=None):
        xp = jnp.pad(xd2, ((0, 0), (0, 0), (ph_, ph_), (pw_, pw_)))
        base_y = jnp.arange(ho)[:, None] * sh
        base_x = jnp.arange(wo)[None, :] * sw
        cols = []
        # offsets layout (ref deform_conv2d): [n, dg*2*kh*kw, ho, wo] —
        # each deformable group g displaces ITS channel slice
        for ki in range(kh):
            for kj in range(kw):
                t = ki * kw + kj
                group_samples = []
                for g in range(dg):
                    base = g * 2 * kh * kw
                    oy = od2[:, base + 2 * t]
                    ox = od2[:, base + 2 * t + 1]
                    ys = base_y[None] + ki * dh + oy
                    xs = base_x[None] + kj * dw + ox
                    sampled = jnp.stack([
                        _bilinear_gather(
                            xp[b, g * cg:(g + 1) * cg],
                            ys[b].reshape(-1), xs[b].reshape(-1)
                        ).reshape(cg, ho, wo)
                        for b in range(n)
                    ])
                    if md2 is not None:
                        sampled = sampled * md2[
                            :, g * kh * kw + t][:, None]
                    group_samples.append(sampled)
                cols.append(jnp.concatenate(group_samples, axis=1))
        col = jnp.stack(cols, 2)  # [n, cin, kh*kw, ho, wo]
        out = jnp.einsum("nckhw,ock->nohw",
                         col, wd2.reshape(cout, cin, kh * kw))
        if bd2 is not None:
            out = out + bd2[None, :, None, None]
        return out

    def _t(v):
        if v is None or isinstance(v, Tensor):
            return v
        return Tensor(v, stop_gradient=True)

    # None placeholders pass through dispatch untouched, so impl sees
    # its five positional slots regardless of which optionals exist
    return dispatch.call(
        "deform_conv2d", impl,
        (_t(x), _t(offset), _t(weight), _t(bias), md_t), {},
    )


class DeformConv2D(nn.Layer):
    """ref ops.py:973 — learnable weight/bias over deform_conv2d."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        from ..nn import initializer as I
        from ..nn.parameter import ParamAttr

        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._attrs = dict(stride=stride, padding=padding,
                           dilation=dilation,
                           deformable_groups=deformable_groups,
                           groups=groups)
        self.weight = self.create_parameter(
            shape=[out_channels, in_channels // groups, *ks],
            attr=ParamAttr._to_attr(weight_attr) if weight_attr
            else ParamAttr(initializer=I.XavierUniform()),
        )
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=[out_channels],
            attr=ParamAttr._to_attr(bias_attr) if bias_attr
            else ParamAttr(initializer=I.Constant(0.0)),
        )

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             mask=mask, **self._attrs)


class RoIAlign(nn.Layer):
    """ref ops.py:1826."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self._output_size,
                         self._spatial_scale)


class RoIPool(nn.Layer):
    """ref ops.py:1657."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._output_size,
                        self._spatial_scale)


class PSRoIPool(nn.Layer):
    """ref ops.py:1523."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._output_size,
                          self._spatial_scale)


class ConvNormActivation(nn.Sequential):
    """ref ops.py:1877 — Conv2D + norm + activation block."""

    def __init__(self, in_channels, out_channels, kernel_size=3,
                 stride=1, padding=None, groups=1,
                 norm_layer=nn.BatchNorm2D, activation_layer=nn.ReLU,
                 dilation=1, bias=None):
        if padding is None:
            padding = (kernel_size - 1) // 2 * dilation
        if bias is None:
            bias = norm_layer is None
        layers = [nn.Conv2D(
            in_channels, out_channels, kernel_size, stride=stride,
            padding=padding, dilation=dilation, groups=groups,
            bias_attr=None if bias else False,
        )]
        if norm_layer is not None:
            layers.append(norm_layer(out_channels))
        if activation_layer is not None:
            layers.append(activation_layer())
        super().__init__(*layers)


def read_file(filename, name=None):
    raise NotImplementedError(
        "read_file needs an image codec; this environment is zero-egress "
        "with no libjpeg binding — feed decoded arrays via paddle.vision "
        "datasets/transforms instead"
    )


def decode_jpeg(x, mode="unchanged", name=None):
    raise NotImplementedError(
        "decode_jpeg needs libjpeg; feed decoded arrays via "
        "paddle.vision datasets/transforms instead"
    )
