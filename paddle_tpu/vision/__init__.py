"""paddle.vision analogue (ref: python/paddle/vision/__init__.py)."""
from . import datasets, transforms
from . import models
from . import ops

__all__ = ["datasets", "transforms", "models", "ops"]
