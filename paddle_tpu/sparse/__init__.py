"""paddle.sparse analogue (ref: python/paddle/sparse/__init__.py —
COO/CSR creation, conversion, elementwise + matmul ops over
phi/kernels/sparse).

TPU-first: backed by jax.experimental.sparse.BCOO — XLA lowers sparse
contractions to gather/scatter+dot programs (TPUs have no sparse MXU
mode; the reference's cuSPARSE kernels have no analogue, so BCOO's
compiled lowering is the honest equivalent).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor

__all__ = [
    "SparseCooTensor", "sparse_coo_tensor", "sparse_csr_tensor",
    "is_sparse", "matmul", "add", "subtract", "multiply", "divide",
    "relu", "coalesce", "transpose", "sum", "is_same_shape", "mask_as",
    # value-elementwise unary family (ref sparse/unary.py)
    "sin", "tan", "asin", "atan", "sinh", "asinh", "atanh", "tanh",
    "sqrt", "square", "log1p", "expm1", "abs", "neg", "pow", "cast",
    "rad2deg", "deg2rad", "nn",
]


class SparseCooTensor:
    """COO sparse tensor (ref: phi/core/sparse_coo_tensor.h)."""

    def __init__(self, bcoo):
        self._bcoo = bcoo

    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        from ..core.dtype import convert_dtype

        return convert_dtype(self._bcoo.dtype)

    @property
    def nnz(self):
        return int(self._bcoo.nse)

    def indices(self):
        return Tensor(jnp.swapaxes(self._bcoo.indices, 0, 1))

    def values(self):
        return Tensor(self._bcoo.data)

    def to_dense(self):
        return Tensor(self._bcoo.todense())

    def to_sparse_coo(self, sparse_dim=None):
        return self

    def numpy(self):
        return np.asarray(self._bcoo.todense())

    def __repr__(self):
        return (
            f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, "
            f"dtype={self.dtype.name})"
        )


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    """Build from [ndim, nnz] indices + [nnz] values (ref
    python/paddle/sparse/creation.py sparse_coo_tensor)."""
    idx = np.asarray(
        indices.numpy() if isinstance(indices, Tensor) else indices
    )
    val = jnp.asarray(
        values._data if isinstance(values, Tensor) else values
    )
    if dtype is not None:
        from ..core.dtype import convert_dtype

        val = val.astype(convert_dtype(dtype).jnp_dtype)
    if shape is None:
        shape = tuple(int(i) + 1 for i in idx.max(axis=1))
    bcoo = jsparse.BCOO(
        (val, jnp.asarray(idx.T, jnp.int32)), shape=tuple(shape)
    )
    return SparseCooTensor(bcoo)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None):
    """CSR accepted, stored as COO internally (BCOO is the XLA-lowered
    format; ref sparse/creation.py sparse_csr_tensor)."""
    crows = np.asarray(
        crows.numpy() if isinstance(crows, Tensor) else crows
    )
    cols = np.asarray(cols.numpy() if isinstance(cols, Tensor) else cols)
    rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
    return sparse_coo_tensor(
        np.stack([rows, cols]), values, shape, dtype
    )


def is_sparse(x):
    return isinstance(x, SparseCooTensor)


def matmul(x, y):
    """sparse @ dense (ref sparse/binary.py matmul). Differentiable
    w.r.t. the DENSE operand (recorded on the tape); gradients w.r.t.
    sparse values are not supported in v1."""
    from ..core import dispatch

    if isinstance(x, SparseCooTensor):
        yt = y if isinstance(y, Tensor) else Tensor(jnp.asarray(y))
        bcoo = x._bcoo
        return dispatch.call("sparse_matmul", lambda d: bcoo @ d, (yt,), {})
    if isinstance(y, SparseCooTensor):
        xt = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
        bcoo = y._bcoo
        return dispatch.call(
            "sparse_matmul", lambda d: (bcoo.T @ d.T).T, (xt,), {}
        )
    xa = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    ya = y._data if isinstance(y, Tensor) else jnp.asarray(y)
    return Tensor(xa @ ya)


def add(x, y):
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        return SparseCooTensor(
            jsparse.bcoo_sum_duplicates(x._bcoo + y._bcoo)
        )
    raise TypeError("sparse.add expects two SparseCooTensors")


def relu(x):
    """ref sparse/unary.py relu — elementwise on the stored values."""
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse.relu expects a SparseCooTensor")
    b = x._bcoo
    return SparseCooTensor(
        jsparse.BCOO((jnp.maximum(b.data, 0), b.indices), shape=b.shape)
    )


# -- value-elementwise unary family (ref sparse/unary.py) --------------------
# Zero-preserving maps apply to the stored values only — the reference
# implements each as a dedicated sparse kernel (phi/kernels/sparse/unary);
# here one table over BCOO values.


def _unary(name, fn):
    def op(x, name=None):
        if not isinstance(x, SparseCooTensor):
            raise TypeError(f"sparse.{name} expects a SparseCooTensor")
        b = x._bcoo
        return SparseCooTensor(
            jsparse.BCOO((fn(b.data), b.indices), shape=b.shape)
        )

    op.__name__ = name
    op.__doc__ = f"sparse.{name} (ref sparse/unary.py:{name})"
    return op


sin = _unary("sin", jnp.sin)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
asinh = _unary("asinh", jnp.arcsinh)
atanh = _unary("atanh", jnp.arctanh)
tanh = _unary("tanh", jnp.tanh)
sqrt = _unary("sqrt", jnp.sqrt)
square = _unary("square", jnp.square)
log1p = _unary("log1p", jnp.log1p)
expm1 = _unary("expm1", jnp.expm1)
abs = _unary("abs", jnp.abs)  # noqa: A001
neg = _unary("neg", jnp.negative)
rad2deg = _unary("rad2deg", jnp.rad2deg)
deg2rad = _unary("deg2rad", jnp.deg2rad)


def pow(x, factor, name=None):  # noqa: A001
    """ref sparse/unary.py:pow — values ** factor."""
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse.pow expects a SparseCooTensor")
    b = x._bcoo
    return SparseCooTensor(
        jsparse.BCOO((b.data ** factor, b.indices), shape=b.shape)
    )


def cast(x, index_dtype=None, value_dtype=None, name=None):
    """ref sparse/unary.py:cast."""
    from ..core.dtype import convert_dtype

    b = x._bcoo
    data, idx = b.data, b.indices
    if value_dtype is not None:
        data = data.astype(convert_dtype(value_dtype).jnp_dtype)
    if index_dtype is not None:
        idx = idx.astype(convert_dtype(index_dtype).jnp_dtype)
    return SparseCooTensor(jsparse.BCOO((data, idx), shape=b.shape))


def coalesce(x, name=None):
    """Merge duplicate indices (ref sparse/unary.py:coalesce)."""
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse.coalesce expects a SparseCooTensor")
    return SparseCooTensor(jsparse.bcoo_sum_duplicates(x._bcoo))


def transpose(x, perm, name=None):
    """ref sparse/unary.py:transpose."""
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse.transpose expects a SparseCooTensor")
    return SparseCooTensor(
        jsparse.bcoo_transpose(x._bcoo, permutation=tuple(perm))
    )


def sum(x, axis=None, dtype=None, keepdim=False, name=None):  # noqa: A001
    """ref sparse/unary.py:sum — returns a DENSE Tensor (the reference
    returns sparse for some axes; dense is the XLA-honest result of a
    contraction)."""
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse.sum expects a SparseCooTensor")
    dense = x._bcoo.todense()
    out = jnp.sum(dense, axis=axis, keepdims=keepdim)
    if dtype is not None:
        from ..core.dtype import convert_dtype

        out = out.astype(convert_dtype(dtype).jnp_dtype)
    return Tensor(out)


def is_same_shape(x, y):
    """ref sparse/unary.py helper."""
    return list(x.shape) == list(y.shape)


def mask_as(x, mask, name=None):
    """Keep x's entries at the mask's sparsity pattern
    (ref sparse/binary.py mask_as)."""
    xa = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    b = mask._bcoo
    vals = xa[tuple(b.indices[:, d] for d in range(b.indices.shape[1]))]
    return SparseCooTensor(
        jsparse.BCOO((vals, b.indices), shape=b.shape)
    )


def _check_pair(name, x, y):
    if not (isinstance(x, SparseCooTensor)
            and isinstance(y, SparseCooTensor)):
        raise TypeError(f"sparse.{name} expects two SparseCooTensors")
    if list(x.shape) != list(y.shape):
        raise ValueError(f"sparse.{name}: shape mismatch")


def subtract(x, y, name=None):
    """ref sparse/binary.py:subtract — O(nnz) union-of-supports path
    (add of the negation, like add())."""
    _check_pair("subtract", x, y)
    return add(x, neg(y))


def multiply(x, y, name=None):
    """ref sparse/binary.py:multiply. Densifies internally (XLA lowers
    the elementwise product over dense intermediates); the support of
    the result is the intersection, so fromdense re-sparsifies."""
    _check_pair("multiply", x, y)
    out = jnp.multiply(x._bcoo.todense(), y._bcoo.todense())
    return SparseCooTensor(jsparse.BCOO.fromdense(out))


def divide(x, y, name=None):
    """ref sparse/binary.py:divide. Defined on x's support only —
    off-support positions stay exact zeros (a naive dense divide would
    store 0/0 NaNs everywhere off-support). Densifies internally."""
    _check_pair("divide", x, y)
    xd = x._bcoo.todense()
    yd = y._bcoo.todense()
    support = jnp.zeros(x._bcoo.shape, bool).at[
        tuple(x._bcoo.indices[:, d]
              for d in range(x._bcoo.indices.shape[1]))
    ].set(True)
    out = jnp.where(support, xd / jnp.where(support, yd, 1.0), 0.0)
    return SparseCooTensor(jsparse.BCOO.fromdense(out))


class _SparseNN:
    """sparse.nn shim: ReLU layer (ref sparse/nn/layer/activation.py)."""

    class ReLU:
        def __call__(self, x):
            return relu(x)

    class Softmax:
        """Row-wise softmax over the stored values of a 2-D COO
        (ref sparse/nn/layer/activation.py Softmax: softmax over
        non-zero entries per row)."""

        def __call__(self, x):
            b = jsparse.bcoo_sum_duplicates(x._bcoo)
            if len(b.shape) < 2:
                raise ValueError("sparse Softmax needs ndim >= 2")
            # group by ALL leading dims (a 3-D [B, R, C] normalizes per
            # [b, r] row, not per batch slice): flatten leading indices
            # to scalar row keys via strides
            strides = np.cumprod(
                (list(b.shape[1:-1]) + [1])[::-1]
            )[::-1].tolist()
            # (module-level `sum` is the sparse op — accumulate manually)
            rows = b.indices[:, 0] * int(strides[0])
            for d in range(1, len(b.shape) - 1):
                rows = rows + b.indices[:, d] * int(strides[d])
            vals = b.data.astype(jnp.float32)
            n_rows = int(np.prod(b.shape[:-1]))
            row_max = jnp.full((n_rows,), -jnp.inf).at[rows].max(vals)
            e = jnp.exp(vals - row_max[rows])
            denom = jnp.zeros((n_rows,)).at[rows].add(e)
            return SparseCooTensor(
                jsparse.BCOO(
                    ((e / denom[rows]).astype(b.data.dtype), b.indices),
                    shape=b.shape,
                )
            )


nn = _SparseNN()
