"""paddle.sparse analogue (ref: python/paddle/sparse/__init__.py —
COO/CSR creation, conversion, elementwise + matmul ops over
phi/kernels/sparse).

TPU-first: backed by jax.experimental.sparse.BCOO — XLA lowers sparse
contractions to gather/scatter+dot programs (TPUs have no sparse MXU
mode; the reference's cuSPARSE kernels have no analogue, so BCOO's
compiled lowering is the honest equivalent).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor

__all__ = [
    "SparseCooTensor", "sparse_coo_tensor", "sparse_csr_tensor",
    "is_sparse", "matmul", "add", "relu",
]


class SparseCooTensor:
    """COO sparse tensor (ref: phi/core/sparse_coo_tensor.h)."""

    def __init__(self, bcoo):
        self._bcoo = bcoo

    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        from ..core.dtype import convert_dtype

        return convert_dtype(self._bcoo.dtype)

    @property
    def nnz(self):
        return int(self._bcoo.nse)

    def indices(self):
        return Tensor(jnp.swapaxes(self._bcoo.indices, 0, 1))

    def values(self):
        return Tensor(self._bcoo.data)

    def to_dense(self):
        return Tensor(self._bcoo.todense())

    def to_sparse_coo(self, sparse_dim=None):
        return self

    def numpy(self):
        return np.asarray(self._bcoo.todense())

    def __repr__(self):
        return (
            f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, "
            f"dtype={self.dtype.name})"
        )


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    """Build from [ndim, nnz] indices + [nnz] values (ref
    python/paddle/sparse/creation.py sparse_coo_tensor)."""
    idx = np.asarray(
        indices.numpy() if isinstance(indices, Tensor) else indices
    )
    val = jnp.asarray(
        values._data if isinstance(values, Tensor) else values
    )
    if dtype is not None:
        from ..core.dtype import convert_dtype

        val = val.astype(convert_dtype(dtype).jnp_dtype)
    if shape is None:
        shape = tuple(int(i) + 1 for i in idx.max(axis=1))
    bcoo = jsparse.BCOO(
        (val, jnp.asarray(idx.T, jnp.int32)), shape=tuple(shape)
    )
    return SparseCooTensor(bcoo)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None):
    """CSR accepted, stored as COO internally (BCOO is the XLA-lowered
    format; ref sparse/creation.py sparse_csr_tensor)."""
    crows = np.asarray(
        crows.numpy() if isinstance(crows, Tensor) else crows
    )
    cols = np.asarray(cols.numpy() if isinstance(cols, Tensor) else cols)
    rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
    return sparse_coo_tensor(
        np.stack([rows, cols]), values, shape, dtype
    )


def is_sparse(x):
    return isinstance(x, SparseCooTensor)


def matmul(x, y):
    """sparse @ dense (ref sparse/binary.py matmul). Differentiable
    w.r.t. the DENSE operand (recorded on the tape); gradients w.r.t.
    sparse values are not supported in v1."""
    from ..core import dispatch

    if isinstance(x, SparseCooTensor):
        yt = y if isinstance(y, Tensor) else Tensor(jnp.asarray(y))
        bcoo = x._bcoo
        return dispatch.call("sparse_matmul", lambda d: bcoo @ d, (yt,), {})
    if isinstance(y, SparseCooTensor):
        xt = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
        bcoo = y._bcoo
        return dispatch.call(
            "sparse_matmul", lambda d: (bcoo.T @ d.T).T, (xt,), {}
        )
    xa = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    ya = y._data if isinstance(y, Tensor) else jnp.asarray(y)
    return Tensor(xa @ ya)


def add(x, y):
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        return SparseCooTensor(
            jsparse.bcoo_sum_duplicates(x._bcoo + y._bcoo)
        )
    raise TypeError("sparse.add expects two SparseCooTensors")


def relu(x):
    """ref sparse/unary.py relu — elementwise on the stored values."""
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse.relu expects a SparseCooTensor")
    b = x._bcoo
    return SparseCooTensor(
        jsparse.BCOO((jnp.maximum(b.data, 0), b.indices), shape=b.shape)
    )
