"""Adamax (ref: python/paddle/optimizer/adamax.py)."""
from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer


class Adamax(Optimizer):
    _acc_names = ("moment", "inf_norm")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False):
        super().__init__(
            learning_rate=learning_rate,
            parameters=parameters,
            weight_decay=weight_decay,
            grad_clip=grad_clip,
            name=name,
            multi_precision=multi_precision,
        )
        self._beta1 = float(beta1)
        self._beta2 = float(beta2)
        self._epsilon = float(epsilon)

    def _init_state(self, p):
        return {"moment": jnp.zeros_like(p), "inf_norm": jnp.zeros_like(p)}

    def _update(self, p, g, state, lr, t, attr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        m = b1 * state["moment"] + (1 - b1) * g
        u = jnp.maximum(b2 * state["inf_norm"], jnp.abs(g) + eps)
        new_p = p - lr / (1 - jnp.power(b1, t)) * m / u
        return new_p, {"moment": m, "inf_norm": u}
