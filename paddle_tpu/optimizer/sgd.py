"""SGD (ref: python/paddle/optimizer/sgd.py)."""
from __future__ import annotations

from .optimizer import Optimizer


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 multi_precision=False):
        super().__init__(
            learning_rate=learning_rate,
            parameters=parameters,
            weight_decay=weight_decay,
            grad_clip=grad_clip,
            name=name,
            multi_precision=multi_precision,
        )

    def _update(self, p, g, state, lr, t, attr):
        return p - lr * g, {}
