"""Optimizer base class.

Capability match for the reference's ``paddle.optimizer.Optimizer`` (ref:
python/paddle/optimizer/optimizer.py:127 — param groups, LRScheduler
integration, grad clip, regularization, accumulator state_dict). The update
machinery is TPU-first instead of per-op fused CUDA kernels
(ref: phi/kernels/gpu/adamw_kernel.cu): every ``step()`` runs ONE jitted XLA
program over the full parameter pytree — clip, regularize, and the
per-parameter update rule fuse into a single device launch; learning rate and
step count enter as scalar operands so LR schedules never recompile.

GradScaler integration: ``_set_found_inf`` installs a device bool; the staged
update keeps old params/state where it is True (the reference re-launches
kernels conditionally on the host instead).
"""
from __future__ import annotations

import collections
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import autograd
from ..core.tensor import Tensor
from ..nn.clip import ClipGradBase
from ..regularizer import L1Decay, L2Decay, WeightDecayRegularizer
from .lr import LRScheduler

__all__ = ["Optimizer"]


def _malloc_trim():
    """Hand freed glibc arena back to the OS (near-host-RAM chunked
    sweeps: freed device buffers otherwise stay resident as arena and
    the next group's temps OOM the box)."""
    try:
        import ctypes

        ctypes.CDLL("libc.so.6").malloc_trim(0)
    except (OSError, AttributeError):
        pass  # non-glibc libc (musl/macOS): no malloc_trim to call


class _PAttr(NamedTuple):
    """Static (hashable) per-parameter attributes baked into the staged
    update: jit sees them as compile-time constants."""

    lr_scale: float
    reg_kind: str | None  # 'l1' | 'l2' | None  (coupled regularizer)
    reg_coeff: float
    need_clip: bool
    multi_precision: bool
    decoupled_decay: float = 0.0  # AdamW-style p *= (1 - lr*coeff)
    lr_ratio: float = 1.0  # AdamW lr_ratio(param) hook


def _found_inf_operand(opt):
    """GradScaler found_inf as a staged scalar operand. The dtype is
    pinned: a bare ``jnp.asarray(False)`` yields a weakly-typed scalar
    that can silently promote downstream (analysis rule dtype-drift)."""
    fi = opt._found_inf
    return fi if fi is not None else jnp.asarray(False, dtype=jnp.bool_)


def _normalize_weight_decay(wd):
    if wd is None:
        return None, 0.0
    if isinstance(wd, L1Decay):
        return "l1", wd.coeff
    if isinstance(wd, (L2Decay,)):
        return "l2", wd.coeff
    if isinstance(wd, (int, float)):
        return "l2", float(wd)
    if isinstance(wd, WeightDecayRegularizer):
        raise TypeError(f"unsupported regularizer {wd!r}")
    raise TypeError(f"weight_decay must be float or L1Decay/L2Decay, got {wd!r}")


class Optimizer:
    """Base optimizer. Subclasses define ``_acc_names`` (state slot names) and

    * ``_init_state(p_array) -> dict[name, array]``
    * ``_update(p, g, state, lr, t, attr) -> (new_p, new_state)`` — pure jnp.

    ``p`` arrives as fp32 master weight when ``multi_precision`` and the
    param is half-precision; the base class handles the down-cast.
    """

    _acc_names: tuple = ()

    def __init__(
        self,
        learning_rate=0.001,
        parameters=None,
        weight_decay=None,
        grad_clip=None,
        name=None,
        multi_precision=False,
    ):
        if parameters is None:
            raise ValueError(
                "parameters is required in dygraph mode (pass model.parameters())"
            )
        parameters = list(parameters)
        if grad_clip is not None and not isinstance(grad_clip, ClipGradBase):
            raise TypeError("grad_clip must be a paddle.nn.ClipGradBy* instance")
        if not isinstance(learning_rate, (int, float, LRScheduler)):
            raise TypeError("learning_rate must be float or LRScheduler")

        self._name = name
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._default_weight_decay = weight_decay
        self._param_groups = []
        self._accumulators = {}  # id(param) -> {acc_name: jax.Array}
        self._global_step = 0
        self._found_inf = None
        self._compiled_step = None
        self._param_name_counter = 0

        if parameters and isinstance(parameters[0], dict):
            for group in parameters:
                self._add_param_group(dict(group))
        else:
            self._add_param_group(
                {"params": parameters, "weight_decay": weight_decay}
            )

    # -- param groups ------------------------------------------------------
    def _add_param_group(self, group):
        params = group["params"]
        if isinstance(params, Tensor):
            params = [params]
        group["params"] = list(params)
        group.setdefault("weight_decay", self._default_weight_decay)
        group.setdefault("learning_rate", 1.0)
        for p in group["params"]:
            if p.name is None:
                p.name = f"param_{self._param_name_counter}"
                self._param_name_counter += 1
        self._param_groups.append(group)
        self._compiled_step = None

    @property
    def _parameter_list(self):
        return [p for g in self._param_groups for p in g["params"]]

    # -- lr ----------------------------------------------------------------
    def get_lr(self):
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError(
                "cannot set_lr when the learning rate is an LRScheduler; "
                "call scheduler.step() instead"
            )
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        if not isinstance(scheduler, LRScheduler):
            raise TypeError("expected an LRScheduler")
        self._learning_rate = scheduler

    # -- state -------------------------------------------------------------
    def _init_state(self, p_array):
        return {}

    def _ensure_state(self, p):
        st = self._accumulators.get(id(p))
        if st is None:
            arr = p._data
            if self._use_master(p):
                master = arr.astype(jnp.float32)
                st = self._init_state(master)
                st["master_weight"] = master
            else:
                st = self._init_state(arr)
            self._accumulators[id(p)] = st
        return st

    def _use_master(self, p):
        return self._multi_precision and p._data.dtype in (
            jnp.bfloat16,
            jnp.float16,
        )

    def _set_found_inf(self, found_inf):
        """GradScaler hook: device bool; when True the step is a no-op."""
        self._found_inf = found_inf

    # -- the staged update -------------------------------------------------
    def _group_weight_decay(self, group):
        return _normalize_weight_decay(group.get("weight_decay"))

    def _collect(self):
        """Gather (param, grad_array, attr) for every trainable param with a
        grad. Param-level regularizer overrides the group's."""
        out = []
        for group in self._param_groups:
            g_kind, g_coeff = self._group_weight_decay(group)
            lr_scale = float(group.get("learning_rate", 1.0))
            for p in group["params"]:
                if not getattr(p, "trainable", not p.stop_gradient):
                    continue
                grad = p.grad
                if grad is None:
                    continue
                kind, coeff = g_kind, g_coeff
                preg = getattr(p, "regularizer", None)
                if preg is not None:
                    kind, coeff = _normalize_weight_decay(preg)
                decoupled, lr_ratio = self._param_extras(p, group)
                attr = _PAttr(
                    lr_scale=lr_scale
                    * float(
                        getattr(p, "optimize_attr", {}).get("learning_rate", 1.0)
                    ),
                    reg_kind=kind,
                    reg_coeff=coeff,
                    need_clip=getattr(p, "need_clip", True),
                    multi_precision=self._use_master(p),
                    decoupled_decay=decoupled,
                    lr_ratio=lr_ratio,
                )
                g_arr = grad._data if isinstance(grad, Tensor) else jnp.asarray(grad)
                out.append((p, g_arr, attr))
        return out

    def _param_extras(self, p, group=None):
        """Hook for subclasses: (decoupled_decay_coeff, lr_ratio) baked into
        the per-param static attrs (AdamW overrides)."""
        return 0.0, 1.0

    def _make_step_fn(self, use_clip=True):
        clip = self._grad_clip if use_clip else None

        def step_fn(attrs, out_shardings, lr, t, found_inf, params, grads,
                    states):
            if clip is not None:
                grads = clip._clip_arrays(
                    params, grads, [a.need_clip for a in attrs]
                )
            new_params, new_states = [], []
            for p, g, s, a, (target, state_targets) in zip(
                params, grads, states, attrs, out_shardings
            ):
                compute_p = s["master_weight"] if a.multi_precision else p
                g = g.astype(compute_p.dtype)
                if a.reg_kind == "l2":
                    g = g + a.reg_coeff * compute_p
                elif a.reg_kind == "l1":
                    g = g + a.reg_coeff * jnp.sign(compute_p)
                eff_lr = lr * a.lr_scale * a.lr_ratio
                if a.decoupled_decay != 0.0:
                    compute_p = compute_p * (1.0 - eff_lr * a.decoupled_decay)
                np_, ns = self._update(compute_p, g, s, eff_lr, t, a)
                if a.multi_precision:
                    ns = dict(ns)
                    ns["master_weight"] = np_
                    np_ = np_.astype(p.dtype)
                np_ = jnp.where(found_inf, p, np_)
                if target is not None:
                    # ZeRO: sharded-state updates must hand the param back
                    # in its own layout (GSPMD emits the all-gather here)
                    np_ = jax.lax.with_sharding_constraint(np_, target)
                st_map = dict(state_targets)
                ns = {
                    # keep old value under found_inf; each slot keeps its
                    # declared layout
                    k: jax.lax.with_sharding_constraint(v, st_map[k])
                    if st_map.get(k) is not None else v
                    for k, v in (
                        (k, jnp.where(found_inf, s[k], v) if k in s else v)
                        for k, v in ns.items()
                    )
                }
                new_params.append(np_)
                new_states.append(ns)
            return new_params, new_states

        # Donating params + optimizer state runs the update in place
        # (old buffers are rebound right after) — the knob that lets an
        # 8B-state dryrun fit host RAM. OPT-IN via donate_state: a donated
        # update invalidates any user-held alias of a parameter buffer
        # ('Array has been deleted'), and on TPU the remote-AOT tunnel
        # round-trips donated buffers anyway (BASELINE.md r4); TrainStep
        # owns donation on the real-chip path. Grads stay undonated so
        # p.grad remains readable after step().
        donate = (5, 7) if self.donate_state else ()
        return jax.jit(
            step_fn, static_argnums=(0, 1), donate_argnums=donate
        )

    @staticmethod
    def _param_out_sharding(p_arr, state):
        """Static layout contract for one param's staged update:
        (param_target, ((state_key, target), ...)). The updated param comes
        back in the param's own NamedSharding — or replicated over the
        state's mesh when only the state is sharded (ZeRO stage 1/2: the
        all-gather) — and each state slot keeps its declared layout."""
        from jax.sharding import NamedSharding, PartitionSpec

        sh = getattr(p_arr, "sharding", None)
        mesh = sh.mesh if isinstance(sh, NamedSharding) else None
        for arr in state.values():
            ssh = getattr(arr, "sharding", None)
            if isinstance(ssh, NamedSharding):
                mesh = ssh.mesh
                break
        if mesh is None:
            return None, ()
        replicated = NamedSharding(mesh, PartitionSpec())
        state_targets = tuple(
            (
                k,
                arr.sharding
                if isinstance(getattr(arr, "sharding", None), NamedSharding)
                else replicated,
            )
            for k, arr in state.items()
        )
        param_target = sh if isinstance(sh, NamedSharding) else replicated
        return param_target, state_targets

    # When set (int), step() updates parameters in groups of this many
    # instead of one whole-tree program: transient memory per update
    # call drops to O(group bytes) — the knob that lets an 8B-state
    # virtual-mesh dryrun fit host RAM (one program per group shape is
    # cached by jit as usual). None = single fused program (default,
    # fastest on a real chip).
    step_chunk: int | None = None
    # Donate param/state buffers into the update program (in-place
    # semantics; see _build_step). Off by default — user-held aliases of
    # parameter buffers stay valid. The virtual-mesh 8B dryrun turns it
    # on to fit host RAM.
    donate_state: bool = False
    # With step_chunk: drop each group's p.grad right after its update,
    # so gradient memory shrinks as the chunked sweep advances (for
    # state sizes near host RAM). Off by default — p.grad stays
    # readable after step() otherwise.
    chunk_free_grads: bool = False

    @autograd.no_grad()
    def step(self):
        if getattr(self, "gradient_accumulation_steps", 1) > 1:
            raise RuntimeError(
                "gradient_accumulation_steps is set on this optimizer "
                "but eager step() does not accumulate — run the step "
                "through paddle.jit.TrainStep (it stages the k-micro-"
                "batch accumulation + single update), or unset the "
                "attribute to step eagerly per batch"
            )
        triples = self._collect()
        if not triples:
            self._global_step += 1
            return
        if self.step_chunk:
            k = int(self.step_chunk)
            if k <= 0:
                raise ValueError(
                    f"step_chunk must be a positive int, got {k}"
                )
            if self._grad_clip is not None:
                # global-norm clipping must see the WHOLE gradient tree;
                # clip once up front, then update chunks with clipping
                # disabled (per-chunk clipping would re-normalize by each
                # chunk's own norm)
                params = [p for p, _, _ in triples]
                grads = [g for _, g, _ in triples]
                clipped = self._grad_clip._clip_arrays(
                    [p._data for p in params], grads,
                    [a.need_clip for _, _, a in triples],
                )
                triples = [
                    (p, g, a) for (p, _, a), g in zip(triples, clipped)
                ]
            for i in range(0, len(triples), k):
                group = triples[i:i + k]
                self._step_group(group, use_clip=False)
                if self.chunk_free_grads:
                    for j in range(i, min(i + k, len(triples))):
                        # release BOTH references to the grad array (the
                        # triples list pins it too) so the buffer is
                        # actually reclaimable mid-sweep
                        p = triples[j][0]
                        p.grad = None
                        triples[j] = None
                    _malloc_trim()
            self._global_step += 1
            return
        self._step_group(triples)
        self._global_step += 1

    def _step_group(self, triples, use_clip=True):
        params = [p for p, _, _ in triples]
        grads = [g for _, g, _ in triples]
        attrs = tuple(a for _, _, a in triples)
        states = [self._ensure_state(p) for p in params]

        lr = jnp.float32(self.get_lr())
        t = jnp.float32(self._global_step + 1)
        found_inf = _found_inf_operand(self)  # dtype-pinned bool

        grad_sharding = getattr(self, "_grad_sharding_for", None)
        if grad_sharding is not None:
            # ZeRO stage>=2 eager path: lay each grad out sharded before the
            # update (device_put = the reduce-scatter's memory effect here;
            # inside jit.TrainStep the constraint stages the real one)
            grads = [
                jax.device_put(g, s)
                if (s := grad_sharding(p)) is not None else g
                for p, g in zip(params, grads)
            ]
        targets = tuple(
            self._param_out_sharding(p._data, st)
            for p, st in zip(params, states)
        )
        if getattr(self, "_compiled_donate", None) != self.donate_state:
            # donate_state toggled after a build: drop stale programs
            self._compiled_step = None
            self._compiled_step_noclip = None
            self._compiled_donate = self.donate_state
        if use_clip:
            if self._compiled_step is None:
                self._compiled_step = self._make_step_fn()
            compiled = self._compiled_step
        else:
            if getattr(self, "_compiled_step_noclip", None) is None:
                self._compiled_step_noclip = self._make_step_fn(
                    use_clip=False
                )
            compiled = self._compiled_step_noclip
        try:
            new_params, new_states = compiled(
                attrs, targets, lr, t, found_inf,
                [p._data for p in params], grads, states,
            )
        except Exception as e:
            if self.donate_state:
                # params/states were DONATED into the failed call and are
                # gone; say so instead of letting later accesses die with
                # an opaque "Array has been deleted"
                raise RuntimeError(
                    "optimizer update failed AFTER its parameter/state "
                    "buffers were donated — training state is destroyed; "
                    "restore from a checkpoint"
                ) from e
            raise
        for p, np_, ns in zip(params, new_params, new_states):
            p._rebind(np_)
            self._accumulators[id(p)] = ns

    def _update(self, p, g, state, lr, t, attr):
        raise NotImplementedError

    # -- paddle API parity -------------------------------------------------
    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list:
            if set_to_zero and p.grad is not None:
                p.grad = Tensor(
                    jnp.zeros_like(p.grad._data), stop_gradient=True
                )
            else:
                p.grad = None

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        """Dygraph minimize = backward + step (ref: optimizer.py minimize)."""
        loss.backward()
        self.step()
        params_grads = [
            (p, p.grad) for p in self._parameter_list if p.grad is not None
        ]
        return None, params_grads

    # -- checkpointing -----------------------------------------------------
    def state_dict(self):
        """Accumulators keyed ``{param.name}_{acc}_0`` plus LR scheduler state
        (ref: optimizer.py state_dict / python/paddle/framework/io.py)."""
        out = collections.OrderedDict()
        for p in self._parameter_list:
            st = self._accumulators.get(id(p))
            if not st:
                continue
            for acc, arr in st.items():
                out[f"{p.name}_{acc}_0"] = Tensor(arr, stop_gradient=True)
        out["global_step"] = self._global_step
        if isinstance(self._learning_rate, LRScheduler):
            out["LR_Scheduler"] = self._learning_rate.state_dict()
        return out

    def set_state_dict(self, state_dict):
        if "LR_Scheduler" in state_dict and isinstance(
            self._learning_rate, LRScheduler
        ):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])
        if "global_step" in state_dict:
            self._global_step = int(
                np.asarray(state_dict["global_step"]).item()
            )
        for p in self._parameter_list:
            st = self._ensure_state(p)
            for acc in list(st):
                key = f"{p.name}_{acc}_0"
                if key in state_dict:
                    src = state_dict[key]
                    arr = src._data if isinstance(src, Tensor) else jnp.asarray(src)
                    if tuple(arr.shape) != tuple(st[acc].shape):
                        raise ValueError(
                            f"shape mismatch for optimizer state {key}: "
                            f"{tuple(arr.shape)} vs {tuple(st[acc].shape)}"
                        )
                    st[acc] = arr.astype(st[acc].dtype)
        return self

    set_dict = set_state_dict

    def __repr__(self):
        lr = (
            self._learning_rate
            if isinstance(self._learning_rate, (int, float))
            else type(self._learning_rate).__name__
        )
        return f"{type(self).__name__}(learning_rate={lr})"
