"""Momentum SGD (ref: python/paddle/optimizer/momentum.py — velocity
accumulator, optional Nesterov)."""
from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer


class Momentum(Optimizer):
    _acc_names = ("velocity",)

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None, multi_precision=False):
        super().__init__(
            learning_rate=learning_rate,
            parameters=parameters,
            weight_decay=weight_decay,
            grad_clip=grad_clip,
            name=name,
            multi_precision=multi_precision,
        )
        self._momentum = float(momentum)
        self._use_nesterov = bool(use_nesterov)

    def _init_state(self, p):
        return {"velocity": jnp.zeros_like(p)}

    def _update(self, p, g, state, lr, t, attr):
        v = self._momentum * state["velocity"] + g
        if self._use_nesterov:
            new_p = p - lr * (g + self._momentum * v)
        else:
            new_p = p - lr * v
        return new_p, {"velocity": v}
