"""NAdam (ref: python/paddle/optimizer/nadam.py — Nesterov-momentum Adam
with the mu-product schedule). mu_product is a device scalar carried in
state (same for every param; kept per-param to stay a pure pytree update)."""
from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer


class NAdam(Optimizer):
    _acc_names = ("moment1", "moment2", "mu_product")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, momentum_decay=0.004, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 multi_precision=False):
        super().__init__(
            learning_rate=learning_rate,
            parameters=parameters,
            weight_decay=weight_decay,
            grad_clip=grad_clip,
            name=name,
            multi_precision=multi_precision,
        )
        self._beta1 = float(beta1)
        self._beta2 = float(beta2)
        self._epsilon = float(epsilon)
        self._momentum_decay = float(momentum_decay)

    def _init_state(self, p):
        return {
            "moment1": jnp.zeros_like(p),
            "moment2": jnp.zeros_like(p),
            "mu_product": jnp.ones((), jnp.float32),
        }

    def _update(self, p, g, state, lr, t, attr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        psi = self._momentum_decay
        mu_t = b1 * (1 - 0.5 * jnp.power(0.96, t * psi))
        mu_t1 = b1 * (1 - 0.5 * jnp.power(0.96, (t + 1) * psi))
        mu_prod = state["mu_product"] * mu_t
        mu_prod_next = mu_prod * mu_t1

        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * jnp.square(g)
        m_hat = (
            mu_t1 * m / (1 - mu_prod_next)
            + (1 - mu_t) * g / (1 - mu_prod)
        )
        v_hat = v / (1 - jnp.power(b2, t))
        new_p = p - lr * m_hat / (jnp.sqrt(v_hat) + eps)
        return new_p, {"moment1": m, "moment2": v, "mu_product": mu_prod}
