"""RAdam — rectified Adam (ref: python/paddle/optimizer/radam.py). The
rectification term is a pure function of the step scalar, so it folds into
the staged update with no extra state."""
from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer


class RAdam(Optimizer):
    _acc_names = ("moment1", "moment2")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False):
        super().__init__(
            learning_rate=learning_rate,
            parameters=parameters,
            weight_decay=weight_decay,
            grad_clip=grad_clip,
            name=name,
            multi_precision=multi_precision,
        )
        self._beta1 = float(beta1)
        self._beta2 = float(beta2)
        self._epsilon = float(epsilon)

    def _init_state(self, p):
        return {"moment1": jnp.zeros_like(p), "moment2": jnp.zeros_like(p)}

    def _update(self, p, g, state, lr, t, attr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * jnp.square(g)
        m_hat = m / (1 - jnp.power(b1, t))

        rho_inf = 2.0 / (1.0 - b2) - 1.0
        b2t = jnp.power(b2, t)
        rho_t = rho_inf - 2.0 * t * b2t / (1.0 - b2t)
        r_num = (rho_t - 4.0) * (rho_t - 2.0) * rho_inf
        r_den = (rho_inf - 4.0) * (rho_inf - 2.0) * rho_t
        # guard the sqrt against the unrectified region (rho_t <= 5)
        r_t = jnp.sqrt(jnp.maximum(r_num / r_den, 0.0))
        v_hat = jnp.sqrt(v / (1.0 - b2t))

        adaptive = p - lr * r_t * m_hat / (v_hat + eps)
        sgd_like = p - lr * m_hat
        return jnp.where(rho_t > 5.0, adaptive, sgd_like), {
            "moment1": m,
            "moment2": v,
        }
