"""Adadelta (ref: python/paddle/optimizer/adadelta.py)."""
from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer


class Adadelta(Optimizer):
    _acc_names = ("avg_squared_grad", "avg_squared_update")

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None, multi_precision=False):
        super().__init__(
            learning_rate=learning_rate,
            parameters=parameters,
            weight_decay=weight_decay,
            grad_clip=grad_clip,
            name=name,
            multi_precision=multi_precision,
        )
        self._epsilon = float(epsilon)
        self._rho = float(rho)

    def _init_state(self, p):
        return {
            "avg_squared_grad": jnp.zeros_like(p),
            "avg_squared_update": jnp.zeros_like(p),
        }

    def _update(self, p, g, state, lr, t, attr):
        rho, eps = self._rho, self._epsilon
        avg_g = rho * state["avg_squared_grad"] + (1 - rho) * jnp.square(g)
        delta = (
            jnp.sqrt(state["avg_squared_update"] + eps)
            / jnp.sqrt(avg_g + eps)
            * g
        )
        avg_u = rho * state["avg_squared_update"] + (1 - rho) * jnp.square(delta)
        return p - lr * delta, {
            "avg_squared_grad": avg_g,
            "avg_squared_update": avg_u,
        }
