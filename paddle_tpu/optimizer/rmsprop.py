"""RMSProp (ref: python/paddle/optimizer/rmsprop.py — centered variant +
momentum accumulator)."""
from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer


class RMSProp(Optimizer):
    _acc_names = ("momentum", "mean_square", "mean_grad")

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False):
        if learning_rate is None:
            raise ValueError("learning_rate is not set")
        super().__init__(
            learning_rate=learning_rate,
            parameters=parameters,
            weight_decay=weight_decay,
            grad_clip=grad_clip,
            name=name,
            multi_precision=multi_precision,
        )
        self._rho = float(rho)
        self._epsilon = float(epsilon)
        self._momentum = float(momentum)
        self._centered = bool(centered)

    def _init_state(self, p):
        st = {
            "momentum": jnp.zeros_like(p),
            "mean_square": jnp.zeros_like(p),
        }
        if self._centered:
            st["mean_grad"] = jnp.zeros_like(p)
        return st

    def _update(self, p, g, state, lr, t, attr):
        rho, eps, mom = self._rho, self._epsilon, self._momentum
        ms = rho * state["mean_square"] + (1 - rho) * jnp.square(g)
        new_state = {"mean_square": ms}
        if self._centered:
            mg = rho * state["mean_grad"] + (1 - rho) * g
            new_state["mean_grad"] = mg
            denom = jnp.sqrt(ms - jnp.square(mg) + eps)
        else:
            denom = jnp.sqrt(ms + eps)
        v = mom * state["momentum"] + lr * g / denom
        new_state["momentum"] = v
        return p - v, new_state
