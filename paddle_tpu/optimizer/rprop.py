"""Rprop — resilient backpropagation (ref: python/paddle/optimizer/rprop.py).
Per-element step sizes adapted by gradient sign agreement; full-batch only."""
from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer


class Rprop(Optimizer):
    _acc_names = ("prev_grad", "learning_rate_elem")

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 name=None, multi_precision=False):
        super().__init__(
            learning_rate=learning_rate,
            parameters=parameters,
            weight_decay=None,
            grad_clip=grad_clip,
            name=name,
            multi_precision=multi_precision,
        )
        self._lr_min = float(learning_rate_range[0])
        self._lr_max = float(learning_rate_range[1])
        self._eta_minus = float(etas[0])
        self._eta_plus = float(etas[1])
        self._initial_lr = float(
            learning_rate if isinstance(learning_rate, (int, float)) else 0.001
        )

    def _init_state(self, p):
        return {
            "prev_grad": jnp.zeros_like(p),
            "learning_rate_elem": jnp.full_like(p, self._initial_lr),
        }

    def _update(self, p, g, state, lr, t, attr):
        sign = jnp.sign(g * state["prev_grad"])
        factor = jnp.where(
            sign > 0, self._eta_plus, jnp.where(sign < 0, self._eta_minus, 1.0)
        )
        lre = jnp.clip(
            state["learning_rate_elem"] * factor, self._lr_min, self._lr_max
        )
        # sign-flip elements take no step and zero their history
        g_eff = jnp.where(sign < 0, 0.0, g)
        new_p = p - lre * jnp.sign(g_eff)
        return new_p, {"prev_grad": g_eff, "learning_rate_elem": lre}
