"""paddle.optimizer analogue (ref: python/paddle/optimizer/__init__.py)."""
from . import lr
from .adadelta import Adadelta
from .adagrad import Adagrad
from .adam import Adam
from .adamax import Adamax
from .adamw import AdamW
from .asgd import ASGD
from .lamb import Lamb
from .lbfgs import LBFGS
from .momentum import Momentum
from .nadam import NAdam
from .optimizer import Optimizer
from .radam import RAdam
from .rmsprop import RMSProp
from .rprop import Rprop
from .sgd import SGD

__all__ = [
    "Optimizer",
    "SGD",
    "Momentum",
    "Adagrad",
    "Adadelta",
    "Adam",
    "AdamW",
    "Adamax",
    "ASGD",
    "Lamb", "LBFGS",
    "NAdam",
    "RAdam",
    "RMSProp",
    "Rprop",
    "lr",
]
