"""L-BFGS (ref: python/paddle/optimizer/lbfgs.py — closure-driven step,
two-loop recursion over a bounded (s, y) history, optional strong-Wolfe
line search).

TPU-native form: the closure re-evaluates loss+grads (eagerly or через a
staged function); the two-loop recursion and the cubic-interpolation
Wolfe search run on flattened jax arrays in ONE jit-compiled direction
program per history length, so the math stays on device and only the
line-search control flow is host-side (it is data-dependent by nature —
the reference drives it from Python for the same reason).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import autograd
from ..core.tensor import Tensor
from .optimizer import Optimizer

__all__ = ["LBFGS"]


def _flatten(arrays):
    return jnp.concatenate([a.reshape(-1).astype(jnp.float32)
                            for a in arrays])


@jax.jit
def _two_loop(grad_flat, s_stack, y_stack, rho, h_diag):
    """L-BFGS two-loop recursion on stacked history [m, n] (zero-padded
    rows carry rho=0 and drop out of the sums)."""

    def bwd(carry, inp):
        q, = carry
        s, y, r = inp
        alpha = r * jnp.dot(s, q)
        return (q - alpha * y,), alpha

    (q,), alphas = jax.lax.scan(
        bwd, (grad_flat,), (s_stack, y_stack, rho), reverse=True
    )
    r = q * h_diag

    def fwd(carry, inp):
        r_, = carry
        s, y, rr, alpha = inp
        beta = rr * jnp.dot(y, r_)
        return (r_ + s * (alpha - beta),), None

    (r,), _ = jax.lax.scan(fwd, (r,), (s_stack, y_stack, rho, alphas))
    return -r


def _cubic_min(x1, f1, g1, x2, f2, g2, lo, hi):
    """Minimizer of the cubic through (x1,f1,g1),(x2,f2,g2), clamped to
    [lo, hi]; bisection fallback on a degenerate discriminant."""
    d1 = g1 + g2 - 3 * (f1 - f2) / (x1 - x2)
    sq = d1 * d1 - g1 * g2
    if sq < 0:
        return (lo + hi) / 2.0
    d2 = sq ** 0.5
    if x1 <= x2:
        t = x2 - (x2 - x1) * ((g2 + d2 - d1) / (g2 - g1 + 2 * d2))
    else:
        t = x1 - (x1 - x2) * ((g1 + d2 - d1) / (g1 - g2 + 2 * d2))
    return min(max(t, lo), hi)


class LBFGS(Optimizer):
    """Closure-driven quasi-Newton optimizer (ref lbfgs.py:342).

        opt = paddle.optimizer.LBFGS(parameters=m.parameters(),
                                     line_search_fn='strong_wolfe')
        def closure():
            opt.clear_grad()
            loss = loss_fn(m(x), y)
            loss.backward()
            return loss
        loss = opt.step(closure)
    """

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9,
                 history_size=100, line_search_fn=None, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(
            learning_rate=learning_rate, parameters=parameters,
            weight_decay=weight_decay, grad_clip=grad_clip, name=name,
        )
        self.max_iter = int(max_iter)
        self.max_eval = int(max_eval if max_eval is not None
                            else max_iter * 5 // 4)
        self.tolerance_grad = float(tolerance_grad)
        self.tolerance_change = float(tolerance_change)
        self.history_size = int(history_size)
        if line_search_fn not in (None, "strong_wolfe"):
            raise ValueError(
                f"line_search_fn must be None or 'strong_wolfe', got "
                f"{line_search_fn!r}"
            )
        self.line_search_fn = line_search_fn
        # persistent across step() calls (the reference's self.state)
        self._hist_s: list = []
        self._hist_y: list = []
        self._prev_grad = None
        self._prev_loss = None
        self._func_evals = 0

    # -- flat-view helpers --------------------------------------------------
    def _params(self):
        return [p for p in self._parameter_list
                if getattr(p, "trainable", not p.stop_gradient)]

    def _gather_flat_grad(self):
        """Flat gradient view, with the optimizer-level grad_clip and
        weight_decay APPLIED (they were silently discarded pre-r6) in the
        base Optimizer's order — clip the raw grads first, THEN add the
        coupled L1/L2 decay term (the decay contribution is never
        clipped, matching _make_step_fn) — so the two-loop direction and
        the Wolfe search see the same effective gradient every other
        optimizer steps on. The matching objective term lives in
        ``_decay_loss`` (the line search must evaluate the function this
        is the gradient of)."""
        params = self._params()
        gs = [
            p.grad._data if p.grad is not None
            else jnp.zeros_like(p._data)
            for p in params
        ]
        if self._grad_clip is not None:
            gs = self._grad_clip._clip_arrays(
                [p._data for p in params], gs,
                [getattr(p, "need_clip", True) for p in params],
            )
        out = []
        for p, g, (kind, coeff) in zip(params, gs, self._decay_cfg()):
            if kind == "l2" and coeff:
                g = g + coeff * p._data.astype(g.dtype)
            elif kind == "l1" and coeff:
                g = g + coeff * jnp.sign(p._data).astype(g.dtype)
            out.append(g)
        return _flatten(out)

    def _decay_cfg(self):
        """Per-param (kind, coeff), with the base Optimizer's override
        rule: a param-level regularizer — even a falsy one like 0.0 —
        beats the optimizer default (ref Optimizer._collect)."""
        from .optimizer import _normalize_weight_decay

        out = []
        for p in self._params():
            preg = getattr(p, "regularizer", None)
            out.append(_normalize_weight_decay(
                preg if preg is not None else self._default_weight_decay
            ))
        return out

    def _decay_loss(self):
        """The objective term whose gradient ``_gather_flat_grad`` adds
        (l2: coeff/2*||p||^2, l1: coeff*|p|_1). Added to every closure
        evaluation so the strong-Wolfe conditions compare f and g of the
        SAME function — without it the decay direction never shows up in
        f and the zoom drives alpha to ~0."""
        total = None
        for p, (kind, coeff) in zip(self._params(), self._decay_cfg()):
            if kind == "l2" and coeff:
                term = 0.5 * coeff * jnp.sum(
                    jnp.square(p._data.astype(jnp.float32))
                )
            elif kind == "l1" and coeff:
                term = coeff * jnp.sum(
                    jnp.abs(p._data.astype(jnp.float32))
                )
            else:
                continue
            total = term if total is None else total + term
        # one device->host sync for the whole decay term, not one per param
        return float(total) if total is not None else 0.0

    def _set_flat_params(self, flat):
        offset = 0
        with autograd.no_grad():
            for p in self._params():
                n = int(p._data.size)
                chunk = flat[offset:offset + n].reshape(p._data.shape)
                p._rebind(chunk.astype(p._data.dtype))
                offset += n

    def _direction(self, grad_flat):
        m = len(self._hist_s)
        if m == 0:
            return -grad_flat
        cap = self.history_size
        s_stack = jnp.stack(self._hist_s[-cap:])
        y_stack = jnp.stack(self._hist_y[-cap:])
        rho = 1.0 / jnp.maximum(
            jnp.einsum("mn,mn->m", s_stack, y_stack), 1e-10
        )
        h_diag = jnp.dot(self._hist_s[-1], self._hist_y[-1]) / jnp.maximum(
            jnp.dot(self._hist_y[-1], self._hist_y[-1]), 1e-10
        )
        return _two_loop(grad_flat, s_stack, y_stack, rho, h_diag)

    # -- strong Wolfe line search (host-driven; data-dependent) -------------
    def _strong_wolfe(self, eval_fn, x0, loss0, grad0, d, alpha0,
                      c1=1e-4, c2=0.9, max_ls=25):
        dg0 = float(jnp.dot(grad0, d))
        if dg0 >= 0:
            return alpha0, loss0, grad0  # not a descent direction
        a_prev, f_prev, g_prev = 0.0, loss0, dg0
        a, f_lo, a_lo, g_lo = alpha0, loss0, 0.0, dg0
        grad_a = grad0
        bracketed = False
        for _ in range(max_ls):
            f_a, grad_a = eval_fn(x0 + a * d)
            dg_a = float(jnp.dot(grad_a, d))
            if f_a > loss0 + c1 * a * dg0 or (bracketed and f_a >= f_prev):
                hi, f_hi, g_hi = a, f_a, dg_a
                lo, f_lo, g_lo = a_prev, f_prev, g_prev
                break
            if abs(dg_a) <= -c2 * dg0:
                return a, f_a, grad_a
            if dg_a >= 0:
                hi, f_hi, g_hi = a_prev, f_prev, g_prev
                lo, f_lo, g_lo = a, f_a, dg_a
                break
            a_prev, f_prev, g_prev = a, f_a, dg_a
            a = a * 2.0
            bracketed = True
        else:
            return a, f_a, grad_a
        # zoom between lo and hi
        for _ in range(max_ls):
            a = _cubic_min(lo, f_lo, g_lo, hi, f_hi, g_hi,
                           min(lo, hi) + 0.1 * abs(hi - lo),
                           max(lo, hi) - 0.1 * abs(hi - lo))
            f_a, grad_a = eval_fn(x0 + a * d)
            dg_a = float(jnp.dot(grad_a, d))
            if f_a > loss0 + c1 * a * dg0 or f_a >= f_lo:
                hi, f_hi, g_hi = a, f_a, dg_a
            else:
                if abs(dg_a) <= -c2 * dg0:
                    return a, f_a, grad_a
                if dg_a * (hi - lo) >= 0:
                    hi, f_hi, g_hi = lo, f_lo, g_lo
                lo, f_lo, g_lo = a, f_a, dg_a
            if abs(hi - lo) < self.tolerance_change:
                break
        return a, f_a, grad_a

    # -- the closure-driven step (ref lbfgs.py:582) -------------------------
    def step(self, closure=None):
        if closure is None:
            raise TypeError(
                "LBFGS.step requires a closure that re-evaluates the "
                "model and returns the loss"
            )

        def evaluate():
            with autograd.enable_grad():
                loss = closure()
            self._func_evals += 1
            # the decay objective term keeps f consistent with the
            # decayed gradient the line search differentiates
            return (
                float(loss.numpy()) + self._decay_loss(),
                self._gather_flat_grad(),
            )

        def eval_at(flat_x):
            self._set_flat_params(flat_x)
            return evaluate()

        loss, grad = evaluate()
        orig_loss = loss
        x = _flatten([p._data for p in self._params()])
        lr = float(self.get_lr())

        for it in range(self.max_iter):
            if float(jnp.max(jnp.abs(grad))) <= self.tolerance_grad:
                break
            d = self._direction(grad)
            # first-ever iteration scales like the reference:
            # min(1, 1/|g|_1) * lr
            if not self._hist_s and it == 0:
                alpha = min(1.0, 1.0 / max(
                    float(jnp.sum(jnp.abs(grad))), 1e-10)) * lr
            else:
                alpha = lr
            prev_x, prev_grad, prev_loss = x, grad, loss
            if self.line_search_fn == "strong_wolfe":
                alpha, loss, grad = self._strong_wolfe(
                    eval_at, x, loss, grad, d, alpha
                )
                x = prev_x + alpha * d
                self._set_flat_params(x)
            else:
                x = x + alpha * d
                self._set_flat_params(x)
                loss, grad = evaluate()
            s = x - prev_x
            y = grad - prev_grad
            if float(jnp.dot(s, y)) > 1e-10:
                self._hist_s.append(s)
                self._hist_y.append(y)
                if len(self._hist_s) > self.history_size:
                    self._hist_s.pop(0)
                    self._hist_y.pop(0)
            if self._func_evals >= self.max_eval:
                break
            if (float(jnp.max(jnp.abs(alpha * d)))
                    <= self.tolerance_change):
                break
            if abs(loss - prev_loss) < self.tolerance_change:
                break

        self._global_step += 1
        return Tensor(jnp.float32(orig_loss), stop_gradient=True)

    def _update(self, p, g, state, lr, t, attr):  # pragma: no cover
        raise RuntimeError(
            "LBFGS is closure-driven; call step(closure), not step()"
        )

    def state_dict(self):
        return {
            "hist_s": list(self._hist_s),
            "hist_y": list(self._hist_y),
            "func_evals": self._func_evals,
            "global_step": self._global_step,
        }

    def set_state_dict(self, state_dict):
        self._hist_s = list(state_dict.get("hist_s", []))
        self._hist_y = list(state_dict.get("hist_y", []))
        self._func_evals = int(state_dict.get("func_evals", 0))
        self._global_step = int(state_dict.get("global_step", 0))
