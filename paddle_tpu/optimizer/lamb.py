"""LAMB — layerwise adaptive large-batch optimizer
(ref: python/paddle/optimizer/lamb.py; phi/kernels/funcs adamw/lamb functors).
Trust ratio r = ||p|| / ||update|| rescales the Adam step per layer."""
from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer


class Lamb(Optimizer):
    _acc_names = ("moment1", "moment2")

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 name=None, multi_precision=False):
        super().__init__(
            learning_rate=learning_rate,
            parameters=parameters,
            weight_decay=None,
            grad_clip=grad_clip,
            name=name,
            multi_precision=multi_precision,
        )
        self._lamb_weight_decay = float(lamb_weight_decay)
        self._beta1 = float(beta1)
        self._beta2 = float(beta2)
        self._epsilon = float(epsilon)
        self._exclude_fn = exclude_from_weight_decay_fn
        self._exclude_mask = ()

    def _init_state(self, p):
        return {"moment1": jnp.zeros_like(p), "moment2": jnp.zeros_like(p)}

    def _collect(self):
        triples = super()._collect()
        self._exclude_mask = tuple(
            bool(self._exclude_fn(p)) if self._exclude_fn is not None else False
            for p, _, _ in triples
        )
        self._collect_index = 0
        return triples

    def _update(self, p, g, state, lr, t, attr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        i = self._collect_index
        self._collect_index += 1
        excluded = self._exclude_mask[i] if i < len(self._exclude_mask) else False
        wd = 0.0 if excluded else self._lamb_weight_decay

        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * jnp.square(g)
        m_hat = m / (1 - jnp.power(b1, t))
        v_hat = v / (1 - jnp.power(b2, t))
        update = m_hat / (jnp.sqrt(v_hat) + eps) + wd * p

        p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
        u_norm = jnp.sqrt(jnp.sum(jnp.square(update)))
        trust = jnp.where(
            (p_norm > 0) & (u_norm > 0), p_norm / u_norm, 1.0
        )
        return p - lr * trust * update, {"moment1": m, "moment2": v}
