"""Adam (ref: python/paddle/optimizer/adam.py; kernel math
phi/kernels/funcs/adam_functors.h). Bias correction is computed from the
global step scalar instead of per-param beta-pow accumulators — one less
state buffer per parameter, same math."""
from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer


class Adam(Optimizer):
    _acc_names = ("moment1", "moment2")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, name=None,
                 multi_precision=False, amsgrad=False):
        super().__init__(
            learning_rate=learning_rate,
            parameters=parameters,
            weight_decay=weight_decay,
            grad_clip=grad_clip,
            name=name,
            multi_precision=multi_precision,
        )
        self._beta1 = float(beta1)
        self._beta2 = float(beta2)
        self._epsilon = float(epsilon)
        self._amsgrad = bool(amsgrad)
        if amsgrad:
            self._acc_names = ("moment1", "moment2", "moment2_max")

    def _init_state(self, p):
        st = {
            "moment1": jnp.zeros_like(p),
            "moment2": jnp.zeros_like(p),
        }
        if self._amsgrad:
            st["moment2_max"] = jnp.zeros_like(p)
        return st

    def _adam_core(self, p, g, m, v, lr, t):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * jnp.square(g)
        # lr_t = lr * sqrt(1-b2^t) / (1-b1^t): same rescaled form the
        # reference kernel uses (adam_functors.h), fusing both corrections.
        # In this form epsilon must carry the same sqrt(1-b2^t) factor to
        # stay equivalent to the textbook vhat form (adam_functors.h:238).
        corr2 = jnp.sqrt(1 - jnp.power(b2, t))
        lr_t = lr * corr2 / (1 - jnp.power(b1, t))
        return m, v, lr_t, eps * corr2

    def _update(self, p, g, state, lr, t, attr):
        m, v, lr_t, eps_t = self._adam_core(
            p, g, state["moment1"], state["moment2"], lr, t
        )
        new_state = {"moment1": m, "moment2": v}
        denom_v = v
        if self._amsgrad:
            v_max = jnp.maximum(state["moment2_max"], v)
            new_state["moment2_max"] = v_max
            denom_v = v_max
        new_p = p - lr_t * m / (jnp.sqrt(denom_v) + eps_t)
        return new_p, new_state
