"""ASGD — averaged SGD (ref: python/paddle/optimizer/asgd.py). Maintains the
running Polyak average of the iterates in ``avg_param``; ``finalize()`` swaps
the averages into the live parameters."""
from __future__ import annotations

import jax.numpy as jnp

from ..core import autograd
from .optimizer import Optimizer


class ASGD(Optimizer):
    _acc_names = ("avg_param",)

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 multi_precision=False):
        super().__init__(
            learning_rate=learning_rate,
            parameters=parameters,
            weight_decay=weight_decay,
            grad_clip=grad_clip,
            name=name,
            multi_precision=multi_precision,
        )
        self._batch_num = int(batch_num)

    def _init_state(self, p):
        return {"avg_param": p}

    def _update(self, p, g, state, lr, t, attr):
        new_p = p - lr * g
        # running average over the window: a_t = a + (p - a) / min(t, n)
        n = jnp.minimum(t, float(max(self._batch_num, 1)))
        avg = state["avg_param"] + (new_p - state["avg_param"]) / n
        return new_p, {"avg_param": avg}

    @autograd.no_grad()
    def finalize(self):
        """Copy the averaged parameters into the model."""
        for p in self._parameter_list:
            st = self._accumulators.get(id(p))
            if st and "avg_param" in st:
                p._rebind(st["avg_param"].astype(p._data.dtype))
