"""Learning-rate schedulers.

API surface of the reference's ``paddle.optimizer.lr`` (ref:
python/paddle/optimizer/lr.py — 19 scheduler classes on an ``LRScheduler``
base with step()/get_lr()/state_dict()). The schedulers are host-side pure
Python: the optimizer reads ``scheduler()`` once per step and feeds the value
into the staged XLA update as a scalar operand, so changing the LR never
triggers recompilation.
"""
from __future__ import annotations

import math

__all__ = [
    "LRScheduler",
    "NoamDecay",
    "PiecewiseDecay",
    "NaturalExpDecay",
    "InverseTimeDecay",
    "PolynomialDecay",
    "LinearWarmup",
    "ExponentialDecay",
    "MultiStepDecay",
    "StepDecay",
    "LambdaDecay",
    "MultiplicativeDecay",
    "ReduceOnPlateau",
    "CosineAnnealingDecay",
    "CosineAnnealingWarmRestarts",
    "CyclicLR",
    "OneCycleLR",
    "LinearLR",
]


class LRScheduler:
    """Base class (ref: python/paddle/optimizer/lr.py:64 LRScheduler).

    Subclasses implement ``get_lr()`` reading ``self.last_epoch`` /
    ``self.base_lr``. ``step()`` advances the epoch counter and refreshes
    ``self.last_lr``.
    """

    def __init__(self, learning_rate=0.1, last_epoch=-1, verbose=False):
        if not isinstance(learning_rate, (int, float)):
            raise TypeError(
                f"learning_rate must be float, got {type(learning_rate)}"
            )
        self.base_lr = float(learning_rate)
        self.last_epoch = last_epoch
        self.verbose = verbose
        self.last_lr = self.base_lr
        self.step()

    def __call__(self):
        return self.last_lr

    def step(self, epoch=None):
        if epoch is None:
            self.last_epoch += 1
        else:
            self.last_epoch = epoch
        self.last_lr = self.get_lr()
        if self.verbose:
            print(
                f"Epoch {self.last_epoch}: {type(self).__name__} set "
                f"learning rate to {self.last_lr}."
            )

    def get_lr(self):
        raise NotImplementedError

    def state_dict(self):
        state = {}
        for k, v in self.__dict__.items():
            if k == "verbose" or callable(v):
                continue
            if isinstance(v, (int, float, bool, str, list, tuple, dict, type(None))):
                state[k] = v
        return state

    def set_state_dict(self, state_dict):
        for k, v in state_dict.items():
            if k in self.__dict__:
                self.__dict__[k] = v
        return self

    set_dict = set_state_dict
    state_keys = state_dict


class NoamDecay(LRScheduler):
    """lr = d_model^-0.5 * min(step^-0.5, step * warmup^-1.5)
    (ref: lr.py NoamDecay)."""

    def __init__(self, d_model, warmup_steps, learning_rate=1.0,
                 last_epoch=-1, verbose=False):
        self.d_model = d_model
        self.warmup_steps = warmup_steps
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 1)
        a = step ** -0.5
        b = step * (self.warmup_steps ** -1.5)
        return self.base_lr * (self.d_model ** -0.5) * min(a, b)


class PiecewiseDecay(LRScheduler):
    """Step-function schedule over boundaries (ref: lr.py PiecewiseDecay)."""

    def __init__(self, boundaries, values, last_epoch=-1, verbose=False):
        if len(values) != len(boundaries) + 1:
            raise ValueError(
                "values must have one more element than boundaries"
            )
        self.boundaries = list(boundaries)
        self.values = list(values)
        super().__init__(values[0], last_epoch, verbose)

    def get_lr(self):
        for b, v in zip(self.boundaries, self.values):
            if self.last_epoch < b:
                return v
        return self.values[-1]


class NaturalExpDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * math.exp(-self.gamma * self.last_epoch)


class InverseTimeDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr / (1 + self.gamma * self.last_epoch)


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate, decay_steps, end_lr=0.0001, power=1.0,
                 cycle=False, last_epoch=-1, verbose=False):
        self.decay_steps = decay_steps
        self.end_lr = end_lr
        self.power = power
        self.cycle = cycle
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = self.last_epoch
        decay_steps = self.decay_steps
        if self.cycle:
            div = math.ceil(step / decay_steps) if step > 0 else 1
            decay_steps = decay_steps * max(div, 1)
        else:
            step = min(step, decay_steps)
        frac = (1 - step / float(decay_steps)) ** self.power
        return (self.base_lr - self.end_lr) * frac + self.end_lr


class LinearWarmup(LRScheduler):
    """Linear ramp into a wrapped scheduler or constant lr
    (ref: lr.py LinearWarmup)."""

    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr,
                 last_epoch=-1, verbose=False):
        if not isinstance(learning_rate, (float, int, LRScheduler)):
            raise TypeError("learning_rate must be float or LRScheduler")
        self.learning_rate = learning_rate
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr
        base = (
            learning_rate
            if isinstance(learning_rate, (float, int))
            else learning_rate.base_lr
        )
        super().__init__(base, last_epoch, verbose)

    def get_lr(self):
        if self.last_epoch < self.warmup_steps:
            return (self.end_lr - self.start_lr) * (
                self.last_epoch / float(self.warmup_steps)
            ) + self.start_lr
        if isinstance(self.learning_rate, LRScheduler):
            self.learning_rate.step(self.last_epoch - self.warmup_steps)
            return self.learning_rate()
        return float(self.learning_rate)

    def state_dict(self):
        state = super().state_dict()
        state.pop("learning_rate", None)
        if isinstance(self.learning_rate, LRScheduler):
            state["LinearWarmup_LR"] = self.learning_rate.state_dict()
        return state

    def set_state_dict(self, state_dict):
        inner = state_dict.pop("LinearWarmup_LR", None)
        if inner is not None and isinstance(self.learning_rate, LRScheduler):
            self.learning_rate.set_state_dict(inner)
        return super().set_state_dict(state_dict)


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * (self.gamma ** self.last_epoch)


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1, last_epoch=-1,
                 verbose=False):
        if not all(
            milestones[i] < milestones[i + 1]
            for i in range(len(milestones) - 1)
        ):
            raise ValueError("milestones must be increasing")
        self.milestones = list(milestones)
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        passed = sum(1 for m in self.milestones if self.last_epoch >= m)
        return self.base_lr * (self.gamma ** passed)


class StepDecay(LRScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1, last_epoch=-1,
                 verbose=False):
        self.step_size = step_size
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * (
            self.gamma ** (max(self.last_epoch, 0) // self.step_size)
        )


class LambdaDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.lr_lambda(self.last_epoch)

    def state_dict(self):
        state = super().state_dict()
        state.pop("lr_lambda", None)
        return state


class MultiplicativeDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def step(self, epoch=None):
        # O(1) incremental path only once last_lr provably corresponds to
        # last_epoch (i.e. after one full get_lr); a construction-time
        # last_epoch jump or explicit epoch uses the full product.
        if epoch is None and getattr(self, "_incremental_ok", False):
            self.last_epoch += 1
            if self.last_epoch > 0:
                self.last_lr = self.last_lr * self.lr_lambda(self.last_epoch)
            if self.verbose:
                print(
                    f"Epoch {self.last_epoch}: MultiplicativeDecay set "
                    f"learning rate to {self.last_lr}."
                )
            return
        super().step(epoch)
        self._incremental_ok = True

    def get_lr(self):
        cur = self.base_lr
        for epoch in range(1, self.last_epoch + 1):
            cur *= self.lr_lambda(epoch)
        return cur

    def state_dict(self):
        state = super().state_dict()
        state.pop("lr_lambda", None)
        return state


class ReduceOnPlateau(LRScheduler):
    """Reduce lr when a metric has stopped improving
    (ref: lr.py ReduceOnPlateau). ``step(metrics)`` takes the watched value."""

    def __init__(self, learning_rate, mode="min", factor=0.1, patience=10,
                 threshold=1e-4, threshold_mode="rel", cooldown=0, min_lr=0,
                 epsilon=1e-8, verbose=False):
        if mode not in ("min", "max"):
            raise ValueError("mode must be 'min' or 'max'")
        if threshold_mode not in ("rel", "abs"):
            raise ValueError("threshold_mode must be 'rel' or 'abs'")
        if factor >= 1.0:
            raise ValueError("factor must be < 1.0")
        self.mode = mode
        self.factor = factor
        self.patience = patience
        self.threshold = threshold
        self.threshold_mode = threshold_mode
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.epsilon = epsilon
        self.cooldown_counter = 0
        self.best = None
        self.num_bad_epochs = 0
        # no super().step() in init: plateau stepping is metric-driven
        self.base_lr = float(learning_rate)
        self.last_lr = float(learning_rate)
        self.last_epoch = 0
        self.verbose = verbose

    def step(self, metrics, epoch=None):
        if epoch is None:
            self.last_epoch += 1
        else:
            self.last_epoch = epoch
        try:
            metrics = float(metrics)
        except (TypeError, ValueError):
            import numpy as np

            metrics = float(np.asarray(metrics).item())

        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
        else:
            if self.best is None or self._is_better(metrics):
                self.best = metrics
                self.num_bad_epochs = 0
            else:
                self.num_bad_epochs += 1
            if self.num_bad_epochs > self.patience:
                self.cooldown_counter = self.cooldown
                self.num_bad_epochs = 0
                new_lr = max(self.last_lr * self.factor, self.min_lr)
                if self.last_lr - new_lr > self.epsilon:
                    self.last_lr = new_lr
                    if self.verbose:
                        print(
                            f"Epoch {self.last_epoch}: ReduceOnPlateau set "
                            f"learning rate to {self.last_lr}."
                        )

    def _is_better(self, current):
        if self.mode == "min":
            if self.threshold_mode == "rel":
                return current < self.best - self.best * self.threshold
            return current < self.best - self.threshold
        if self.threshold_mode == "rel":
            return current > self.best + self.best * self.threshold
        return current > self.best + self.threshold

    def get_lr(self):
        return self.last_lr


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate, T_max, eta_min=0, last_epoch=-1,
                 verbose=False):
        self.T_max = T_max
        self.eta_min = float(eta_min)
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return (
            self.eta_min
            + (self.base_lr - self.eta_min)
            * (1 + math.cos(math.pi * self.last_epoch / self.T_max))
            / 2
        )


class CosineAnnealingWarmRestarts(LRScheduler):
    def __init__(self, learning_rate, T_0, T_mult=1, eta_min=0, last_epoch=-1,
                 verbose=False):
        if T_0 <= 0 or not isinstance(T_0, int):
            raise ValueError("T_0 must be a positive integer")
        if T_mult < 1 or not isinstance(T_mult, int):
            raise ValueError("T_mult must be an integer >= 1")
        self.T_0 = T_0
        self.T_i = T_0
        self.T_mult = T_mult
        self.eta_min = float(eta_min)
        self.T_cur = last_epoch
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return (
            self.eta_min
            + (self.base_lr - self.eta_min)
            * (1 + math.cos(math.pi * self.T_cur / self.T_i))
            / 2
        )

    def step(self, epoch=None):
        if epoch is None:
            epoch = self.last_epoch + 1
            self.T_cur += 1
            if self.T_cur >= self.T_i:
                self.T_cur -= self.T_i
                self.T_i *= self.T_mult
        else:
            if epoch >= self.T_0:
                if self.T_mult == 1:
                    self.T_cur = epoch % self.T_0
                    self.T_i = self.T_0
                else:
                    n = int(
                        math.log(
                            epoch / self.T_0 * (self.T_mult - 1) + 1,
                            self.T_mult,
                        )
                    )
                    self.T_cur = epoch - self.T_0 * (
                        self.T_mult ** n - 1
                    ) / (self.T_mult - 1)
                    self.T_i = self.T_0 * self.T_mult ** n
            else:
                self.T_i = self.T_0
                self.T_cur = epoch
        self.last_epoch = epoch
        self.last_lr = self.get_lr()


class CyclicLR(LRScheduler):
    """Triangular cyclic schedule (ref: lr.py CyclicLR)."""

    def __init__(self, base_learning_rate, max_learning_rate,
                 step_size_up=2000, step_size_down=None, mode="triangular",
                 exp_gamma=1.0, scale_fn=None, scale_mode="cycle",
                 last_epoch=-1, verbose=False):
        self.max_lr = max_learning_rate
        self.step_size_up = step_size_up
        self.step_size_down = (
            step_size_down if step_size_down is not None else step_size_up
        )
        self.total_size = self.step_size_up + self.step_size_down
        self.mode = mode
        self.exp_gamma = exp_gamma
        self._custom_scale_fn = scale_fn
        self.scale_mode = scale_mode if scale_fn else {
            "triangular": "cycle",
            "triangular2": "cycle",
            "exp_range": "iterations",
        }.get(mode, "cycle")
        super().__init__(base_learning_rate, last_epoch, verbose)

    def _scale(self, x):
        if self._custom_scale_fn is not None:
            return self._custom_scale_fn(x)
        if self.mode == "triangular":
            return 1.0
        if self.mode == "triangular2":
            return 1 / (2.0 ** (x - 1))
        return self.exp_gamma ** x

    def get_lr(self):
        iterations = self.last_epoch
        cycle = 1 + iterations // self.total_size
        pct_per_step = (iterations % self.total_size) / self.total_size
        pct_up = self.step_size_up / self.total_size
        if pct_per_step <= pct_up:
            scale_factor = pct_per_step / pct_up
        else:
            scale_factor = (1 - pct_per_step) / (1 - pct_up)
        base_height = (self.max_lr - self.base_lr) * scale_factor
        x = cycle if self.scale_mode == "cycle" else iterations
        return self.base_lr + base_height * self._scale(x)

    def state_dict(self):
        state = super().state_dict()
        state.pop("_custom_scale_fn", None)
        return state


class OneCycleLR(LRScheduler):
    """1cycle policy (ref: lr.py OneCycleLR), cosine annealing strategy."""

    def __init__(self, max_learning_rate, total_steps, divide_factor=25.0,
                 end_learning_rate=0.0001, phase_pct=0.3,
                 anneal_strategy="cos", three_phase=False, last_epoch=-1,
                 verbose=False):
        self.max_lr = max_learning_rate
        self.total_steps = total_steps
        initial_lr = max_learning_rate / divide_factor
        self.end_lr = end_learning_rate
        self.anneal_strategy = anneal_strategy
        if three_phase:
            self._phases = [
                (float(phase_pct * total_steps) - 1, initial_lr,
                 max_learning_rate),
                (float(2 * phase_pct * total_steps) - 2, max_learning_rate,
                 initial_lr),
                (total_steps - 1, initial_lr, end_learning_rate),
            ]
        else:
            self._phases = [
                (float(phase_pct * total_steps) - 1, initial_lr,
                 max_learning_rate),
                (total_steps - 1, max_learning_rate, end_learning_rate),
            ]
        super().__init__(initial_lr, last_epoch, verbose)

    def _anneal(self, start, end, pct):
        if self.anneal_strategy == "cos":
            return end + (start - end) / 2.0 * (math.cos(math.pi * pct) + 1)
        return (end - start) * pct + start

    def get_lr(self):
        step = self.last_epoch
        start_step = 0.0
        for end_step, start_lr, end_lr in self._phases:
            if step <= end_step or end_step == self._phases[-1][0]:
                pct = (step - start_step) / (end_step - start_step)
                return self._anneal(start_lr, end_lr, min(max(pct, 0.0), 1.0))
            start_step = end_step
        return self.end_lr

    def state_dict(self):
        state = super().state_dict()
        state.pop("_phases", None)
        return state


class LinearLR(LRScheduler):
    """Linearly ramp the multiplier from start_factor to end_factor over
    total_steps (ref: lr.py LinearLR)."""

    def __init__(self, learning_rate, total_steps, start_factor=1.0 / 3,
                 end_factor=1.0, last_epoch=-1, verbose=False):
        if start_factor > 1.0 or start_factor <= 0:
            raise ValueError("start_factor must be in (0, 1]")
        self.total_steps = total_steps
        self.start_factor = start_factor
        self.end_factor = end_factor
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        pct = min(max(self.last_epoch, 0), self.total_steps) / self.total_steps
        factor = self.start_factor + (self.end_factor - self.start_factor) * pct
        return self.base_lr * factor
