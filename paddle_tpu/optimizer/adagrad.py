"""Adagrad (ref: python/paddle/optimizer/adagrad.py)."""
from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer


class Adagrad(Optimizer):
    _acc_names = ("moment",)

    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 initial_accumulator_value=0.0, multi_precision=False):
        super().__init__(
            learning_rate=learning_rate,
            parameters=parameters,
            weight_decay=weight_decay,
            grad_clip=grad_clip,
            name=name,
            multi_precision=multi_precision,
        )
        self._epsilon = float(epsilon)
        self._initial = float(initial_accumulator_value)

    def _init_state(self, p):
        return {"moment": jnp.full_like(p, self._initial)}

    def _update(self, p, g, state, lr, t, attr):
        moment = state["moment"] + jnp.square(g)
        new_p = p - lr * g / (jnp.sqrt(moment) + self._epsilon)
        return new_p, {"moment": moment}
