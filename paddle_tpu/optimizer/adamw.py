"""AdamW — decoupled weight decay (ref: python/paddle/optimizer/adamw.py:32).

``weight_decay`` here is the decoupled coefficient (applied directly to the
parameter, scaled by lr), NOT a coupled regularizer; ``apply_decay_param_fun``
filters which params decay, matching the reference's API.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .adam import Adam


class AdamW(Adam):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, amsgrad=False,
                 name=None):
        # weight_decay deliberately NOT forwarded to the base class: it is
        # decoupled, not a grad-coupled regularizer.
        super().__init__(
            learning_rate=learning_rate,
            beta1=beta1,
            beta2=beta2,
            epsilon=epsilon,
            parameters=parameters,
            weight_decay=None,
            grad_clip=grad_clip,
            name=name,
            multi_precision=multi_precision,
            amsgrad=amsgrad,
        )
        self._coeff = float(weight_decay)
        self._lr_ratio = lr_ratio
        self._apply_decay_param_fun = apply_decay_param_fun
        self._decay_names = None

    def _group_weight_decay(self, group):
        # Per-group "weight_decay" in AdamW stays decoupled; never coupled.
        return None, 0.0

    def _collect(self):
        triples = super()._collect()
        # Record, positionally, which params decay this step (static mask).
        self._decay_names = tuple(
            self._apply_decay_param_fun(p.name)
            if self._apply_decay_param_fun is not None
            else True
            for p, _, _ in triples
        )
        self._lr_ratios = tuple(
            float(self._lr_ratio(p)) if self._lr_ratio is not None else 1.0
            for p, _, _ in triples
        )
        return triples


    def _make_step_fn(self):
        clip = self._grad_clip

        def step_fn(attrs, decay_mask, lr_ratios, lr, t, found_inf,
                    params, grads, states):
            if clip is not None:
                grads = clip._clip_arrays(
                    params, grads, [a.need_clip for a in attrs]
                )
            new_params, new_states = [], []
            for i, (p, g, s, a) in enumerate(
                zip(params, grads, states, attrs)
            ):
                compute_p = s["master_weight"] if a.multi_precision else p
                g = g.astype(compute_p.dtype)
                eff_lr = lr * a.lr_scale * lr_ratios[i]
                if decay_mask[i] and self._coeff != 0.0:
                    compute_p = compute_p * (1.0 - eff_lr * self._coeff)
                np_, ns = self._update(compute_p, g, s, eff_lr, t, a)
                if a.multi_precision:
                    ns = dict(ns)
                    ns["master_weight"] = np_
                    np_ = np_.astype(p.dtype)
                np_ = jnp.where(found_inf, p, np_)
                ns = {
                    k: jnp.where(found_inf, s[k], v) if k in s else v
                    for k, v in ns.items()
                }
                new_params.append(np_)
                new_states.append(ns)
            return new_params, new_states

        jitted = jax.jit(step_fn, static_argnums=(0, 1, 2))

        def wrapper(attrs, lr, t, found_inf, params, grads, states):
            return jitted(
                attrs, self._decay_names, self._lr_ratios,
                lr, t, found_inf, params, grads, states,
            )

        return wrapper
