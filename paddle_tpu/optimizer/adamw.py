"""AdamW — decoupled weight decay (ref: python/paddle/optimizer/adamw.py:32).

``weight_decay`` here is the decoupled coefficient (applied directly to the
parameter, scaled by lr) rather than a grad-coupled regularizer;
``apply_decay_param_fun`` filters which params decay and ``lr_ratio`` scales
per-param learning rates (the layerwise-decay hook), matching the
reference's API. Both fold into the base class's staged update through the
``_param_extras`` hook — param-level coupled regularizers still apply.
"""
from __future__ import annotations

from .adam import Adam


class AdamW(Adam):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, amsgrad=False,
                 name=None):
        # weight_decay deliberately NOT forwarded to the base class: it is
        # decoupled, not a grad-coupled regularizer.
        super().__init__(
            learning_rate=learning_rate,
            beta1=beta1,
            beta2=beta2,
            epsilon=epsilon,
            parameters=parameters,
            weight_decay=None,
            grad_clip=grad_clip,
            name=name,
            multi_precision=multi_precision,
            amsgrad=amsgrad,
        )
        self._coeff = float(weight_decay)
        self._lr_ratio = lr_ratio
        self._apply_decay_param_fun = apply_decay_param_fun

    def _group_weight_decay(self, group):
        # A per-group "weight_decay" on AdamW is also decoupled, never
        # coupled; the group coefficient is consumed in _param_extras.
        return None, 0.0

    def _param_extras(self, p, group=None):
        decay = self._coeff
        if group is not None and group.get("weight_decay") is not None:
            gwd = group["weight_decay"]
            decay = float(getattr(gwd, "coeff", gwd))
        if self._apply_decay_param_fun is not None and not (
            self._apply_decay_param_fun(p.name)
        ):
            decay = 0.0
        ratio = float(self._lr_ratio(p)) if self._lr_ratio is not None else 1.0
        return decay, ratio
