"""jit.save / jit.load — AOT model export over StableHLO.

ref: python/paddle/jit/api.py jit.save -> TranslatedLayer
(jit/translated_layer.py) and the inference deployment path
(fluid/inference AnalysisPredictor). TPU-native: the deployable artifact
is a serialized StableHLO program (jax.export) + the parameter arrays —
the same compiled-serving shape as §2.14 #28 (AOT XLA executables); no
TensorRT analogue is needed because XLA is the server compiler too.

Artifact layout at <path>:
    <path>.pdmodel   serialized StableHLO (jax.export blob)
    <path>.pdiparams parameters + buffers (framework save format)
    <path>.pdmeta    input spec metadata (json)
"""
from __future__ import annotations

import json

import numpy as np

import jax
import jax.numpy as jnp
from jax import export as jax_export

from ..core import autograd
from ..core.dtype import convert_dtype
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer

__all__ = ["save", "load", "InputSpec", "TranslatedLayer"]


class InputSpec:
    """ref: python/paddle/static/input.py InputSpec."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = list(shape)
        self.dtype = convert_dtype(dtype).name
        self.name = name

    def _sds(self):
        shape = [1 if (d is None or d < 0) else d for d in self.shape]
        return jax.ShapeDtypeStruct(
            tuple(shape), convert_dtype(self.dtype).jnp_dtype
        )

    def to_json(self):
        return {"shape": self.shape, "dtype": self.dtype, "name": self.name}

    @classmethod
    def from_json(cls, d):
        return cls(d["shape"], d["dtype"], d.get("name"))


def save(layer, path, input_spec=None, **config):
    """Stage layer.forward on the given specs and export (ref jit/api.py
    jit.save). Dynamic dims in specs are exported at size 1 (XLA static
    shapes; re-export per bucket for other sizes)."""
    if isinstance(layer, Layer):
        fn = layer.forward
        params = [p for _, p in layer.named_parameters()]
        buffers = [b for _, b in layer.named_buffers()]
        state = layer.state_dict()
    else:
        fn = layer
        params, buffers, state = [], [], {}
    if input_spec is None:
        raise ValueError("jit.save requires input_spec")
    specs = [
        s if isinstance(s, InputSpec) else InputSpec(**s)
        for s in input_spec
    ]

    p_arrays = [p._data for p in params]
    b_arrays = [b._data for b in buffers]

    def staged(param_arrays, buffer_arrays, *inputs):
        from .api import _swap_payloads
        from ..core import random as random_mod

        old_p = _swap_payloads(params, param_arrays)
        old_b = _swap_payloads(buffers, buffer_arrays)
        # rng-marked ops split the global generator key during tracing;
        # restore it afterwards so no tracer escapes into eager state (the
        # exported program bakes the keys it drew — inference artifacts are
        # deterministic by design)
        old_key = random_mod.default_generator._key
        try:
            with autograd.no_grad():
                out = fn(*[Tensor(i) for i in inputs])
        finally:
            _swap_payloads(params, old_p)
            _swap_payloads(buffers, old_b)
            random_mod.default_generator._key = old_key
        return jax.tree_util.tree_map(
            lambda o: o._data if isinstance(o, Tensor) else o,
            out,
            is_leaf=lambda o: isinstance(o, Tensor),
        )

    exported = jax_export.export(jax.jit(staged))(
        [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in p_arrays],
        [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in b_arrays],
        *[s._sds() for s in specs],
    )
    with open(path + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    from ..framework.io_api import save as fsave

    fsave({"params": state}, path + ".pdiparams")
    with open(path + ".pdmeta", "w") as f:
        json.dump(
            {
                "input_spec": [s.to_json() for s in specs],
                "param_names": [
                    name for name, _ in (
                        layer.named_parameters()
                        if isinstance(layer, Layer) else []
                    )
                ],
                "buffer_names": [
                    name for name, _ in (
                        layer.named_buffers()
                        if isinstance(layer, Layer) else []
                    )
                ],
            },
            f,
        )


class TranslatedLayer:
    """Loaded inference artifact (ref jit/translated_layer.py). Runs the
    deserialized StableHLO program; parameters are baked as call inputs."""

    def __init__(self, exported, param_arrays, buffer_arrays, meta):
        self._exported = exported
        self._params = param_arrays
        self._buffers = buffer_arrays
        self._meta = meta

    def __call__(self, *inputs):
        arrs = [
            i._data if isinstance(i, Tensor) else jnp.asarray(i)
            for i in inputs
        ]
        out = self._exported.call(self._params, self._buffers, *arrs)
        return jax.tree_util.tree_map(
            lambda a: Tensor(a, stop_gradient=True), out
        )

    forward = __call__

    def eval(self):
        return self

    @property
    def input_spec(self):
        return [
            InputSpec.from_json(d) for d in self._meta["input_spec"]
        ]


def load(path, **config):
    """ref jit/api.py paddle.jit.load."""
    with open(path + ".pdmodel", "rb") as f:
        exported = jax_export.deserialize(f.read())
    from ..framework.io_api import load as fload

    blob = fload(path + ".pdiparams")
    with open(path + ".pdmeta") as f:
        meta = json.load(f)
    state = blob["params"]
    p_arrays = [
        state[n]._data if isinstance(state[n], Tensor)
        else jnp.asarray(np.asarray(state[n]))
        for n in meta["param_names"]
    ]
    b_arrays = [
        state[n]._data if isinstance(state[n], Tensor)
        else jnp.asarray(np.asarray(state[n]))
        for n in meta["buffer_names"]
    ]
    return TranslatedLayer(exported, p_arrays, b_arrays, meta)
