"""jit.save / jit.load — AOT model export over StableHLO.

ref: python/paddle/jit/api.py jit.save -> TranslatedLayer
(jit/translated_layer.py) and the inference deployment path
(fluid/inference AnalysisPredictor). TPU-native: the deployable artifact
is a serialized StableHLO program (jax.export) + the parameter arrays —
the same compiled-serving shape as §2.14 #28 (AOT XLA executables); no
TensorRT analogue is needed because XLA is the server compiler too.

Artifact layout at <path>:
    <path>.pdmodel   serialized StableHLO (jax.export blob)
    <path>.pdiparams parameters + buffers (framework save format)
    <path>.pdmeta    input spec metadata + export versions (json)

Dynamic dims: XLA programs have static shapes, so a spec dim of
``None``/``-1`` needs a policy. ``save(..., bucket_sizes={dim: [sizes]})``
exports ONE PROGRAM PER BUCKET COMBINATION (the ``jit.bucketing``
policy applied at export time) as ``<path>.b<sizes>.pdmodel`` files;
``load`` returns a TranslatedLayer that picks the right program by
shape, pads inputs up to the bucket, and slices padded output dims
back. Without ``bucket_sizes`` a dynamic dim is exported at size 1
(call sites must match exactly).

Version safety: the pdmeta records the exporting jax version and
calling-convention version; ``load`` raises a clear ValueError naming
both sides when a blob cannot be deserialized under the running jax,
instead of failing deep inside the deserializer.
"""
from __future__ import annotations

import itertools
import json

import numpy as np

import jax
import jax.numpy as jnp
from jax import export as jax_export

from ..core import autograd
from ..core.dtype import convert_dtype
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from .bucketing import next_bucket

__all__ = ["save", "load", "InputSpec", "TranslatedLayer"]

_META_FORMAT = 2


class InputSpec:
    """ref: python/paddle/static/input.py InputSpec."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = list(shape)
        self.dtype = convert_dtype(dtype).name
        self.name = name

    def dynamic_dims(self):
        return [
            d for d, v in enumerate(self.shape) if v is None or v < 0
        ]

    def _sds(self, dim_sizes=None):
        """Concrete ShapeDtypeStruct; dynamic dims resolve through
        ``dim_sizes`` ({dim: size}, the bucket combination) or 1."""
        dim_sizes = dim_sizes or {}
        shape = [
            (dim_sizes.get(d, 1) if (v is None or v < 0) else v)
            for d, v in enumerate(self.shape)
        ]
        return jax.ShapeDtypeStruct(
            tuple(shape), convert_dtype(self.dtype).jnp_dtype
        )

    def to_json(self):
        return {"shape": self.shape, "dtype": self.dtype, "name": self.name}

    @classmethod
    def from_json(cls, d):
        return cls(d["shape"], d["dtype"], d.get("name"))


def _bucket_path(path, combo):
    return f"{path}.b{'x'.join(str(s) for s in combo)}.pdmodel"


def save(layer, path, input_spec=None, bucket_sizes=None, **config):
    """Stage layer.forward on the given specs and export (ref jit/api.py
    jit.save).

    ``bucket_sizes``: {dim_index: [sizes]} covering every dynamic dim
    in the specs — one program is exported per bucket combination (the
    ``jit.bucketing`` recompile-avoidance policy, applied ahead of
    time). Without it, dynamic dims export at size 1."""
    if isinstance(layer, Layer):
        fn = layer.forward
        params = [p for _, p in layer.named_parameters()]
        buffers = [b for _, b in layer.named_buffers()]
        state = layer.state_dict()
    else:
        fn = layer
        params, buffers, state = [], [], {}
    if input_spec is None:
        raise ValueError("jit.save requires input_spec")
    specs = [
        s if isinstance(s, InputSpec) else InputSpec(**s)
        for s in input_spec
    ]
    dyn_dims = sorted({d for s in specs for d in s.dynamic_dims()})
    buckets = None
    if bucket_sizes:
        buckets = {
            int(d): sorted(int(v) for v in sizes)
            for d, sizes in bucket_sizes.items()
        }
        missing = [d for d in dyn_dims if d not in buckets]
        if missing:
            raise ValueError(
                f"bucket_sizes covers dims {sorted(buckets)} but the "
                f"input specs have dynamic dims {dyn_dims} (missing "
                f"{missing})"
            )
        # only dims that are actually dynamic somewhere get programs
        buckets = {d: buckets[d] for d in dyn_dims}
        if not buckets:
            raise ValueError(
                "bucket_sizes given but no input spec has a dynamic "
                "dim (use concrete shapes instead)"
            )

    p_arrays = [p._data for p in params]
    b_arrays = [b._data for b in buffers]

    def staged(param_arrays, buffer_arrays, *inputs):
        from .api import _swap_payloads
        from ..core import random as random_mod

        old_p = _swap_payloads(params, param_arrays)
        old_b = _swap_payloads(buffers, buffer_arrays)
        # rng-marked ops split the global generator key during tracing;
        # restore it afterwards so no tracer escapes into eager state (the
        # exported program bakes the keys it drew — inference artifacts are
        # deterministic by design)
        old_key = random_mod.default_generator._key
        try:
            with autograd.no_grad():
                out = fn(*[Tensor(i) for i in inputs])
        finally:
            _swap_payloads(params, old_p)
            _swap_payloads(buffers, old_b)
            random_mod.default_generator._key = old_key
        return jax.tree_util.tree_map(
            lambda o: o._data if isinstance(o, Tensor) else o,
            out,
            is_leaf=lambda o: isinstance(o, Tensor),
        )

    def _export(dim_sizes):
        return jax_export.export(jax.jit(staged))(
            [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in p_arrays],
            [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in b_arrays],
            *[s._sds(dim_sizes) for s in specs],
        )

    combos = None
    if buckets:
        dims = sorted(buckets)
        combos = [
            list(c) for c in itertools.product(*[buckets[d] for d in dims])
        ]
        exported0 = None
        for combo in combos:
            exported = _export(dict(zip(dims, combo)))
            exported0 = exported0 or exported
            with open(_bucket_path(path, combo), "wb") as f:
                f.write(exported.serialize())
    else:
        exported0 = _export(None)
        with open(path + ".pdmodel", "wb") as f:
            f.write(exported0.serialize())
    from ..framework.io_api import save as fsave

    fsave({"params": state}, path + ".pdiparams")
    with open(path + ".pdmeta", "w") as f:
        json.dump(
            {
                "format": _META_FORMAT,
                "jax_version": jax.__version__,
                "calling_convention_version": getattr(
                    exported0, "calling_convention_version", None
                ),
                "input_spec": [s.to_json() for s in specs],
                "buckets": (
                    {"dims": sorted(buckets), "combos": combos}
                    if buckets else None
                ),
                "param_names": [
                    name for name, _ in (
                        layer.named_parameters()
                        if isinstance(layer, Layer) else []
                    )
                ],
                "buffer_names": [
                    name for name, _ in (
                        layer.named_buffers()
                        if isinstance(layer, Layer) else []
                    )
                ],
            },
            f,
        )


def _deserialize_program(blob, meta, label):
    """jax_export.deserialize with a CLEAR failure mode: a version-
    mismatched or corrupt blob raises a ValueError naming the recorded
    and running jax versions instead of failing deep in the
    deserializer."""
    try:
        return jax_export.deserialize(blob)
    except Exception as e:
        # analysis: allow(broad-except) classify-and-reraise: the
        # deserializer's failure types are internal and unstable
        saved = meta.get("jax_version")
        if saved and saved != jax.__version__:
            raise ValueError(
                f"{label}: artifact was exported with jax {saved} "
                f"(calling convention "
                f"{meta.get('calling_convention_version')}) but this "
                f"process runs jax {jax.__version__} and cannot "
                f"deserialize it — re-export the model under the "
                f"current jax"
            ) from e
        raise ValueError(
            f"{label}: serialized program is unreadable (corrupt blob "
            f"or incompatible exporter): {type(e).__name__}: {e}"
        ) from e


class TranslatedLayer:
    """Loaded inference artifact (ref jit/translated_layer.py). Runs the
    deserialized StableHLO program; parameters are baked as call inputs.

    Bucketed artifacts hold one program per bucket combination: a call
    picks the smallest combination covering the actual dynamic-dim
    sizes, zero-pads the inputs up to it, and slices padded output dims
    back to the true size. Which output dims to slice is DERIVED, not
    guessed: an (output, axis) pair tracks a bucket dim iff its exported
    size varies across that dim's bucket combinations — so a fixed-size
    output dim that merely coincides with a padded target is left alone.
    (With a single bucket size per dim there is nothing to compare, and
    the equal-to-target heuristic is the fallback.)"""

    def __init__(self, exported, param_arrays, buffer_arrays, meta,
                 programs=None):
        self._exported = exported          # single-program artifacts
        self._programs = programs or {}    # {combo: exported}
        self._params = param_arrays
        self._buffers = buffer_arrays
        self._meta = meta
        buckets = meta.get("buckets") if self._programs else None
        if buckets:
            self._bucket_dims = buckets["dims"]
            self._sizes_per_dim = {
                d: sorted({c[j] for c in buckets["combos"]})
                for j, d in enumerate(self._bucket_dims)
            }
            self._out_tracking = self._derive_out_tracking()

    def _derive_out_tracking(self):
        """{bucket dim: {(flat output index, axis)} that track it} —
        computed once by diffing ``out_avals`` between two programs
        that differ only in that dim's bucket size. ``None`` per dim
        when only one size was exported (no pair to compare)."""
        combos = [tuple(c) for c in self._meta["buckets"]["combos"]]
        have = set(combos)
        base = combos[0]
        tracking = {}
        for j, d in enumerate(self._bucket_dims):
            alt = next(
                (s for s in self._sizes_per_dim[d] if s != base[j]), None
            )
            partner = base[:j] + (alt,) + base[j + 1:]
            avals0 = getattr(self._programs[base], "out_avals", None)
            if alt is None or partner not in have or avals0 is None:
                tracking[d] = None  # fall back to the size heuristic
                continue
            avals1 = self._programs[partner].out_avals
            tracking[d] = {
                (i, k)
                for i, (a0, a1) in enumerate(zip(avals0, avals1))
                for k, (s0, s1) in enumerate(zip(a0.shape, a1.shape))
                if s0 != s1
            }
        return tracking

    def _pick_program(self, arrs):
        """(exported, {dim: (target, required)}) for these inputs."""
        specs = self._meta["input_spec"]
        plan = {}
        for d in self._bucket_dims:
            required = 0
            for spec, a in zip(specs, arrs):
                shape = spec["shape"]
                if d < len(shape) and (
                    shape[d] is None or shape[d] < 0
                ):
                    required = max(required, a.shape[d])
            target = next_bucket(required, self._sizes_per_dim[d])
            plan[d] = (target, required)
        combo = tuple(plan[d][0] for d in self._bucket_dims)
        exported = self._programs.get(combo)
        if exported is None:
            raise ValueError(
                f"no exported program for bucket combination {combo} "
                f"(available: {sorted(self._programs)})"
            )
        return exported, plan

    def _pad_inputs(self, arrs, plan):
        specs = self._meta["input_spec"]
        out = []
        for spec, a in zip(specs, arrs):
            widths = [(0, 0)] * a.ndim
            padded = False
            for d, (target, _) in plan.items():
                shape = spec["shape"]
                if d < len(shape) and (
                    shape[d] is None or shape[d] < 0
                ) and a.shape[d] < target:
                    widths[d] = (0, target - a.shape[d])
                    padded = True
            out.append(jnp.pad(a, widths) if padded else a)
        return out

    def _slice_outputs(self, out, plan):
        cuts = {
            d: (t, r) for d, (t, r) in plan.items() if t != r
        }
        if not cuts:
            return out
        leaves, treedef = jax.tree_util.tree_flatten(out)
        new = []
        for i, y in enumerate(leaves):
            if not hasattr(y, "ndim"):
                new.append(y)
                continue
            idx = []
            changed = False
            for k in range(y.ndim):
                cut = slice(None)
                for d, (target, required) in cuts.items():
                    tracked = self._out_tracking.get(d)
                    hit = (
                        (i, k) in tracked if tracked is not None
                        # single-size bucket: no cross-program diff to
                        # consult — assume a dim AT the padded target
                        # tracks it (the pre-derivation heuristic)
                        else k == d
                    )
                    if hit and y.shape[k] == target:
                        cut = slice(0, required)
                        changed = True
                        break
                idx.append(cut)
            new.append(y[tuple(idx)] if changed else y)
        return jax.tree_util.tree_unflatten(treedef, new)

    def __call__(self, *inputs):
        arrs = [
            i._data if isinstance(i, Tensor) else jnp.asarray(i)
            for i in inputs
        ]
        if self._programs:
            exported, plan = self._pick_program(arrs)
            arrs = self._pad_inputs(arrs, plan)
            out = exported.call(self._params, self._buffers, *arrs)
            out = self._slice_outputs(out, plan)
        else:
            out = self._exported.call(self._params, self._buffers, *arrs)
        return jax.tree_util.tree_map(
            lambda a: Tensor(a, stop_gradient=True), out
        )

    forward = __call__

    def eval(self):
        return self

    @property
    def input_spec(self):
        return [
            InputSpec.from_json(d) for d in self._meta["input_spec"]
        ]


def load(path, **config):
    """ref jit/api.py paddle.jit.load."""
    with open(path + ".pdmeta") as f:
        meta = json.load(f)
    buckets = meta.get("buckets")
    if buckets:
        programs = {}
        for combo in buckets["combos"]:
            with open(_bucket_path(path, combo), "rb") as f:
                programs[tuple(combo)] = _deserialize_program(
                    f.read(), meta, _bucket_path(path, combo)
                )
        exported = None
    else:
        with open(path + ".pdmodel", "rb") as f:
            exported = _deserialize_program(
                f.read(), meta, path + ".pdmodel"
            )
        programs = None
    from ..framework.io_api import load as fload

    blob = fload(path + ".pdiparams")
    state = blob["params"]
    p_arrays = [
        state[n]._data if isinstance(state[n], Tensor)
        else jnp.asarray(np.asarray(state[n]))
        for n in meta["param_names"]
    ]
    b_arrays = [
        state[n]._data if isinstance(state[n], Tensor)
        else jnp.asarray(np.asarray(state[n]))
        for n in meta["buffer_names"]
    ]
    return TranslatedLayer(
        exported, p_arrays, b_arrays, meta, programs=programs
    )
