"""jit staging implementation.

The functionalization contract: eager Tensors are Python objects whose
payload (`_data`) we swap for tracers during the single trace, then restore.
Anything the traced body mutates (parameters via the optimizer update,
buffers via BatchNorm, the RNG key) is lifted to explicit inputs/outputs of
the staged function — the XLA analogue of the reference's inplace pass +
variable-scope binding (fluid/pir/transforms/general/inplace_pass.cc;
new_executor/pir_adaptor value binding).

Because Tensor is pytree-registered, jax.jit moves whole Tensor-bearing
structures across the staging boundary directly; outputs come back as fresh
detached Tensors.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import autograd
from ..core import random as random_mod
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from ..observability import jit_events

_NOT_TO_STATIC = set()

# monotonic instance tokens for the compile-log signatures: id(self)
# is reused by the allocator after collection (and truncating it can
# collide two LIVE instances), which would alias a fresh instance's
# first compile onto a dead one's warm signature — a false
# retrace-after-warmup alarm
import itertools as _itertools  # noqa: E402
import re as _re  # noqa: E402

_instance_tokens = _itertools.count(1)

# default object.__repr__ shape: "<pkg.Cls object at 0x7f...>" — a
# process-local address that must never reach a cross-process cache key
_ADDR_REPR = _re.compile(r" at 0x[0-9a-fA-F]+>")


def not_to_static(fn):
    """Mark a function to stay eager (ref: jit/api.py not_to_static)."""
    _NOT_TO_STATIC.add(fn)
    return fn


def ignore_module(modules):
    """API-parity no-op: jax tracing handles arbitrary modules."""
    return None


def _swap_payloads(tensors, arrays):
    old = [t._data for t in tensors]
    for t, a in zip(tensors, arrays):
        t._data = a
    return old


class _rng_lift:
    """Swap the global generator key for a per-call traced key during
    staging, so dropout etc. draw from a fresh key every execution instead
    of a constant baked at trace time."""

    def __init__(self, key):
        self._key = key

    def __enter__(self):
        self._saved = random_mod.default_generator._key
        random_mod.default_generator._key = self._key
        return self

    def final_key(self):
        return random_mod.default_generator._key

    def __exit__(self, *exc):
        random_mod.default_generator._key = self._saved
        return False


def _to_arrays(tree):
    return jax.tree_util.tree_map(
        lambda x: x._data if isinstance(x, Tensor) else x,
        tree,
        is_leaf=lambda x: isinstance(x, Tensor),
    )


class _nan_net:
    """Staged NaN/Inf debug net (FLAGS_check_nan_inf inside jit).

    While tracing, collects each dispatched op's isfinite-violated flag
    (core.dispatch routes them here instead of a host callback — pure
    dataflow, so it works on backends without callback support). The
    flags become ONE stacked bool output of the staged program; `raise_if`
    checks it on the host after execution and names the first bad op —
    the staged analogue of the reference's static-executor check
    (fluid/framework/new_executor/nan_inf_utils.cc)."""

    def __init__(self, enabled):
        self.enabled = enabled
        self.names = []
        self._collector = [] if enabled else None

    def __enter__(self):
        if self.enabled:
            from ..core import dispatch

            self._prev = dispatch.set_nan_collector(self._collector)
        return self

    def __exit__(self, *exc):
        if self.enabled:
            from ..core import dispatch

            dispatch.set_nan_collector(self._prev)
        return False

    def flags_output(self):
        if not self.enabled or not self._collector:
            return jnp.zeros((0,), jnp.bool_)
        self.names = [n for n, _ in self._collector]
        return jnp.stack([b for _, b in self._collector])

    def raise_if(self, flags_value):
        if not self.enabled or flags_value is None:
            return
        import numpy as np

        vals = np.asarray(flags_value)
        if vals.size and vals.any():
            from ..core import flags as flags_mod
            from ..core.dispatch import _nan_inf_report

            idx = int(np.argmax(vals))
            _nan_inf_report(
                True, self.names[idx],
                flags_mod.get_flag("FLAGS_check_nan_inf_level"),
            )


def _nan_check_enabled():
    from ..core import flags as flags_mod

    return bool(flags_mod.get_flag("FLAGS_check_nan_inf"))


class StaticFunction:
    """Stage a tensor function or Layer forward into one XLA computation
    (ref: jit/dy2static/program_translator.py:397 StaticFunction).

    Parameters/buffers are lifted to inputs on every call (cheap: array
    handles), so eager updates between calls are honoured without
    retracing; buffer mutations inside forward (BatchNorm running stats)
    come back as outputs and are rebound after execution. jax.jit is the
    compile cache (keyed on input shapes/dtypes — the reference keys its
    _ExecutorCache on program+scope, base/executor.py:869).

    Training works: when grads are enabled, the staged program is recorded
    on the eager tape as ONE op whose vjp is the transposed compiled
    program (jax.vjp of a jitted function runs compiled in both
    directions) — the analogue of the reference's RunProgramOp wrapping a
    fwd/bwd partial-program pair (jit/dy2static/partial_program.py).
    """

    def __init__(self, function, layer=None, check=None, cache=None):
        self._function = function
        self._layer = layer
        if layer is not None:
            self._params = [p for _, p in layer.named_parameters()]
            self._buffers = [b for _, b in layer.named_buffers()]
        else:
            self._params = []
            self._buffers = []
        self._core = None
        self._out_tree = None
        self._nan_nets = {}
        self._cur_nan_key = None
        if check not in (None, "warn", "error"):
            raise ValueError(
                f'check must be None, "warn" or "error", got {check!r}'
            )
        self._check = check
        self._checked_sigs = set()
        self._instance_tok = next(_instance_tokens)
        # persistent compile cache (paddle_tpu.compilecache): eval-mode
        # calls run through AOT executables keyed on the function's
        # bytecode fingerprint + abstract signature, loaded from disk
        # by a later process with zero tracing. None disables.
        self._cache_spec = cache
        self._cc = None            # resolved lazily
        self._code_fp = None
        self._aot = {}             # sig -> (compiled, user out_tree)
        self._warned_unstable = False

    def _run_check(self, args, kwargs, sig):
        """``to_static(check=...)`` choke point: on the first call per
        input signature (``sig`` — the same key the nan net uses), run
        the static analyzer over the function (trace only, nothing
        executes) and warn/raise per mode BEFORE the real staging trace
        — so e.g. a host-sync lands as a structured AnalysisError with
        provenance instead of a raw TracerBoolConversionError."""
        if sig in self._checked_sigs:
            return
        from .. import analysis

        # check_call, not check: user kwargs named mode/passes/... must
        # reach the analyzed function, not the analyzer's options
        report = analysis.check_call(self, args, kwargs, mode=self._check)
        analysis.enforce(
            report, self._check,
            what=f"to_static(check={self._check!r}) analysis of "
            f"{getattr(self._function, '__name__', self._function)!r}",
        )
        # marked checked only on a pass: a blocking finding re-raises
        # (as a structured AnalysisError) on every call, instead of
        # degrading to the raw tracer error on the second one
        self._checked_sigs.add(sig)

    def _build_core(self):
        fn = self._function
        params, buffers = self._params, self._buffers
        outer = self
        self._built_nan = _nan_check_enabled()

        def core(param_arrays, buffer_arrays, key, in_flat, in_meta,
                 mode=None):
            """in_flat: flat tensor-slot arrays; in_meta: (treedef, flat
            template with None at tensor slots, slot indices) — static.
            ``mode`` (static) carries the layer's train/eval flag into
            the trace-cache key: the flag shapes the traced program
            (dropout, batchnorm) but is invisible to the abstract
            signature, and jax caches lowerings per signature — without
            it, lowering after a train()/eval() flip would silently
            reuse the other mode's trace."""
            jit_events.mark_traced()  # compile/retrace event log
            treedef, template, slots = in_meta
            flat = list(template)
            for i, a in zip(slots, in_flat):
                flat[i] = Tensor(a, stop_gradient=True)
            args, kwargs = jax.tree_util.tree_unflatten(treedef, flat)
            old_p = _swap_payloads(params, param_arrays)
            old_b = _swap_payloads(buffers, buffer_arrays)
            net = _nan_net(outer._built_nan)
            try:
                with _rng_lift(key) as lift:
                    with net, autograd.no_grad():
                        out = fn(*args, **kwargs)
                    new_key = lift.final_key()
                out_flat, out_tree = jax.tree_util.tree_flatten(
                    out, is_leaf=lambda x: isinstance(x, Tensor)
                )
                outer._out_tree = out_tree
                out_arrays = [
                    o._data if isinstance(o, Tensor) else o for o in out_flat
                ]
                new_buf = [b._data for b in buffers]
                nan_flags = net.flags_output()
                # one net per trace: jax.jit caches per shape signature,
                # so flag indices must decode with THAT trace's op list
                outer._nan_nets[outer._cur_nan_key] = net
            finally:
                _swap_payloads(params, old_p)
                _swap_payloads(buffers, old_b)
            return out_arrays, new_buf, new_key, nan_flags

        return jax.jit(core, static_argnames=("in_meta", "mode"))

    @staticmethod
    def _is_data(x):
        import numpy as np

        return isinstance(x, (Tensor, jax.Array, np.ndarray))

    def _split_inputs(self, args, kwargs):
        """Split (args, kwargs) into traced data slots and a hashable
        static template (treedef + non-data leaves)."""
        flat, treedef = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor)
        )
        slots = tuple(i for i, x in enumerate(flat) if self._is_data(x))
        arrays = [
            flat[i]._data if isinstance(flat[i], Tensor) else flat[i]
            for i in slots
        ]
        template = tuple(
            None if self._is_data(x) else x for x in flat
        )
        return arrays, (treedef, template, slots)

    # -- persistent compile cache (paddle_tpu.compilecache) ------------------
    def _call_aot(self, sig, param_arrays, buf_arrays, key, in_arrays,
                  in_meta):
        """Run this signature through an AOT executable: loaded from
        the persistent compile cache (zero traces — recorded as an
        ``aot-hit`` event) or compiled once via ``self._core.lower``
        (the probes fire normally) and serialized for the next process.
        Returns ``(outs, new_buf, nan_flags)``; ``self._out_tree`` is
        restored from the artifact so unflattening works without the
        trace that normally populates it."""
        # the layer's train/eval flag shapes the traced program (dropout,
        # batchnorm) but is invisible to the abstract signature — key on
        # it, or a train()-mode call could replay an eval-mode executable
        # (in-process or from a previous process's artifact)
        mode = getattr(self._layer, "training", None)
        entry = self._aot.get((sig, mode))
        if entry is None:
            entry = self._aot_load_or_compile(
                sig, param_arrays, buf_arrays, key, in_arrays, in_meta,
                mode,
            )
            self._aot[(sig, mode)] = entry
        exe, out_tree = entry
        self._out_tree = out_tree
        outs, new_buf, _, nflags = exe(
            param_arrays, buf_arrays, key, in_arrays
        )
        return outs, new_buf, nflags

    def _aot_load_or_compile(self, sig, param_arrays, buf_arrays, key,
                             in_arrays, in_meta, mode=None):
        import pickle

        from .. import compilecache as cc_mod

        if self._cc is None:
            self._cc = cc_mod.resolve(self._cache_spec)
        cc = self._cc
        if self._code_fp is None:
            self._code_fp = cc_mod.code_fingerprint(self._function) or ""
        name = getattr(self._function, "__name__", "staged_fn")
        cache_name = f"to_static.{name}"
        # disk key: bytecode fingerprint + abstract input signature +
        # the static input template — NOT the instance token (a fresh
        # process's instance must hit the previous process's artifact).
        # Caveat (docs/compilecache.md): the fingerprint covers this
        # function's own bytecode, not its callees' — see
        # compilecache.code_fingerprint.
        meta_token = repr(in_meta)
        # a static arg with a default object repr embeds a process-local
        # address: the key would be unique per process — every restart
        # a miss plus a freshly-stored orphan artifact. Such signatures
        # compile in-memory only.
        disk_ok = bool(self._code_fp) and not _ADDR_REPR.search(
            meta_token
        )
        if self._code_fp and not disk_ok and not self._warned_unstable:
            self._warned_unstable = True
            import sys

            sys.stderr.write(
                f"[compilecache] {cache_name}: a static argument has no "
                "stable repr (address-bearing); this signature is "
                "compiled per process, not disk-cached\n"
            )
        sig_str = (
            f"to_static:{self._code_fp}:"
            + cc_mod.signature_str((
                cc_mod.abstractify(param_arrays),
                cc_mod.abstractify(buf_arrays),
                cc_mod.abstractify(key),
                cc_mod.abstractify(in_arrays),
            ))
            + f":meta={meta_token}:mode={mode}"
        )
        store_key = cc.key(cache_name, sig_str)
        if disk_ok:
            # the out-tree sidecar unpickles inside finish= so a damaged
            # sidecar falls back (counted + warned, no aot-hit recorded)
            # exactly like a damaged executable
            got = cc.load_executable_bundle(
                store_key, name=cache_name, signature=sig_str,
                finish=lambda exe, meta, blobs: (
                    exe, pickle.loads(blobs["out_tree"])
                ),
            )
            if got is not None:
                return got
        # fresh compile: lowering traces core once (mark_traced fires
        # under the caller's watch), which also populates
        # self._out_tree as a trace side effect
        exe = self._core.lower(
            param_arrays, buf_arrays, key, in_arrays, in_meta, mode
        ).compile()
        out_tree = self._out_tree
        if disk_ok:
            cc.store_executable(
                store_key, exe, name=cache_name, signature=sig_str,
                extra_blobs={"out_tree": pickle.dumps(out_tree)},
            )
        return exe, out_tree

    def __call__(self, *args, **kwargs):
        if self._core is not None and (
            getattr(self, "_built_nan", False) != _nan_check_enabled()
        ):
            self._core = None  # debug-net toggle changes the program
        if self._core is None:
            self._core = self._build_core()
        in_arrays, in_meta = self._split_inputs(args, kwargs)
        sig = (
            in_meta,
            tuple(
                (tuple(a.shape), str(a.dtype))
                for a in in_arrays if hasattr(a, "shape")
            ),
        )
        if self._check is not None:
            self._run_check(args, kwargs, sig)
        self._cur_nan_key = sig
        buf_arrays = [b._data for b in self._buffers]
        key = random_mod.default_generator.split_key()
        params = self._params
        n_out = [None]

        train_mode = autograd.is_grad_enabled() and any(
            not p.stop_gradient for p in params
        )
        # compile/retrace event log: the watch supplies identity +
        # elapsed for any trace core fires during this call; train and
        # eval trace distinct programs (vjp vs plain), so they are
        # distinct signatures, not retraces of each other
        # the instance token keeps two DISTINCT functions that share a
        # name (every Layer's 'forward') from reading as retraces of
        # each other — the alarm must only fire when THIS function's
        # already-warm signature traces again
        _watch = jit_events.watch(
            getattr(self._function, "__name__", "staged_fn"),
            kind="to_static",
            signature=f"{self._instance_tok:x}:"
            f"{hash(sig) & 0xFFFFFFFF:08x}"
            f":{'train' if train_mode else 'eval'}",
        )
        if train_mode:
            core = self._core
            n_p = len(params)

            def impl(*arrays):
                outs, new_buf, _, nflags = core(
                    list(arrays[:n_p]), buf_arrays, key,
                    list(arrays[n_p:]), in_meta,
                )
                n_out[0] = len(outs)
                return tuple(outs) + tuple(new_buf) + (nflags,)

            from ..core import dispatch

            flat_all = jax.tree_util.tree_flatten(
                (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor)
            )[0]
            slot_vals = [flat_all[i] for i in in_meta[2]]
            in_tensors = [
                v if isinstance(v, Tensor) else Tensor(v, stop_gradient=True)
                for v in slot_vals
            ]
            with _watch:
                results = dispatch.call(
                    "jit_program", impl,
                    tuple(params) + tuple(in_tensors), {},
                )
            results = (
                list(results) if isinstance(results, (tuple, list))
                else [results]
            )
            k = n_out[0]
            out_flat = results[:k]
            new_buf = results[k:-1]
            nflags = results[-1]
            if self._built_nan and nflags is not None:
                self._nan_nets[self._cur_nan_key].raise_if(nflags._data)
            for b, nb in zip(self._buffers, new_buf):
                if nb is not None:
                    b._rebind(nb.detach()._data)
            return jax.tree_util.tree_unflatten(self._out_tree, out_flat)

        if self._cache_spec is not None and not self._built_nan:
            # persistent-compile-cache path (eval only: the train path
            # routes through the tape's vjp machinery, and the nan
            # debug net needs a live trace to decode its flag indices)
            with _watch:
                outs, new_buf, nflags = self._call_aot(
                    sig, [p._data for p in params], buf_arrays, key,
                    in_arrays, in_meta,
                )
        else:
            with _watch:
                outs, new_buf, _, nflags = self._core(
                    [p._data for p in params], buf_arrays, key,
                    in_arrays, in_meta,
                )
        if self._built_nan:
            self._nan_nets[self._cur_nan_key].raise_if(nflags)
        for b, a in zip(self._buffers, new_buf):
            b._rebind(a)
        out_flat = [
            Tensor(a, stop_gradient=True) if isinstance(a, jax.Array) else a
            for a in outs
        ]
        return jax.tree_util.tree_unflatten(self._out_tree, out_flat)


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph=True, check=None, cache=None,
              **kwargs):
    """Decorator/wrapper staging a function or Layer (ref: jit/api.py:197).

    ``input_spec``/``build_strategy``/``backend`` are accepted for API
    parity; shapes are taken from the first call (jax.jit caches per
    shape signature, recompiling per new signature — the bucketing
    policy replacing the reference's symbolic-shape DimExpr machinery).

    ``check="warn"|"error"`` runs the static analyzer
    (``paddle_tpu.analysis``) over the function on the first call per
    input signature: host syncs, retrace hazards, dtype drift etc.
    surface as structured findings (warned or raised) before staging.

    ``cache=`` (a directory path or ``compilecache.CompileCache``)
    persists eval-mode compiled executables to disk: a later process
    staging the same function over the same signature loads the
    executable with zero tracing and zero compilation
    (docs/compilecache.md). Training calls and the NaN debug net bypass
    the cache.
    """
    if check is not None and not full_graph:
        raise ValueError(
            "check= requires full_graph=True (the graph-break fallback "
            "intentionally tolerates host syncs)"
        )
    if cache is not None and not full_graph:
        raise ValueError(
            "cache= requires full_graph=True (graph-break segments "
            "trace per-branch and are not AOT-serializable as one "
            "program)"
        )

    def _wrap(obj):
        if isinstance(obj, Layer):
            if full_graph:
                sf = StaticFunction(obj.forward, layer=obj, check=check,
                                    cache=cache)
            else:
                from .graph_break import GraphBreakFunction

                sf = GraphBreakFunction(obj.forward, layer=obj)
            obj.forward = sf
            return obj
        if obj in _NOT_TO_STATIC:
            return obj
        if not full_graph:
            from .graph_break import GraphBreakFunction

            return GraphBreakFunction(obj)
        return StaticFunction(obj, check=check, cache=cache)

    if function is not None:
        return _wrap(function)
    return _wrap


class TrainStep:
    """Whole-train-step staging: fwd + bwd + clip + optimizer update in ONE
    XLA program with donated parameter/optimizer-state buffers.

    The analogue of the reference's Plan/Job executor path
    (new_executor/standalone_executor.cc:47) composed with its inplace pass:
    XLA sees the complete step, fuses across the fwd/bwd boundary, and
    writes parameter updates in place via donation.

        step = paddle.jit.TrainStep(model, loss_fn, optimizer)
        loss = step(x, y)      # loss_fn(model, x, y) -> scalar loss

    ``loss_fn(model, *args, **kwargs)`` runs the forward and returns the
    scalar loss; everything it does is staged. The LR schedule and
    GradScaler found_inf enter as scalar operands (no recompile per step).

    ``accum_steps=k`` stages GRADIENT ACCUMULATION (the reference's
    gradient-merge pass, distributed/passes/auto_parallel_gradient_merge.py)
    as a ``lax.scan`` over k micro-batches: every data input's leading
    batch axis is split [B] -> [k, B//k], the scan body runs fwd+bwd on
    one micro-batch (so only ONE micro-batch's activations are ever
    live), gradients accumulate in fp32 through the carry, and a single
    optimizer update runs on the mean gradient — numerically the step a
    k-times-larger batch would take. Composes with ZeRO: stage>=2
    gradient shardings constrain the carry, so the running sum stays
    reduce-scattered across the mesh inside the scan.
    """

    def __init__(self, model, loss_fn, optimizer, donate=True,
                 accum_steps=None):
        self._model = model
        self._loss_fn = loss_fn
        self._opt = optimizer
        self._donate = donate
        if accum_steps is None:
            accum_steps = getattr(
                optimizer, "gradient_accumulation_steps", 1
            )
        self._accum = int(accum_steps)
        if self._accum < 1:
            raise ValueError(
                f"accum_steps must be >= 1, got {accum_steps}"
            )
        self._params = [
            p for p in optimizer._parameter_list
            if getattr(p, "trainable", not p.stop_gradient)
        ]
        self._buffers = [b for _, b in model.named_buffers()]
        self._compiled = None
        self._live_idx = None  # params that actually received grads
        self._nan_nets = {}
        self._cur_nan_key = None
        self._instance_tok = next(_instance_tokens)

    def _build(self):
        model, loss_fn, opt = self._model, self._loss_fn, self._opt
        params, buffers = self._params, self._buffers
        opt_step_fn = opt._make_step_fn()
        self._built_nan = _nan_check_enabled()
        outer = self

        def staged(param_arrays, buffer_arrays, states, lr, t, found_inf,
                   key, tree_args):
            jit_events.mark_traced()  # compile/retrace event log
            old_p = _swap_payloads(params, param_arrays)
            old_b = _swap_payloads(buffers, buffer_arrays)
            saved = [(p.grad, p._grad_node, p._out_index, p.stop_gradient)
                     for p in params]
            net = _nan_net(outer._built_nan)
            try:
                for p in params:
                    p.grad = None
                    p._grad_node = None
                    p.stop_gradient = False
                with _rng_lift(key) as lift:
                    args, kwargs = tree_args
                    with net:
                        loss = loss_fn(model, *args, **kwargs)
                        loss.backward()
                    new_key = lift.final_key()

                live_idx = [
                    i for i, p in enumerate(params) if p.grad is not None
                ]
                if self._live_idx is None:
                    self._live_idx = live_idx
                live = [params[i] for i in live_idx]
                attrs = tuple(self._attr_for(p) for p in live)
                live_grads = [p.grad._data for p in live]
                # ZeRO stage>=2: constrain gradient layout in-program so XLA
                # reduce-scatters instead of all-reducing. Shardings were
                # precomputed from concrete payloads in __call__ (params are
                # tracers here).
                if self._grad_shardings is not None:
                    live_grads = [
                        jax.lax.with_sharding_constraint(g, s)
                        if (s := self._grad_shardings[i]) is not None else g
                        for i, g in zip(live_idx, live_grads)
                    ]
                targets = tuple(
                    self._out_shardings[i] for i in live_idx
                )
                new_live, new_states = opt_step_fn(
                    attrs, targets, lr, t, found_inf,
                    [p._data for p in live],
                    live_grads,
                    [states[i] for i in live_idx],
                )
                new_param_arrays = list(param_arrays)
                out_states = list(states)
                for j, i in enumerate(live_idx):
                    new_param_arrays[i] = new_live[j]
                    out_states[i] = new_states[j]
                new_buffer_arrays = [b._data for b in buffers]
                loss_val = loss._data
                nan_flags = net.flags_output()
                outer._nan_nets[outer._cur_nan_key] = net
            finally:
                _swap_payloads(params, [s for s in old_p])
                _swap_payloads(buffers, old_b)
                for p, (g, node, oi, sg) in zip(params, saved):
                    p.grad = g
                    p._grad_node = node
                    p._out_index = oi
                    p.stop_gradient = sg
            return (new_param_arrays, new_buffer_arrays, out_states,
                    loss_val, new_key, nan_flags)

        def staged_accum(param_arrays, buffer_arrays, states, lr, t,
                         found_inf, key, tree_args):
            """accum_steps>1: scan k micro-batches, one update."""
            jit_events.mark_traced()  # compile/retrace event log
            k = self._accum
            old_p = _swap_payloads(params, param_arrays)
            old_b = _swap_payloads(buffers, buffer_arrays)
            saved = [(p.grad, p._grad_node, p._out_index, p.stop_gradient)
                     for p in params]
            try:
                for p in params:
                    p.grad = None
                    p._grad_node = None
                    p.stop_gradient = False

                def split(a):
                    if not hasattr(a, "shape") or a.ndim == 0:
                        raise ValueError(
                            "accum_steps requires every data input to "
                            "have a leading batch axis to micro-split; "
                            f"got {a!r}"
                        )
                    if a.shape[0] % k:
                        raise ValueError(
                            f"batch axis {a.shape[0]} not divisible by "
                            f"accum_steps={k}"
                        )
                    return a.reshape((k, a.shape[0] // k) + a.shape[1:])

                micro_tree = jax.tree_util.tree_map(split, tree_args)
                keys = jax.random.split(key, k + 1)

                # fp32 accumulators for every trainable param; ZeRO
                # layouts constrain the carry so the running sum stays
                # sharded through the scan
                def g_init(i, a):
                    dt = (jnp.float32 if a.dtype in (jnp.bfloat16,
                                                     jnp.float16)
                          else a.dtype)
                    z = jnp.zeros(a.shape, dt)
                    if (self._grad_shardings is not None
                            and self._grad_shardings[i] is not None):
                        z = jax.lax.with_sharding_constraint(
                            z, self._grad_shardings[i]
                        )
                    return z

                grad_acc0 = [g_init(i, a)
                             for i, a in enumerate(param_arrays)]
                live_holder = []

                def body(carry, xs):
                    grad_acc, bufs = carry
                    mt, key_i = xs
                    _swap_payloads(buffers, bufs)
                    for p in params:
                        p.grad = None
                        p._grad_node = None
                    net = _nan_net(outer._built_nan)
                    with _rng_lift(key_i):
                        args_i, kwargs_i = mt
                        with net:
                            loss = loss_fn(model, *args_i, **kwargs_i)
                            loss.backward()
                    li = [i for i, p in enumerate(params)
                          if p.grad is not None]
                    if not live_holder:
                        live_holder.append(li)
                        outer._nan_nets[outer._cur_nan_key] = net
                    new_acc = list(grad_acc)
                    for i in li:
                        g = params[i].grad._data.astype(grad_acc[i].dtype)
                        if (self._grad_shardings is not None
                                and self._grad_shardings[i] is not None):
                            g = jax.lax.with_sharding_constraint(
                                g, self._grad_shardings[i]
                            )
                        new_acc[i] = grad_acc[i] + g
                    new_bufs = [b._data for b in buffers]
                    return ((new_acc, new_bufs),
                            (loss._data, net.flags_output()))

                (grad_acc, buf_fin), (losses, nan_stack) = jax.lax.scan(
                    body, (grad_acc0, list(buffer_arrays)),
                    (micro_tree, keys[1:]),
                )
                live_idx = live_holder[0]
                if self._live_idx is None:
                    self._live_idx = live_idx
                live = [params[i] for i in live_idx]
                attrs = tuple(self._attr_for(p) for p in live)
                live_grads = [
                    (grad_acc[i] * (1.0 / k)).astype(
                        param_arrays[i].dtype
                    )
                    for i in live_idx
                ]
                targets = tuple(self._out_shardings[i] for i in live_idx)
                new_live, new_states = opt_step_fn(
                    attrs, targets, lr, t, found_inf,
                    [params[i]._data for i in live_idx],
                    live_grads,
                    [states[i] for i in live_idx],
                )
                new_param_arrays = list(param_arrays)
                out_states = list(states)
                for j, i in enumerate(live_idx):
                    new_param_arrays[i] = new_live[j]
                    out_states[i] = new_states[j]
                loss_val = losses.mean()
                nan_flags = (
                    nan_stack.any(axis=0) if nan_stack.size
                    else jnp.zeros((0,), jnp.bool_)
                )
            finally:
                _swap_payloads(params, [s for s in old_p])
                _swap_payloads(buffers, old_b)
                for p, (g, node, oi, sg) in zip(params, saved):
                    p.grad = g
                    p._grad_node = node
                    p._out_index = oi
                    p.stop_gradient = sg
            return (new_param_arrays, list(buf_fin), out_states,
                    loss_val, keys[0], nan_flags)

        donate = (0, 2) if self._donate else ()
        fn = staged if self._accum == 1 else staged_accum
        return jax.jit(fn, donate_argnums=donate)

    def _attr_for(self, p):
        """Per-param static attrs, mirroring Optimizer._collect for one
        param (group lookup preserved)."""
        from ..optimizer.optimizer import _PAttr, _normalize_weight_decay

        opt = self._opt
        for group in opt._param_groups:
            if any(q is p for q in group["params"]):
                g_kind, g_coeff = opt._group_weight_decay(group)
                lr_scale = float(group.get("learning_rate", 1.0))
                break
        else:
            group, g_kind, g_coeff, lr_scale = None, None, 0.0, 1.0
        preg = getattr(p, "regularizer", None)
        if preg is not None:
            g_kind, g_coeff = _normalize_weight_decay(preg)
        decoupled, lr_ratio = opt._param_extras(p, group)
        return _PAttr(
            lr_scale=lr_scale
            * float(getattr(p, "optimize_attr", {}).get("learning_rate", 1.0)),
            reg_kind=g_kind,
            reg_coeff=g_coeff,
            need_clip=getattr(p, "need_clip", True),
            multi_precision=opt._use_master(p),
            decoupled_decay=decoupled,
            lr_ratio=lr_ratio,
        )

    def __call__(self, *args, **kwargs):
        opt = self._opt
        if self._compiled is not None and (
            getattr(self, "_built_nan", False) != _nan_check_enabled()
        ):
            self._compiled = None  # debug-net toggle changes the program
        if self._compiled is None:
            self._compiled = self._build()
        states = [opt._ensure_state(p) for p in self._params]
        # concrete layouts, read before payloads become tracers (static
        # per-param out constraints for the staged optimizer update)
        self._out_shardings = tuple(
            opt._param_out_sharding(p._data, st)
            for p, st in zip(self._params, states)
        )
        grad_sharding = getattr(opt, "_grad_sharding_for", None)
        self._grad_shardings = (
            tuple(grad_sharding(p) for p in self._params)
            if grad_sharding is not None else None
        )
        from ..optimizer.optimizer import _found_inf_operand

        lr = jnp.float32(opt.get_lr())
        t = jnp.float32(opt._global_step + 1)
        found_inf = _found_inf_operand(opt)
        key = random_mod.default_generator.split_key()
        tree_args = (_to_arrays(args), _to_arrays(kwargs))
        self._cur_nan_key = (
            jax.tree_util.tree_structure(tree_args),
            tuple(
                (tuple(a.shape), str(a.dtype))
                for a in jax.tree_util.tree_leaves(tree_args)
                if hasattr(a, "shape")
            ),
        )
        with jit_events.watch(
            getattr(self._loss_fn, "__name__", "train_step"),
            kind="train_step",
            signature=f"{self._instance_tok:x}:"
            f"{hash(self._cur_nan_key) & 0xFFFFFFFF:08x}",
        ):
            (new_params, new_buffers, new_states, loss_val, _,
             nan_flags) = self._compiled(
                [p._data for p in self._params],
                [b._data for b in self._buffers],
                states, lr, t, found_inf, key, tree_args,
            )
        with autograd.no_grad():
            for p, a, ns in zip(self._params, new_params, new_states):
                p._rebind(a)
                p.grad = None
                opt._accumulators[id(p)] = ns
            for b, a in zip(self._buffers, new_buffers):
                b._rebind(a)
        opt._global_step += 1
        if self._built_nan:
            # raise AFTER rebinding: the pre-step buffers were donated,
            # so the new (NaN-carrying but valid) arrays must land on the
            # params or a caught error leaves the model pointing at
            # deleted buffers — resume from checkpoint to recover values
            self._nan_nets[self._cur_nan_key].raise_if(nan_flags)
        return Tensor(loss_val, stop_gradient=True)
