"""Input bucketing: the recompile-avoidance policy for dynamic shapes.

ref: the reference compiles dynamic shapes symbolically (pir DimExpr,
pir/include/dialect/shape/utils/dim_expr.h + ShapeConstraintIRAnalysis);
XLA's dynamic-dimension support is too limited for that design, so per
SURVEY §7 step 3 the TPU-native policy is PADDING TO BUCKETS: variable
dims are padded up to a small set of bucket sizes, giving one compiled
program per bucket instead of one per shape (the standard TPU serving
recipe for variable batch/sequence).

    fn = paddle.jit.bucketize(model_fn, buckets={0: [8, 16, 32]})
    fn(x_batch_13)   # pads dim 0 to 16; at most len(buckets) compiles

Outputs whose padded dimension survives to the output are sliced back to
the true size (tracked per call). Padding is zeros; reductions over the
padded axis are the CALLER's responsibility to mask (same contract as
any padded batch).

Slice-back is a size heuristic: an output dim equal to the padded target
is sliced to the true size (unpadded INPUT tensors passed through
unchanged are exempted by identity). An output that coincidentally has
the bucket size on a bucketed dim (e.g. a returned weight of shape
[bucket, k]) would be mis-sliced — return such values outside the
bucketed function.
"""
from __future__ import annotations

from ..core.tensor import Tensor

__all__ = ["bucketize", "BucketedFunction", "next_bucket"]


def next_bucket(size, buckets):
    """Smallest bucket holding ``size`` (buckets ascending). Public: the
    serving engine buckets prefill lengths through the same policy so the
    compiled-program set stays bounded."""
    for b in buckets:
        if size <= b:
            return b
    raise ValueError(
        f"size {size} exceeds the largest bucket {buckets[-1]}; add a "
        "bigger bucket"
    )


_next_bucket = next_bucket  # pre-r6 internal name


class BucketedFunction:
    def __init__(self, fn, buckets, pad_value=0):
        self._fn = fn
        self._buckets = {
            int(d): sorted(int(b) for b in bs) for d, bs in buckets.items()
        }
        self._pad_value = pad_value
        self.signatures = set()  # distinct padded signatures seen

    def __call__(self, *args, **kwargs):
        from .. import ops as F

        slice_back = {}   # dim -> (padded, original)
        passthrough = []  # unpadded input tensors: never slice these

        def pad(x):
            if not isinstance(x, Tensor):
                return x
            pads_needed = False
            widths = []
            for d in range(x.ndim):
                bs = self._buckets.get(d)
                if bs is None or x.shape[d] in bs:
                    widths.append((0, 0))
                    continue
                target = _next_bucket(x.shape[d], bs)
                widths.append((0, target - x.shape[d]))
                slice_back[d] = (target, x.shape[d])
                pads_needed = True
            if not pads_needed:
                passthrough.append(x)
                return x
            flat = [w for pair in widths for w in pair]
            # widths are in leading-dim order (F.pad defaults to the
            # torch-style last-dim-first convention)
            return F.pad(
                x, flat, value=self._pad_value, pad_from_last_axis=False
            )

        import jax

        is_t = lambda v: isinstance(v, Tensor)  # noqa: E731
        args = jax.tree_util.tree_map(pad, args, is_leaf=is_t)
        kwargs = jax.tree_util.tree_map(pad, kwargs, is_leaf=is_t)
        self.signatures.add(
            tuple(
                (tuple(v.shape), str(v.dtype))
                for v in jax.tree_util.tree_leaves(
                    (args, kwargs), is_leaf=is_t
                )
                if isinstance(v, Tensor)
            )
        )
        out = self._fn(*args, **kwargs)

        def unpad(y):
            if not isinstance(y, Tensor):
                return y
            if any(y is t for t in passthrough):
                return y  # an unpadded input flowed straight through
            idx = []
            changed = False
            for d in range(y.ndim):
                pb = slice_back.get(d)
                if pb and y.shape[d] == pb[0] and pb[0] != pb[1]:
                    idx.append(slice(0, pb[1]))
                    changed = True
                else:
                    idx.append(slice(None))
            return F.getitem(y, tuple(idx)) if changed else y

        return jax.tree_util.tree_map(unpad, out, is_leaf=is_t)


def bucketize(function=None, buckets=None, pad_value=0):
    """Wrap ``function`` (a plain callable or a to_static StaticFunction)
    with the bucket-padding policy. ``buckets``: {tensor_dim: [sizes]}."""
    if buckets is None:
        raise ValueError("bucketize requires buckets={dim: [sizes]}")

    def wrap(fn):
        return BucketedFunction(fn, buckets, pad_value)

    if function is not None:
        return wrap(function)
    return wrap
