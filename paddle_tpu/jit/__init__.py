"""paddle.jit analogue — program capture onto XLA.

Re-design of the reference's to_static stack (ref: python/paddle/jit/api.py:197
StaticFunction/ProgramTranslator; fluid/framework/new_executor
standalone_executor.h:34) for the XLA compilation model: instead of
source-to-source AST rewriting + a PIR interpreter, the define-by-run tape is
*pure traceable Python over jax arrays*, so one `jax.jit` trace captures
forward + backward + optimizer into a single XLA program with buffer
donation and a compile cache (XLA plays the role of PIR passes + CINN).

Two entry points:
  * ``to_static(fn_or_layer)``  — stage any tensor function / Layer forward.
  * ``TrainStep(model, loss_fn, optimizer)`` — stage the full training step
    (fwd + bwd + clip + update); parameters and optimizer state are donated
    so updates happen in-place in device memory.
"""
from .api import StaticFunction, TrainStep, ignore_module, not_to_static, to_static
from .bucketing import BucketedFunction, bucketize
from .serialization import InputSpec, TranslatedLayer, load, save

__all__ = [
    "to_static",
    "not_to_static",
    "ignore_module",
    "StaticFunction",
    "TrainStep",
    "save",
    "load",
    "InputSpec",
    "TranslatedLayer",
    "bucketize",
    "BucketedFunction",
]
