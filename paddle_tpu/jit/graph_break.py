"""Graph-break fallback for ``to_static(full_graph=False)``.

ref: the reference's SOT bytecode JIT (fluid/pybind/sot/eval_frame.c +
jit/sot/opcode_translator/executor/opcode_executor.py) runs arbitrary
Python by symbolically interpreting bytecode, BREAKING the graph at
untraceable points (data-dependent branches) and compiling the traceable
segments between breaks.

TPU-native honest subset, without a bytecode VM: a LAZY-SEGMENT engine at
the op-dispatch layer. The staged fast path (one jax.jit trace) is tried
first; when tracing dies on data-dependent Python control flow
(TracerBoolConversionError / ConcretizationTypeError — bool()/int() on a
tracer), the function re-runs in segment mode:

  * every dispatched op is RECORDED, not executed; outputs carry abstract
    shape/dtype (jax.eval_shape) in a `_Deferred` payload,
  * when Python needs a concrete value (``bool(t)``, ``t.item()``,
    ``.numpy()`` — exactly the reference's graph-break triggers), the
    pending segment FLUSHES: it compiles to ONE XLA program (cached by
    program signature) and executes, filling every deferred tensor,
  * the branch proceeds on the concrete value and a new segment begins.

So `if loss > 0:` costs one segment boundary, and everything between
boundaries still runs compiled — the SOT contract, expressed in dataflow
instead of bytecode.

GRADIENTS compose with segments (the reference's SOT compiles fwd+bwd
partial programs around each break, partial_program.py): when grads are
required, each flushed segment executes through dispatch.call as ONE
tape op whose vjp is the transposed compiled segment. Segment inputs
that were earlier segments' outputs are ordinary tape tensors, so
cotangents stitch across the break points through the normal eager tape
— loss.backward() after the call sees one GradNode per segment.
"""
from __future__ import annotations

import jax

from ..core import autograd, dispatch
from ..core.tensor import Tensor

__all__ = ["GraphBreakFunction", "BREAK_ERRORS"]

BREAK_ERRORS = (
    jax.errors.TracerBoolConversionError,
    jax.errors.ConcretizationTypeError,
    jax.errors.TracerIntegerConversionError,
    jax.errors.TracerArrayConversionError,
)


class _Deferred:
    """Abstract placeholder payload for a not-yet-flushed op output."""

    __slots__ = ("aval", "segment", "slot")

    def __init__(self, aval, segment, slot):
        self.aval = aval
        self.segment = segment
        self.slot = slot

    @property
    def shape(self):
        return self.aval.shape

    @property
    def dtype(self):
        return self.aval.dtype

    @property
    def ndim(self):
        return len(self.aval.shape)


class _Segment:
    """One pending compiled region: a straight-line op list."""

    def __init__(self, owner, grad_mode=False):
        self.owner = owner
        self.grad_mode = grad_mode
        self.nodes = []        # (impl, flat_args, treedef, attrs, n_out)
        self.ext = []          # concrete external jax arrays
        self.ext_tensors = []  # the Tensors behind ext (tape inputs)
        self.ext_ids = {}      # id(array) -> ext slot
        self.out_tensors = []  # deferred Tensors to fill on flush
        self.n_slots = 0

    # -- recording ---------------------------------------------------------
    def _arg_ref(self, x):
        if isinstance(x, Tensor):
            d = x._data
            if isinstance(d, _Deferred) and d.segment is self:
                return ("slot", d.slot)
            arr = d if not isinstance(d, _Deferred) else _flush_get(x)
            key = id(arr)
            if key not in self.ext_ids:
                self.ext_ids[key] = len(self.ext)
                self.ext.append(arr)
                self.ext_tensors.append(x)
            return ("ext", self.ext_ids[key])
        return ("const", x)

    def record(self, op_name, impl, args, attrs):
        flat, treedef = jax.tree_util.tree_flatten(
            args, is_leaf=lambda v: isinstance(v, Tensor)
        )
        refs = [self._arg_ref(x) for x in flat]

        def abstract(ref):
            kind, v = ref
            if kind == "slot":
                return self._slot_aval(v)
            if kind == "ext":
                a = self.ext[v]
                return jax.ShapeDtypeStruct(a.shape, a.dtype)
            return v

        aval_flat = [abstract(r) for r in refs]

        def meta_fn(*tensor_avals):
            it = iter(tensor_avals)
            rebuilt = [
                next(it) if r[0] != "const" else r[1] for r in refs
            ]
            return impl(
                *jax.tree_util.tree_unflatten(treedef, rebuilt), **attrs
            )

        tensor_avals = [a for r, a in zip(refs, aval_flat)
                        if r[0] != "const"]
        out_aval = jax.eval_shape(meta_fn, *tensor_avals)
        out_flat, out_tree = jax.tree_util.tree_flatten(out_aval)
        base = self.n_slots
        self.n_slots += len(out_flat)
        self.nodes.append(
            (op_name, impl, refs, treedef, dict(attrs), base,
             len(out_flat))
        )
        outs = []
        for i, av in enumerate(out_flat):
            t = Tensor.__new__(Tensor)
            t.__init__(jax.numpy.zeros((), "float32"))  # placeholder init
            t._data = _Deferred(av, self, base + i)
            # grad mode: deferred outputs read as grad-requiring until
            # the flush wires real tape nodes (flush overwrites this)
            t.stop_gradient = not self.grad_mode
            self.out_tensors.append(t)
            outs.append(t)
        self.owner.stats["staged_ops"] += 1
        return jax.tree_util.tree_unflatten(out_tree, outs)

    def _slot_aval(self, slot):
        for t in self.out_tensors:
            d = t._data
            if isinstance(d, _Deferred) and d.slot == slot:
                return jax.ShapeDtypeStruct(d.shape, d.dtype)
        raise KeyError(slot)

    # -- flushing ----------------------------------------------------------
    def signature(self):
        return tuple(
            (name, id(impl), tuple(r[0] + str(r[1]) if r[0] != "const"
                                   else "c" + repr(r[1]) for r in refs),
             repr(sorted(attrs.items())), base, n_out)
            for name, impl, refs, treedef, attrs, base, n_out in self.nodes
        ) + tuple((a.shape, str(a.dtype)) for a in self.ext)

    def build_replay(self):
        nodes = list(self.nodes)

        def replay(ext):
            env = [None] * self.n_slots
            for name, impl, refs, treedef, attrs, base, n_out in nodes:
                rebuilt = []
                for kind, v in refs:
                    if kind == "slot":
                        rebuilt.append(env[v])
                    elif kind == "ext":
                        rebuilt.append(ext[v])
                    else:
                        rebuilt.append(v)
                out = impl(
                    *jax.tree_util.tree_unflatten(treedef, rebuilt),
                    **attrs,
                )
                out_flat = jax.tree_util.tree_flatten(out)[0]
                for i, a in enumerate(out_flat):
                    env[base + i] = a
            return env

        return replay

    def flush(self):
        if not self.nodes:
            return
        sig = self.signature()
        jitted = self.owner._compile_cache.get(sig)
        if jitted is None:
            jitted = jax.jit(self.build_replay())
            self.owner._compile_cache[sig] = jitted
        want_grad = self.grad_mode and autograd.is_grad_enabled() and any(
            not t.stop_gradient for t in self.ext_tensors
            if isinstance(t, Tensor)
        )
        if want_grad:
            # ONE tape op for the whole segment: jax.vjp of the jitted
            # replay runs compiled in both directions; the dispatch hook
            # must be off or the replay's call would be re-recorded
            def seg_impl(ext):
                return tuple(jitted(ext))

            prev_hook = dispatch._segment_hook
            dispatch._segment_hook = None
            try:
                outs = dispatch.call(
                    "graph_segment", seg_impl,
                    (list(self.ext_tensors),), {},
                )
            finally:
                dispatch._segment_hook = prev_hook
            outs = (list(outs) if isinstance(outs, (tuple, list))
                    else [outs])
            for t in self.out_tensors:
                d = t._data
                if isinstance(d, _Deferred):
                    o = outs[d.slot]
                    t._data = o._data
                    t._grad_node = o._grad_node
                    t._out_index = o._out_index
                    t.stop_gradient = o.stop_gradient
        else:
            env = jitted(self.ext)
            for t in self.out_tensors:
                d = t._data
                if isinstance(d, _Deferred):
                    t._data = env[d.slot]
                    t.stop_gradient = True
        self.owner.stats["segments"] += 1
        self.nodes, self.ext, self.ext_ids = [], [], {}
        self.ext_tensors = []
        self.out_tensors, self.n_slots = [], 0


def _flush_get(tensor):
    d = tensor._data
    if isinstance(d, _Deferred):
        d.segment.flush()
    return tensor._data


class _segment_scope:
    """Install the dispatch + concretization hooks for one call."""

    def __init__(self, owner, grad_mode=False):
        self.owner = owner
        self.segment = _Segment(owner, grad_mode=grad_mode)

    def __enter__(self):
        self._prev_hook = dispatch._segment_hook
        dispatch._segment_hook = self._record
        from ..core import tensor as tensor_mod

        self._prev_flush = tensor_mod._lazy_flush_hook
        tensor_mod._lazy_flush_hook = _flush_get
        return self

    def _record(self, op_name, impl, args, attrs):
        return self.segment.record(op_name, impl, args, attrs)

    def __exit__(self, *exc):
        dispatch._segment_hook = self._prev_hook
        from ..core import tensor as tensor_mod

        tensor_mod._lazy_flush_hook = self._prev_flush
        if exc[0] is None:
            self.segment.flush()
        return False


class GraphBreakFunction:
    """``to_static(full_graph=False)`` wrapper: full-graph staging with
    automatic graph-break fallback (class docstring above)."""

    def __init__(self, function, layer=None):
        from .api import StaticFunction

        self._function = function
        self._layer = layer
        self._static = StaticFunction(function, layer=layer)
        self._compile_cache = {}
        self.mode = "full"
        self.stats = {"segments": 0, "staged_ops": 0, "breaks": 0,
                      "eager_calls": 0}

    def __call__(self, *args, **kwargs):
        if self.mode == "full":
            try:
                return self._static(*args, **kwargs)
            except BREAK_ERRORS:
                # data-dependent Python control flow: fall back for this
                # and future calls (the reference caches the break point
                # via guards; our guard is the callable itself)
                self.mode = "segment"
                self.stats["breaks"] += 1

        def _wants_grad(tree):
            from ..nn.layer.layers import Layer

            for v in jax.tree_util.tree_leaves(
                tree, is_leaf=lambda x: isinstance(x, (Tensor, Layer))
            ):
                if isinstance(v, Tensor) and not v.stop_gradient:
                    return True
                if isinstance(v, Layer) and any(
                    not p.stop_gradient for p in v.parameters()
                ):
                    return True
            return False

        grads_needed = autograd.is_grad_enabled() and (
            any(not p.stop_gradient for p in (self._static._params or []))
            or _wants_grad((args, kwargs))
        )
        if grads_needed:
            # segments still compile: each flush is one tape op (fwd
            # compiled, vjp = transposed compiled segment), stitched by
            # the eager tape across break points
            self.stats["grad_segment_calls"] = (
                self.stats.get("grad_segment_calls", 0) + 1
            )
            with _segment_scope(self, grad_mode=True):
                return self._function(*args, **kwargs)
        with _segment_scope(self), autograd.no_grad():
            return self._function(*args, **kwargs)
