"""Quantization (ref: python/paddle/quantization — QAT/PTQ frameworks
with observers/quanters; static/quantization passes).

TPU-first scope: simulated quantization (fake-quant with straight-through
gradients) for QAT, and abs-max observers for PTQ calibration. int8
matmuls execute on the MXU via XLA's native int8 support when weights are
converted; the reference's TensorRT deployment path has no analogue.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer

__all__ = [
    "QuantConfig", "QAT", "PTQ", "AbsmaxObserver", "FakeQuanterWithAbsMax",
    "quant_dequant",
    "PerChannelAbsmaxObserver", "EMAObserver",
    "weight_quantize", "weight_dequantize", "quantize_weights",
    "weight_quantize_grouped", "quantize_moe_experts",
]


def quant_dequant(x, scale, bits=8):
    """Simulated quantization with a straight-through estimator (ref:
    quantization/quanters fake-quant ops): rounding is treated as
    identity in backward via x + stop_grad(qdq(x) - x)."""
    from .. import ops as F

    qmax = float(2 ** (bits - 1) - 1)
    s = scale if isinstance(scale, Tensor) else Tensor(
        np.asarray(scale, np.float32)
    )
    scaled = x / s * qmax
    rounded = F.round(scaled)
    clipped = F.clip(rounded, -qmax, qmax)
    qdq = clipped / qmax * s
    return x + (qdq - x).detach()


class AbsmaxObserver(Layer):
    """PTQ calibration observer (ref: quantization/observers/abs_max.py):
    tracks the running max |x| to derive the scale."""

    def __init__(self, quant_bits=8):
        super().__init__()
        self.quant_bits = quant_bits
        self._max = 0.0

    def forward(self, x):
        from .. import ops as F

        cur = float(F.max(F.abs(x)).numpy())
        self._max = max(self._max, cur)
        return x

    def scale(self):
        return max(self._max, 1e-8)


class FakeQuanterWithAbsMax(Layer):
    """QAT quanter (ref: quantization/quanters/abs_max.py): per-call
    abs-max scale + STE fake-quant."""

    def __init__(self, quant_bits=8):
        super().__init__()
        self.quant_bits = quant_bits

    def forward(self, x):
        from .. import ops as F

        scale = F.max(F.abs(x.detach()))
        return quant_dequant(x, scale + 1e-8, self.quant_bits)


class QuantConfig:
    """ref: quantization/config.py QuantConfig — which layer types get
    which activation/weight quanters."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight
        self._type_cfgs = {}  # layer_type -> (activation, weight)

    def add_type_config(self, layer_types, activation=None, weight=None):
        if not isinstance(layer_types, (list, tuple)):
            layer_types = [layer_types]
        for lt in layer_types:
            self._type_cfgs[lt] = (activation, weight)

    def quantable_types(self):
        if self._type_cfgs:
            return tuple(self._type_cfgs)
        from ..nn.layer.common import Linear
        from ..nn.layer.conv import Conv2D

        return (Linear, Conv2D)

    def quanters_for(self, layer):
        """Fresh (activation, weight) quanter instances for this layer,
        honoring per-type overrides then the global defaults."""
        import copy

        act, w = None, None
        for lt, (a_, w_) in self._type_cfgs.items():
            if isinstance(layer, lt):
                act, w = a_, w_
                break
        act = act or self.activation
        w = w or self.weight
        mk = lambda q: (
            copy.deepcopy(q) if q is not None else FakeQuanterWithAbsMax()
        )
        return mk(act), mk(w)


class _QuantWrapper(Layer):
    """Wraps a layer: fake-quant its input activation and weight."""

    def __init__(self, inner, config: QuantConfig):
        super().__init__()
        self.inner = inner
        self.act_q, self.w_q = config.quanters_for(inner)

    def forward(self, *args, **kwargs):
        args = tuple(
            self.act_q(a) if isinstance(a, Tensor) else a for a in args
        )
        w = self.inner.weight
        orig = w._data
        qdq_w = self.w_q(w)
        w._data = qdq_w._data
        # carry the STE grad path: route through the quantized weight's
        # tape node by temporarily swapping payload+node
        node, oi, sg = w._grad_node, w._out_index, w.stop_gradient
        w._grad_node = qdq_w._grad_node
        w._out_index = qdq_w._out_index
        w.stop_gradient = qdq_w.stop_gradient
        try:
            out = self.inner(*args, **kwargs)
        finally:
            w._data = orig
            w._grad_node, w._out_index, w.stop_gradient = node, oi, sg
        return out


class QAT:
    """ref: quantization/qat.py QAT.quantize — wrap quantable layers with
    fake quanters for quantization-aware training."""

    def __init__(self, config: QuantConfig = None):
        self.config = config or QuantConfig()

    def quantize(self, model: Layer, inplace=False):
        if not inplace:
            import copy

            model = copy.deepcopy(model)
        types = self.config.quantable_types()

        def convert(layer):
            for name, sub in list(layer.named_children()):
                if isinstance(sub, types):
                    setattr(layer, name, _QuantWrapper(sub, self.config))
                else:
                    convert(sub)

        convert(model)
        return model


class PTQ:
    """ref: quantization/ptq.py PTQ — insert observers, calibrate with
    data, then `convert` bakes the scales into fake-quant wrappers."""

    def __init__(self, config: QuantConfig = None):
        self.config = config or QuantConfig()
        self._observers = []

    def quantize(self, model: Layer, inplace=False):
        if not inplace:
            import copy

            model = copy.deepcopy(model)
        types = self.config.quantable_types()
        observers = self._observers

        class _Observed(Layer):
            def __init__(self, inner):
                super().__init__()
                self.inner = inner
                self.obs = AbsmaxObserver()
                observers.append(self.obs)

            def forward(self, *a, **k):
                a = tuple(
                    self.obs(x) if isinstance(x, Tensor) else x for x in a
                )
                return self.inner(*a, **k)

        def convert(layer):
            for name, sub in list(layer.named_children()):
                if isinstance(sub, types):
                    setattr(layer, name, _Observed(sub))
                else:
                    convert(sub)

        convert(model)
        return model

    def convert(self, model: Layer, inplace=False):
        """Replace observers with fixed-scale fake quant on activations."""
        def swap(layer):
            for name, sub in list(layer.named_children()):
                if type(sub).__name__ == "_Observed":
                    scale = sub.obs.scale()
                    inner = sub.inner

                    class _Fixed(Layer):
                        def __init__(self, inner, scale):
                            super().__init__()
                            self.inner = inner
                            self._scale = scale

                        def forward(self, *a, **k):
                            a = tuple(
                                quant_dequant(x, self._scale)
                                if isinstance(x, Tensor) else x
                                for x in a
                            )
                            return self.inner(*a, **k)

                    setattr(layer, name, _Fixed(inner, scale))
                else:
                    swap(sub)

        swap(model)
        return model


class PerChannelAbsmaxObserver(Layer):
    """Per-output-channel absmax calibration (ref:
    quantization/observers/abs_max_headwise.py / channel-wise observers):
    one scale per slice along ``quant_axis``."""

    def __init__(self, quant_bits=8, quant_axis=-1):
        super().__init__()
        self.quant_bits = quant_bits
        self.quant_axis = quant_axis
        self._absmax = None

    def forward(self, x):
        import jax.numpy as jnp

        axis = self.quant_axis % x.ndim
        reduce_axes = tuple(d for d in range(x.ndim) if d != axis)
        cur = jnp.max(jnp.abs(x._data), axis=reduce_axes)
        self._absmax = (
            cur if self._absmax is None
            else jnp.maximum(self._absmax, cur)
        )
        return x

    def scale(self):
        import jax.numpy as jnp

        if self._absmax is None:
            raise RuntimeError("observer has seen no data")
        return Tensor(jnp.maximum(self._absmax, 1e-8))


class EMAObserver(Layer):
    """Moving-average absmax (ref: quantization/observers/ema.py —
    activation ranges smoothed across calibration batches)."""

    def __init__(self, quant_bits=8, moving_rate=0.9):
        super().__init__()
        self.quant_bits = quant_bits
        self.moving_rate = moving_rate
        self._ema = None

    def forward(self, x):
        import jax.numpy as jnp

        cur = float(jnp.max(jnp.abs(x._data)))
        self._ema = (
            cur if self._ema is None
            else self.moving_rate * self._ema
            + (1 - self.moving_rate) * cur
        )
        return x

    def scale(self):
        if self._ema is None:
            raise RuntimeError("observer has seen no data")
        return Tensor(np.asarray(max(self._ema, 1e-8), np.float32))


def weight_quantize(w, bits=8, quant_axis=-1):
    """Real int8 weight quantization (the deployment path; ref
    quantization int8 export): returns (int8 weights, per-channel fp32
    scales along quant_axis)."""
    import jax.numpy as jnp

    arr = w._data if isinstance(w, Tensor) else jnp.asarray(w)
    axis = quant_axis % arr.ndim
    reduce_axes = tuple(d for d in range(arr.ndim) if d != axis)
    qmax = float(2 ** (bits - 1) - 1)
    scale = jnp.maximum(
        jnp.max(jnp.abs(arr), axis=reduce_axes, keepdims=True), 1e-8
    )
    q = jnp.clip(jnp.round(arr / scale * qmax), -qmax, qmax).astype(
        jnp.int8
    )
    return Tensor(q), Tensor(jnp.squeeze(scale, reduce_axes) / qmax)


def weight_dequantize(q, scale, quant_axis=-1):
    """Inverse of weight_quantize."""
    import jax.numpy as jnp

    qa = q._data if isinstance(q, Tensor) else jnp.asarray(q)
    sa = scale._data if isinstance(scale, Tensor) else jnp.asarray(scale)
    axis = quant_axis % qa.ndim
    shape = [1] * qa.ndim
    shape[axis] = qa.shape[axis]
    return Tensor(qa.astype(jnp.float32) * sa.reshape(shape))


def weight_quantize_grouped(w, bits=8):
    """Per-expert, per-output-channel int8 quantization of stacked MoE
    expert weights ``[e, k, f]``: one scale per (expert, output channel)
    — absmax over the contraction axis — so each expert's quantization
    error is independent of its siblings' weight ranges. Returns
    (int8 weights [e, k, f], fp32 scales [e, f]) with
    ``w ≈ q * scales[:, None, :]`` (the same scale convention as
    :func:`weight_quantize`)."""
    import jax.numpy as jnp

    arr = w._data if isinstance(w, Tensor) else jnp.asarray(w)
    if arr.ndim != 3:
        raise ValueError(
            f"weight_quantize_grouped expects stacked [e, k, f] expert "
            f"weights, got shape {tuple(arr.shape)}"
        )
    qmax = float(2 ** (bits - 1) - 1)
    scale = jnp.maximum(
        jnp.max(jnp.abs(arr), axis=1, keepdims=True), 1e-8
    )  # [e, 1, f]
    q = jnp.clip(jnp.round(arr / scale * qmax), -qmax, qmax).astype(
        jnp.int8
    )
    return Tensor(q), Tensor(scale[:, 0, :] / qmax)


def quantize_moe_experts(model, bits=8):
    """Weight-only int8 deployment conversion for MoE expert FFNs (the
    serving memory win for the widest weights in an MoE model): every
    ``incubate.SwiGLUExperts`` under ``model`` has its three stacked
    projections replaced IN PLACE by int8 weights plus per-channel fp32
    scales (``weight_quantize_grouped``). The quantized experts run
    only through ``MoELayer(impl="ragged")``, where ``grouped_matmul``
    dequantizes in-kernel — no dense float copy is ever rebuilt.
    Inference-only: quantized weights are marked stop_gradient. The
    scales are registered as buffers, so ``state_dict()`` of a
    quantized model carries them next to the int8 weights — quantize
    the target model BEFORE loading such a state_dict (the structural
    conversion, like QAT wrapping, is not re-derived from the dict).

    Returns {sublayer_name: bytes_saved}.
    """
    from ..incubate.moe import SwiGLUExperts

    out = {}
    for name, sub in model.named_sublayers(include_self=True):
        if not isinstance(sub, SwiGLUExperts) or sub.quantized:
            continue
        saved = 0
        for wn in ("w_gate", "w_up", "w_down"):
            w = getattr(sub, wn)
            q, s = weight_quantize_grouped(w, bits=bits)
            before = w._data.size * w._data.dtype.itemsize
            w._rebind(q._data)
            w.stop_gradient = True
            s.stop_gradient = True
            sub.register_buffer(wn + "_scale", s)
            saved += before - (
                q._data.size * q._data.dtype.itemsize
                + s._data.size * s._data.dtype.itemsize
            )
        out[name or "root"] = saved
    return out


def quantize_weights(model, bits=8, layer_types=("Linear",)):
    """Weight-only int8 deployment conversion: every matching layer's
    weight is replaced by dequantize(int8(w)) (the serving memory win;
    XLA folds the dequant into the matmul). Returns
    {layer_name: (int8_weights, scales)} for export."""
    out = {}
    for name, sub in model.named_sublayers(include_self=True):
        if type(sub).__name__ not in layer_types:
            continue
        w = getattr(sub, "weight", None)
        if w is None or w.ndim < 2:
            continue
        q, s = weight_quantize(w, bits=bits)
        w._rebind(weight_dequantize(q, s)._data.astype(w._data.dtype))
        out[name or "root"] = (q, s)
    return out
