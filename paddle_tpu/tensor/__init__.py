"""paddle.tensor namespace (ref: python/paddle/tensor/__init__.py —
the functional tensor surface plus the TensorArray helpers from
tensor/array.py)."""
from ..core.aux_tensors import (
    StringTensor,
    TensorArray,
    array_length,
    array_read,
    array_write,
    create_array,
)
from ..ops import api as _api

# mirror the op surface by name rather than star-import: custom-op
# registration (utils/cpp_extension) may append names to
# ops.api.__all__ whose attributes live on other modules
_ops_all = [n for n in _api.__all__ if hasattr(_api, n)]
globals().update({n: getattr(_api, n) for n in _ops_all})

__all__ = list(_ops_all) + [
    "TensorArray", "StringTensor", "create_array", "array_write",
    "array_read", "array_length",
]
