"""paddle.tensor namespace (ref: python/paddle/tensor/__init__.py —
the functional tensor surface plus the TensorArray helpers from
tensor/array.py)."""
from ..core.aux_tensors import (
    StringTensor,
    TensorArray,
    array_length,
    array_read,
    array_write,
    create_array,
)
from ..ops.api import *  # noqa: F401,F403
from ..ops.api import __all__ as _ops_all

__all__ = list(_ops_all) + [
    "TensorArray", "StringTensor", "create_array", "array_write",
    "array_read", "array_length",
]
