"""Layer — the module base class.

ref: python/paddle/nn/layer/layers.py:353. Same contract: parameter /
buffer / sublayer registries via __setattr__, forward pre/post hooks,
train/eval flags, state_dict with structured names. TPU addition:
`raw_params()` exposes the pytree the jit layer stages into XLA.
"""
from __future__ import annotations

import collections

import numpy as np

from ...core import autograd
from ...core.dtype import convert_dtype
from ...core.tensor import Tensor
from .. import initializer as I
from ..parameter import Parameter, ParamAttr


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = dtype
        self._parameters = collections.OrderedDict()
        self._sub_layers = collections.OrderedDict()
        self._buffers = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._hook_id = 0
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # -- construction ------------------------------------------------------
    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias=False,
        default_initializer=None,
    ):
        attr = ParamAttr._to_attr(attr)
        dtype = I._init_override["dtype"] or dtype or self._dtype
        init = (
            I._init_override["initializer"]
            or attr.initializer
            or default_initializer
            or (I.Constant(0.0) if is_bias else I._global_initializer["weight"])
        )
        data = init(shape, dtype=convert_dtype(dtype).name)
        p = Parameter(data, trainable=attr.trainable, name=attr.name)
        p.optimize_attr["learning_rate"] = attr.learning_rate
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # -- attribute magic ---------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            params[name] = value
            self.__dict__.pop(name, None)
            self._sub_layers.pop(name, None)
            self._buffers.pop(name, None)
            return
        subs = self.__dict__.get("_sub_layers")
        if isinstance(value, Layer):
            if subs is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            subs[name] = value
            self.__dict__.pop(name, None)
            if params is not None:
                params.pop(name, None)
            return
        bufs = self.__dict__.get("_buffers")
        if bufs is not None and name in bufs:
            bufs[name] = value
            return
        if params is not None and name in params:
            if value is None:
                params[name] = None
                return
            raise TypeError(f"cannot override parameter {name!r} with non-Parameter")
        object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for registry in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(registry)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"{type(self).__name__!r} object has no attribute {name!r}"
        )

    def __delattr__(self, name):
        for registry in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(registry)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return (
            list(super().__dir__())
            + list(self._parameters)
            + list(self._sub_layers)
            + list(self._buffers)
        )

    # -- traversal ---------------------------------------------------------
    def children(self):
        for _, layer in self.named_children():
            yield layer

    def named_children(self):
        seen = set()
        for name, layer in self._sub_layers.items():
            if layer is not None and id(layer) not in seen:
                seen.add(id(layer))
                yield name, layer

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        if layers_set is None:
            layers_set = set()
        if include_self and id(self) not in layers_set:
            layers_set.add(id(self))
            yield prefix, self
        for name, layer in self.named_children():
            if layer is None or id(layer) in layers_set:
                continue
            sub_prefix = prefix + ("." if prefix else "") + name
            yield from layer.named_sublayers(
                prefix=sub_prefix, include_self=True, layers_set=layers_set
            )

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        layers = (
            self.named_sublayers(prefix=prefix, include_self=True)
            if include_sublayers
            else [(prefix, self)]
        )
        for layer_prefix, layer in layers:
            for name, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield layer_prefix + ("." if layer_prefix else "") + name, p

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        layers = (
            self.named_sublayers(prefix=prefix, include_self=True)
            if include_sublayers
            else [(prefix, self)]
        )
        for layer_prefix, layer in layers:
            for name, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield layer_prefix + ("." if layer_prefix else "") + name, b

    def apply(self, fn):
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    # -- modes -------------------------------------------------------------
    def train(self):
        for layer in self.sublayers(include_self=True):
            layer.training = True
        return self

    def eval(self):
        for layer in self.sublayers(include_self=True):
            layer.training = False
        return self

    # -- hooks -------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # -- call --------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    # -- state dict --------------------------------------------------------
    def state_dict(self, include_sublayers=True, structured_name_prefix="", keep_vars=True):
        out = collections.OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix):
            out[name] = p
        for layer_prefix, layer in self.named_sublayers(
            prefix=structured_name_prefix, include_self=True
        ):
            for bname, b in layer._buffers.items():
                if b is None or bname in layer._non_persistable_buffer_names:
                    continue
                out[layer_prefix + ("." if layer_prefix else "") + bname] = b
        return out

    def set_state_dict(self, state_dict, use_structured_name=True):
        missing, unexpected = [], []
        own = self.state_dict()
        consumed = set()
        with autograd.no_grad():
            for name, target in own.items():
                if name in state_dict:
                    src = state_dict[name]
                    arr = src._data if isinstance(src, Tensor) else np.asarray(src)
                    if tuple(np.shape(arr)) != tuple(target._data.shape):
                        raise ValueError(
                            f"shape mismatch for {name}: ckpt {np.shape(arr)} vs "
                            f"model {tuple(target._data.shape)}"
                        )
                    import jax.numpy as jnp

                    target._rebind(jnp.asarray(arr, dtype=target._data.dtype))
                    consumed.add(name)
                else:
                    missing.append(name)
        unexpected = [k for k in state_dict if k not in consumed]
        return missing, unexpected

    load_dict = set_state_dict

    # -- dtype/device ------------------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        return self._to_impl(device=device, dtype=dtype)

    def _to_impl(self, device=None, dtype=None):
        import jax
        import jax.numpy as jnp

        with autograd.no_grad():
            for t in list(self.parameters()) + list(self.buffers()):
                arr = t._data
                if dtype is not None and t.dtype.is_floating:
                    arr = arr.astype(convert_dtype(dtype).jnp_dtype)
                if device is not None:
                    from ...core.device import parse_device

                    arr = jax.device_put(arr, parse_device(device).jax_device)
                t._rebind(arr)
        if dtype is not None:
            self._dtype = convert_dtype(dtype).name
        return self

    def astype(self, dtype):
        return self._to_impl(dtype=dtype)

    def float(self):
        return self._to_impl(dtype="float32")

    def bfloat16(self):
        return self._to_impl(dtype="bfloat16")

    def half(self):
        return self._to_impl(dtype="float16")

    # -- misc --------------------------------------------------------------
    def full_name(self):
        return self._name_scope

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, child in self.named_children():
            child_repr = repr(child).replace("\n", "\n  ")
            lines.append(f"({name}): {child_repr}")
        main = self.__class__.__name__ + "(" + extra
        if lines:
            main += "\n  " + "\n  ".join(lines) + "\n"
        return main + ")"
