"""Recurrent layers (ref: python/paddle/nn/layer/rnn.py — RNNCellBase:152,
SimpleRNNCell:271, LSTMCell:404, GRUCell:569, RNN:723, BiRNN:810,
SimpleRNN/LSTM/GRU over _RNNBase:1211).

TPU-first: the multi-layer classes call the fused `rnn` op (one lax.scan per
layer/direction inside a single tape entry) rather than a Python loop over
cells; the cell classes remain for single-step use and the generic RNN
wrapper.
"""
from __future__ import annotations

import numpy as np

from ... import ops as F
from .. import initializer as I
from ..parameter import ParamAttr
from .layers import Layer

__all__ = [
    "RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell",
    "RNN", "BiRNN", "SimpleRNN", "LSTM", "GRU",
]


class RNNCellBase(Layer):
    """ref: nn/layer/rnn.py:152. get_initial_states builds zero states."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        batch = batch_ref.shape[batch_dim_idx]
        hidden = self.hidden_size
        state_shape = getattr(self, "state_shape", (hidden,))
        if isinstance(state_shape, tuple) and state_shape and isinstance(
            state_shape[0], (tuple, list)
        ):
            return tuple(
                F.full([batch] + list(s), init_value, dtype or "float32")
                for s in state_shape
            )
        return F.full(
            [batch] + list(state_shape), init_value, dtype or "float32"
        )


def _cell_params(layer, input_size, hidden_size, n_gates, weight_ih_attr,
                 weight_hh_attr, bias_ih_attr, bias_hh_attr):
    std = 1.0 / np.sqrt(hidden_size)
    for name, shape, attr_in in (
        ("weight_ih", [n_gates * hidden_size, input_size], weight_ih_attr),
        ("weight_hh", [n_gates * hidden_size, hidden_size], weight_hh_attr),
    ):
        attr = ParamAttr._to_attr(attr_in)
        if attr.initializer is None:
            attr.initializer = I.Uniform(-std, std)
        setattr(layer, name, layer.create_parameter(shape=shape, attr=attr))
    for name, attr_in in (
        ("bias_ih", bias_ih_attr),
        ("bias_hh", bias_hh_attr),
    ):
        if attr_in is False:
            setattr(layer, name, None)
            layer.add_parameter(name, None)
            continue
        attr = ParamAttr._to_attr(attr_in)
        if attr.initializer is None:
            attr.initializer = I.Uniform(-std, std)
        setattr(
            layer,
            name,
            layer.create_parameter(
                shape=[n_gates * hidden_size], attr=attr, is_bias=True
            ),
        )


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        if activation not in ("tanh", "relu"):
            raise ValueError("activation must be tanh or relu")
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        _cell_params(self, input_size, hidden_size, 1, weight_ih_attr,
                     weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h = states
        z = F.matmul(inputs, self.weight_ih, transpose_y=True)
        if self.bias_ih is not None:
            z = z + self.bias_ih
        z = z + F.matmul(h, self.weight_hh, transpose_y=True)
        if self.bias_hh is not None:
            z = z + self.bias_hh
        h_new = F.tanh(z) if self.activation == "tanh" else F.relu(z)
        return h_new, h_new

    @property
    def state_shape(self):
        return (self.hidden_size,)


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        _cell_params(self, input_size, hidden_size, 4, weight_ih_attr,
                     weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h, c = states
        gates = F.matmul(inputs, self.weight_ih, transpose_y=True)
        if self.bias_ih is not None:
            gates = gates + self.bias_ih
        gates = gates + F.matmul(h, self.weight_hh, transpose_y=True)
        if self.bias_hh is not None:
            gates = gates + self.bias_hh
        i, f, g, o = F.split(gates, 4, axis=-1)
        i = F.sigmoid(i)
        f = F.sigmoid(f)
        g = F.tanh(g)
        o = F.sigmoid(o)
        c_new = f * c + i * g
        h_new = o * F.tanh(c_new)
        return h_new, (h_new, c_new)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        _cell_params(self, input_size, hidden_size, 3, weight_ih_attr,
                     weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h = states
        gi = F.matmul(inputs, self.weight_ih, transpose_y=True)
        if self.bias_ih is not None:
            gi = gi + self.bias_ih
        gh = F.matmul(h, self.weight_hh, transpose_y=True)
        if self.bias_hh is not None:
            gh = gh + self.bias_hh
        ri, zi, ci = F.split(gi, 3, axis=-1)
        rh, zh, ch = F.split(gh, 3, axis=-1)
        r = F.sigmoid(ri + rh)
        z = F.sigmoid(zi + zh)
        c = F.tanh(ci + r * ch)
        h_new = (F.ones_like(z) - z) * c + z * h
        return h_new, h_new

    @property
    def state_shape(self):
        return (self.hidden_size,)


class RNN(Layer):
    """Generic cell-driven sweep (ref: nn/layer/rnn.py:723). Python loop —
    use SimpleRNN/LSTM/GRU for the fused scan path."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        axis = 0 if self.time_major else 1
        steps = inputs.shape[axis]
        states = (
            initial_states
            if initial_states is not None
            else self.cell.get_initial_states(
                inputs, batch_dim_idx=1 if self.time_major else 0
            )
        )
        outs = []
        order = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        for t in order:
            xt = (
                F.getitem(inputs, (t,))
                if self.time_major
                else F.getitem(inputs, (slice(None), t))
            )
            out, states = self.cell(xt, states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        output = F.stack(outs, axis=axis)
        return output, states


class BiRNN(Layer):
    """ref: nn/layer/rnn.py:810 — forward + backward cells, concat outputs."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        if initial_states is None:
            states_fw = states_bw = None
        else:
            states_fw, states_bw = initial_states
        out_fw, st_fw = self.rnn_fw(inputs, states_fw)
        out_bw, st_bw = self.rnn_bw(inputs, states_bw)
        return F.concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)


class _RNNBase(Layer):
    """Multi-layer fused path over the `rnn` op (ref: nn/layer/rnn.py:1211
    _RNNBase driving _C_ops.rnn)."""

    _mode = "LSTM"
    _n_gates = 4
    _n_states = 2

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        if direction not in ("forward", "bidirect", "bidirectional"):
            raise ValueError(f"bad direction {direction!r}")
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.bidirectional = direction in ("bidirect", "bidirectional")
        self.time_major = time_major
        self.dropout = dropout
        d = 2 if self.bidirectional else 1

        std = 1.0 / np.sqrt(hidden_size)
        self._flat_names = []
        for layer in range(num_layers):
            for direction_i in range(d):
                in_size = input_size if layer == 0 else hidden_size * d
                suffix = f"_l{layer}" + ("_reverse" if direction_i else "")
                for base, shape, attr_in, is_bias in (
                    ("weight_ih", [self._n_gates * hidden_size, in_size],
                     weight_ih_attr, False),
                    ("weight_hh", [self._n_gates * hidden_size, hidden_size],
                     weight_hh_attr, False),
                    ("bias_ih", [self._n_gates * hidden_size],
                     bias_ih_attr, True),
                    ("bias_hh", [self._n_gates * hidden_size],
                     bias_hh_attr, True),
                ):
                    attr = ParamAttr._to_attr(attr_in)
                    if attr.initializer is None:
                        attr.initializer = I.Uniform(-std, std)
                    p = self.create_parameter(
                        shape=shape, attr=attr, is_bias=is_bias
                    )
                    name = base + suffix
                    self.add_parameter(name, p)
                    self._flat_names.append(name)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        d = 2 if self.bidirectional else 1
        batch = inputs.shape[0 if not self.time_major else 1]
        if initial_states is None:
            h0 = F.zeros(
                [self.num_layers * d, batch, self.hidden_size], inputs.dtype
            )
            initial_states = (
                (h0, F.zeros_like(h0)) if self._n_states == 2 else (h0,)
            )
        elif not isinstance(initial_states, (tuple, list)):
            initial_states = (initial_states,)

        weights = [getattr(self, n) for n in self._flat_names]
        res = F.rnn(
            inputs, list(initial_states), weights, self._mode,
            self.num_layers, self.time_major, self.dropout,
            self.bidirectional, self.training,
        )
        out = res[0]
        if self._n_states == 2:
            return out, (res[1], res[2])
        return out, res[1]


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kw):
        self._mode = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        self._n_gates = 1
        self._n_states = 1
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kw)


class LSTM(_RNNBase):
    _mode = "LSTM"
    _n_gates = 4
    _n_states = 2


class GRU(_RNNBase):
    _mode = "GRU"
    _n_gates = 3
    _n_states = 1

    def __init__(self, *args, **kw):
        self._n_gates = 3
        self._n_states = 1
        super().__init__(*args, **kw)
